"""Fused ingest chain: tokenize → encode → index slot-write (ISSUE 16).

PR 15's Device Observatory verdicted the embed ingest path HOST-BOUND at
0.33 MFU: the device idled while the host tokenized, padded, round-
tripped embeddings to numpy and issued one micro slot-write per row.
This module is the fix — ROADMAP item 2's dispatch-chain rebuild:

* **one jitted chain per shape bucket**: encoder forward → (already
  L2-normalized) embeddings → scatter slot-write into the KNN shard's
  HBM buffers, with the index triple DONATED so the write is in-place
  and no intermediate device→host round trip exists between encode and
  insert;
* **tokenize-ahead host stage**: a producer thread tokenizes, pads and
  (optionally) stages the NEXT batch's token arrays on device while the
  previous batch's chain is executing — double-buffered H2D, bounded by
  ``PATHWAY_INGEST_DEPTH`` staged batches so host and device stay one
  batch apart instead of strictly alternating;
* **device-plane records** at the new ``ingest.fused`` site: padded and
  effective FLOPs (real tokens over bucket tokens) so ``--profile``
  shows the verdict flip from host-bound to compute/bandwidth-bound and
  the MFU gauge reports honest utilization.

Padding discipline: the encoder's pow2-batch × multiple-of-32-seq
buckets bound the shape set; padded rows carry slot index == capacity,
which the scatter drops (``mode="drop"``) — no masking pass, no second
dispatch. The chain stores the encoder's L2-normalized embeddings
directly, which is exactly what the COS-metric shard would have
computed on its own write path.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Iterable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from pathway_tpu.internals import device as _devsup
from pathway_tpu.internals.device import (
    PLANE as _DEVICE,
    device_site,
    ingest_bucket,
    nbytes_of,
)
from pathway_tpu.internals.faults import fault_point
from pathway_tpu.models.encoder import (
    SentenceEncoder,
    forward_cost_model,
    pad_batch,
)
from pathway_tpu.ops.knn import KnnShard, Metric

device_site(
    "ingest.fused",
    cost_model=forward_cost_model,
    dtypes=("uint16", "int32", "float32", "bool"),
    where="pathway_tpu/ops/ingest.py:IngestPipeline._dispatch",
    donates=("vectors", "valid", "sq_norms"),
    description="fused tokenize->encode->scatter-write chain "
                "(index triple donated, in-place in HBM)",
)


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, "") or default)
        return v if v > 0 else default
    except ValueError:
        return default


def _env_on(name: str, default: bool = True) -> bool:
    raw = str(os.environ.get(name, "1" if default else "0")).strip().lower()
    return raw not in ("0", "false", "no")


# args 4..6 of the fused chain are the index buffer triple — donated so
# the slot-write is in-place in HBM. Module-level so the Device Doctor's
# donation audit checks the SAME argnums the pipeline jits with.
FUSED_DONATE_ARGNUMS = (4, 5, 6)


def make_fused(model):
    """The un-jitted fused chain body: encoder forward → scatter
    slot-write of the (already L2-normalized) embeddings into the index
    triple. Module-level so the Device Doctor (analysis/device_plan.py)
    lowers the SAME code object the pipeline dispatches — the anti-drift
    contract; ``IngestPipeline`` jits exactly this with
    ``donate_argnums=FUSED_DONATE_ARGNUMS``."""

    def fused(params, ids, lengths, slots, vectors, valid, sq_norms):
        mask = (
            jnp.arange(ids.shape[1], dtype=jnp.int32)[None, :]
            < lengths[:, None]
        ).astype(jnp.int32)
        emb = model.apply({"params": params}, ids.astype(jnp.int32), mask)
        # padded rows carry slot == capacity: out of bounds, dropped
        # by the scatter — no separate masking pass
        vectors = vectors.at[slots].set(emb, mode="drop")
        valid = valid.at[slots].set(
            jnp.ones(slots.shape, bool), mode="drop"
        )
        sq_norms = sq_norms.at[slots].set(
            jnp.sum(emb * emb, axis=-1), mode="drop"
        )
        return emb, vectors, valid, sq_norms

    return fused


class IngestPipeline:
    """Pipelined embed→index ingest over one encoder + one KNN shard.

    ``ingest(keys, texts)`` runs one batch through the fused chain;
    ``run(batches)`` drives the tokenize-ahead loop over an iterable of
    ``(keys, texts)`` batches. Not thread-safe itself (one producer, one
    dispatcher); concurrent *queries* against the shard remain safe —
    the chain holds the shard's writer lock across slot assignment and
    launch, same discipline as ``KnnShard.add``.
    """

    site = "ingest.fused"

    def __init__(
        self,
        encoder: SentenceEncoder,
        index: KnnShard,
        *,
        depth: int | None = None,
        stage_h2d: bool | None = None,
    ):
        if index.dimension != encoder.embed_dim:
            raise ValueError(
                f"index dimension {index.dimension} != encoder embed dim "
                f"{encoder.embed_dim}"
            )
        if index.metric not in (Metric.COS, Metric.DOT):
            # the chain stores L2-normalized embeddings; an L2SQ index
            # would need raw norms the encoder already collapsed to 1
            raise ValueError(
                "fused ingest supports cos/dot shards (normalized "
                f"embeddings), not {index.metric}"
            )
        self.encoder = encoder
        self.index = index
        self.depth = (
            depth if depth is not None
            else _env_int("PATHWAY_INGEST_DEPTH", 2)
        )
        self.stage_h2d = (
            stage_h2d if stage_h2d is not None
            else _env_on("PATHWAY_INGEST_STAGE_H2D", True)
        )
        self._seen_buckets: set = set()
        # running totals for MFU/bucket-fill accounting (bench + smoke):
        # real tokens are what the corpus contained, padded tokens are
        # what the device executed
        self.rows_ingested = 0
        self.real_tokens = 0
        self.padded_tokens = 0
        # donate the index triple: the slot-write is in-place in HBM —
        # the whole point of fusing encode and insert into one chain
        self._fused = jax.jit(
            make_fused(encoder.model), donate_argnums=FUSED_DONATE_ARGNUMS
        )

    # -- host stage --------------------------------------------------------
    def _stage(self, keys: Sequence[Any], texts: Sequence[str]):
        """Tokenize + pad one batch and (optionally) start its H2D copy.
        Runs on the producer thread in ``run`` — batch N+1 is staged
        while batch N's fused chain occupies the device."""
        enc = self.encoder
        ids, mask = enc.tokenizer(list(texts))
        ids_p, mask_p, n = pad_batch(
            ids, mask, enc.config.max_len, enc.batch_size
        )
        lengths = mask_p.sum(axis=1, dtype=np.int32)
        if enc.config.vocab_size <= 65536:
            ids_p = ids_p.astype(np.uint16)  # compact H2D wire format
        eff_tokens = float(np.sum(lengths[:n], dtype=np.int64))
        ids_dev: Any = ids_p
        lengths_dev: Any = lengths
        # injectable H2D staging failure (ISSUE 17): fires per staged
        # batch; run()'s producer supervision classifies and retries it
        fault_point("device.h2d", site=self.site)
        if self.stage_h2d:
            # start the copies now (async): the device pulls the next
            # batch's tokens while it still computes the previous one
            ids_dev = jax.device_put(ids_p)
            lengths_dev = jax.device_put(lengths)
        return (list(keys), ids_dev, lengths_dev, n, eff_tokens)

    # -- device stage ------------------------------------------------------
    def _dispatch(self, staged) -> Any:
        keys, ids_dev, lengths_dev, n, eff_tokens = staged
        index = self.index
        nb, Lb = ids_dev.shape
        self.rows_ingested += n
        self.real_tokens += int(eff_tokens)
        self.padded_tokens += nb * Lb
        dev = _DEVICE.begin(self.site) if _DEVICE.on else None
        try:
            with index.lock:
                slots = index._assign_slots(keys)
                cap = index.capacity
                # pad the slot vector to the batch bucket with the OOB
                # sentinel the scatter drops
                slots_full = np.full((nb,), cap, np.int32)
                slots_full[:n] = slots
                bucket = ingest_bucket(nb, Lb, cap, ids_dev.dtype.name)
                if bucket not in self._seen_buckets:
                    self._seen_buckets.add(bucket)
                    _DEVICE.note_recompile(self.site)
                # supervised (ISSUE 17): injected faults raise before
                # the launch (retry-safe); a real failure that consumed
                # the donated index triple classifies permanent
                emb, index.vectors, index.valid, index.sq_norms = (
                    _devsup.supervised_dispatch(
                        self.site,
                        lambda: self._fused(
                            self.encoder.params,
                            jnp.asarray(ids_dev),
                            jnp.asarray(lengths_dev),
                            jnp.asarray(slots_full),
                            index.vectors, index.valid, index.sq_norms,
                        ),
                    )
                )
                out_vectors = index.vectors
        except BaseException:
            _DEVICE.end(dev, None, block=False)
            raise
        if dev is not None:
            cfg = self.encoder.config
            d = index.dimension
            # forward dominates; the scatter write adds the sq-norm
            # reduction + row traffic (same model as KnnShard.add)
            flops, acc = forward_cost_model(cfg, nb, Lb)
            flops += 4.0 * nb * d
            acc += 8.0 * nb * d + 8.0 * nb
            # end() blocks OUTSIDE the lock (update-while-serving)
            _DEVICE.end(
                dev, (emb, out_vectors),
                flops=flops, bytes_accessed=acc,
                transfer_bytes=nbytes_of(ids_dev, lengths_dev) + 4 * nb,
                effective_share=eff_tokens / float(nb * Lb),
            )
        return emb[:n]

    # -- public API --------------------------------------------------------
    def ingest(self, keys: Sequence[Any], texts: Sequence[str]) -> Any:
        """One batch through the fused chain: tokenize (host), then
        encode + slot-write as a single jitted dispatch. Returns the
        (async, device-resident) embeddings of the real rows."""
        if not keys:
            return jnp.zeros((0, self.encoder.embed_dim), jnp.float32)
        return self._dispatch(self._stage(keys, texts))

    def run(self, batches: Iterable[tuple[Sequence[Any], Sequence[str]]],
            *, block: bool = True) -> int:
        """Drive the pipelined loop: a tokenize-ahead producer thread
        stages up to ``depth`` batches while the caller's thread issues
        the fused dispatches. Returns the number of rows ingested."""
        staged_q: queue.Queue = queue.Queue(maxsize=self.depth)
        err: list[BaseException] = []

        def producer():
            # SupervisorPolicy semantics (io/_connector.py) for the
            # tokenize-ahead stage: a transient hiccup (tokenizer I/O,
            # H2D copy) restarts the producer on the SAME batch with
            # bounded backoff instead of killing the whole pipelined
            # run; pulling from the batches iterator itself cannot be
            # retried (a raised generator is dead), so those failures
            # stay permanent
            import time as _t

            from pathway_tpu.parallel import protocol as _proto
            from pathway_tpu.udfs.retries import is_retryable

            it = iter(batches)
            retries = _devsup.dispatch_retries()
            try:
                while True:
                    try:
                        keys, texts = next(it)
                    except StopIteration:
                        break
                    attempt = 0
                    while True:
                        try:
                            staged = self._stage(keys, texts)
                            break
                        except BaseException as e:
                            kind = (
                                "transient"
                                if isinstance(e, Exception)
                                and is_retryable(e)
                                else "permanent"
                            )
                            verdict = _proto.device_dispatch_decide(
                                kind, attempt, retries
                            )
                            if verdict[0] != "retry":
                                raise
                            attempt = verdict[1]
                            stats = _DEVICE.stats
                            if stats is not None:
                                stats.on_device_dispatch_retry(self.site)
                            _t.sleep(min(2.0, 0.05 * (2 ** (attempt - 1))))
                    staged_q.put(staged)
            except BaseException as e:  # surface on the consumer side
                err.append(e)
            finally:
                staged_q.put(None)

        t = threading.Thread(
            target=producer, name="ingest-tokenize-ahead", daemon=True
        )
        t.start()
        rows = 0
        while True:
            staged = staged_q.get()
            if staged is None:
                break
            self._dispatch(staged)
            rows += staged[3]
        t.join()
        if err:
            raise err[0]
        if block:
            jax.block_until_ready(self.index.vectors)
        return rows
