"""Pallas TPU kernel: fused KNN scoring — matmul + running top-k.

Single pass over the database shard in VMEM-sized blocks: each grid step
computes a [Q, BLOCK] score tile on the MXU and folds it into a running
[Q, K] top-k held in VMEM scratch, so the full [Q, capacity] score matrix
never exists in HBM. This is the TPU replacement for the reference's
batched `index.dot(query)` + k_smallest loop
(/root/reference/src/external_integration/brute_force_knn_integration.rs:150-214),
which bounds memory by query-batching instead; we bound it by db-blocking,
which keeps query batches intact for the MXU.

Top-k inside the kernel is K-step selection (max + mask-out), K static and
small; `jax.lax.top_k` does not lower inside Pallas TPU kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pathway_tpu.internals.device import (
    PLANE as _DEVICE,
    device_site,
    pallas_bucket,
)

NEG_INF = float("-inf")


def _knn_kernel(q_ref, db_ref, mask_ref, out_v_ref, out_i_ref, sv_ref, si_ref,
                *, k: int, block: int):
    j = pl.program_id(0)
    nb = pl.num_programs(0)

    @pl.when(j == 0)
    def _init():
        sv_ref[:] = jnp.full(sv_ref.shape, NEG_INF, jnp.float32)
        si_ref[:] = jnp.zeros(si_ref.shape, jnp.int32)

    scores = jnp.dot(
        q_ref[:], db_ref[:].T,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ) + mask_ref[:]                                       # [Q, B]
    q = scores.shape[0]
    base = j * block
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (q, block), 1) + base

    cand_v = jnp.concatenate([sv_ref[:], scores], axis=1)  # [Q, K+B]
    cand_i = jnp.concatenate([si_ref[:], col_ids], axis=1)
    width = k + block
    iota = jax.lax.broadcasted_iota(jnp.int32, (q, width), 1)

    new_v = []
    new_i = []
    for _ in range(k):
        m = jnp.max(cand_v, axis=1)                        # [Q]
        am = jnp.argmax(cand_v, axis=1)                    # [Q]
        hit = iota == am[:, None]
        sel_i = jnp.sum(jnp.where(hit, cand_i, 0), axis=1)
        new_v.append(m)
        new_i.append(sel_i)
        cand_v = jnp.where(hit, NEG_INF, cand_v)
    sv_ref[:] = jnp.stack(new_v, axis=1)
    si_ref[:] = jnp.stack(new_i, axis=1)

    @pl.when(j == nb - 1)
    def _flush():
        out_v_ref[:] = sv_ref[:]
        out_i_ref[:] = si_ref[:]


def pallas_knn_cost(
    q: int, cap: int, d: int, k: int, block: int
) -> tuple[float, float]:
    """Analytical ``(flops, hbm_bytes_accessed)`` of the fused kernel —
    the device plane's cost model for this dispatch site. FLOPs: the
    per-block score matmul (2·q·block·d MACs per grid step = 2·q·cap·d
    total) plus K selection sweeps over the [q, k+block] candidate tile
    (~3 ops per candidate per step). Bytes: the database streams from
    HBM once, the query tile re-reads per grid step (its BlockSpec maps
    every step to the same [q, d] tile), and the running top-k lives in
    VMEM scratch — only the final [q, k] pair lands back in HBM."""
    nb = max(1, cap // block)
    flops = 2.0 * q * cap * d + 3.0 * k * q * (k + block) * nb
    bytes_accessed = (
        4.0 * cap * d          # database blocks, streamed once
        + 4.0 * q * d * nb     # query tile, re-fetched per grid step
        + 4.0 * cap            # additive validity mask (f32)
        + 8.0 * q * k          # (values, indices) result
    )
    return flops, bytes_accessed


device_site(
    "pallas.topk",
    cost_model=pallas_knn_cost,
    dtypes=("float32", "int32"),
    where="pathway_tpu/ops/pallas_knn.py:pallas_topk_scores",
    description="fused Pallas matmul + running top-k over VMEM blocks",
)

# seen compiled-shape buckets (ISSUE 20): every static-arg/shape combo of
# the pallas_call is one executable; a fresh key ticks
# device_site_recompiles_total so the retrace audit pins honest counters
_SEEN_BUCKETS: set = set()


def pallas_topk_scores(
    queries: jax.Array,    # [Q, D] f32
    database: jax.Array,   # [cap, D] f32
    add_mask: jax.Array,   # [cap] f32 additive (0 valid, -inf invalid)
    *,
    k: int,
    block: int = 1024,
    interpret: bool = False,
):
    """Fused scored top-k: returns (values [Q, k], indices [Q, k]).

    Host wrapper over the jitted kernel so the device plane (ISSUE 15)
    can record a timed dispatch per call — one attribute check when
    tracing is off."""
    q, d = queries.shape
    bucket = pallas_bucket(q, database.shape[0], d, k, block, interpret)
    if bucket not in _SEEN_BUCKETS:
        _SEEN_BUCKETS.add(bucket)
        _DEVICE.note_recompile("pallas.topk")
    if not _DEVICE.on:
        return _pallas_topk_scores_jit(
            queries, database, add_mask, k=k, block=block,
            interpret=interpret,
        )
    dev = _DEVICE.begin("pallas.topk")
    try:
        out = _pallas_topk_scores_jit(
            queries, database, add_mask, k=k, block=block,
            interpret=interpret,
        )
    except BaseException:
        _DEVICE.end(dev, None, block=False)
        raise
    flops, acc = pallas_knn_cost(q, database.shape[0], d, k, block)
    _DEVICE.end(dev, out, flops=flops, bytes_accessed=acc)
    return out


@functools.partial(
    jax.jit, static_argnames=("k", "block", "interpret")
)
def _pallas_topk_scores_jit(
    queries: jax.Array,    # [Q, D] f32
    database: jax.Array,   # [cap, D] f32
    add_mask: jax.Array,   # [cap] f32 additive (0 valid, -inf invalid)
    *,
    k: int,
    block: int = 1024,
    interpret: bool = False,
):
    q, d = queries.shape
    cap = database.shape[0]
    assert cap % block == 0, "capacity must be a multiple of block"
    nb = cap // block

    kernel = functools.partial(_knn_kernel, k=k, block=block)
    out_v, out_i = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((q, d), lambda j: (0, 0)),
            pl.BlockSpec((block, d), lambda j: (j, 0)),
            pl.BlockSpec((1, block), lambda j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((q, k), lambda j: (0, 0)),
            pl.BlockSpec((q, k), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((q, k), jnp.float32),
            jax.ShapeDtypeStruct((q, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((q, k), jnp.float32),
            pltpu.VMEM((q, k), jnp.int32),
        ],
        interpret=interpret,
    )(queries, database, add_mask[None, :])
    return out_v, out_i
