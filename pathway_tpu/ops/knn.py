"""HBM-resident brute-force KNN shard.

TPU-native re-design of the reference's BruteForceKNNIndex
(/root/reference/src/external_integration/brute_force_knn_integration.rs:22-237):
the reference keeps a row-major Array2<f64> on the host, grows/shrinks it
geometrically and scores queries with ndarray dot on CPU. Here the vector
store lives in device HBM as a padded f32[capacity, d] buffer with a
validity mask; capacity doubles on growth (powers of two only, so XLA sees
a small, stable set of shapes — no recompilation storms); deletes are O(1)
slot-free-list operations; scoring is a fused matmul + top-k on the MXU
(pathway_tpu.ops.topk) with queries padded to power-of-two batch sizes.
"""

from __future__ import annotations

import enum
import functools
import threading
from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from pathway_tpu.internals import device as _devsup
from pathway_tpu.internals.device import (
    PLANE as _DEVICE,
    device_site,
    knn_search_bucket,
    knn_write_bucket,
    nbytes_of,
    pow2_capacity,
)
from pathway_tpu.ops.topk import chunked_topk_scores, topk_scan_cost

_MIN_CAPACITY = 128


class Metric(enum.Enum):
    COS = "cos"
    L2SQ = "l2sq"
    DOT = "dot"


# shared-bucket alias (ISSUE 20): the capacity schedule jit sees and the
# shape set the Device Doctor enumerates are the SAME function — pinned
# by tests so they cannot drift
_next_pow2 = pow2_capacity


def write_cost_model(nrows: int, d: int) -> tuple[float, float]:
    """Analytical ``(flops, bytes_accessed)`` of one slot-write scatter:
    the optional normalize + sq-norm reduction over the written rows,
    touching the rows + norms in HBM. Shared by the ``knn.write`` /
    ``knn.sharded_write`` dispatch records and the Device Doctor's
    per-dispatch copy-cost blame (ISSUE 20)."""
    return 4.0 * nrows * d, 8.0 * nrows * d + 8.0 * nrows


device_site(
    "knn.write",
    cost_model=write_cost_model,
    dtypes=("float32", "bool", "int32"),
    where="pathway_tpu/ops/knn.py:KnnShard.add",
    donates=("vectors", "valid", "sq_norms"),
    description="donated in-place slot-write into the HBM buffer triple",
)

device_site(
    "knn.search",
    cost_model=topk_scan_cost,
    dtypes=("float32", "bool", "int32"),
    where="pathway_tpu/ops/knn.py:KnnShard.search",
    description="fused matmul + top-k scan over the padded vector store",
)


@functools.lru_cache(maxsize=None)
def _search_fn(k: int, metric: str, chunk: int, precision: str):
    @jax.jit
    def search(queries, vectors, valid, sq_norms):
        queries = queries.astype(jnp.float32)
        if metric == "cos":
            n = jnp.linalg.norm(queries, axis=-1, keepdims=True)
            queries = queries / jnp.maximum(n, 1e-30)
        sq = sq_norms if metric == "l2sq" else None
        return chunked_topk_scores(
            queries, vectors, valid, k,
            chunk=chunk, sq_norms=sq,
            metric="l2sq" if metric == "l2sq" else "dot",
            precision=precision,
        )

    return search


@functools.partial(
    jax.jit, static_argnames=("normalize",), donate_argnums=(0, 1, 2)
)
def _write_slots(vectors, valid, sq_norms, slots, new_vecs, new_valid, *,
                 normalize: bool = False):
    new_vecs = new_vecs.astype(jnp.float32)
    if normalize:
        n = jnp.linalg.norm(new_vecs, axis=-1, keepdims=True)
        new_vecs = new_vecs / jnp.maximum(n, 1e-30)
    vectors = vectors.at[slots].set(new_vecs)
    valid = valid.at[slots].set(new_valid)
    sq_norms = sq_norms.at[slots].set(jnp.sum(new_vecs * new_vecs, axis=-1))
    return vectors, valid, sq_norms


class KnnShard:
    """One device shard of a brute-force index: add/remove/search.

    Host side owns the key↔slot mapping (the reference's KeyToU64IdMapper,
    external_integration/mod.rs); the device side only sees dense slots.
    """

    def __init__(
        self,
        dimension: int,
        metric: Metric | str = Metric.COS,
        *,
        chunk: int | None = None,  # None = auto-scale to the scores budget
        precision: str = "highest",
        capacity: int = _MIN_CAPACITY,
        device: Any | None = None,
    ):
        self.dimension = int(dimension)
        self.metric = Metric(metric)
        self.chunk = chunk
        self.precision = precision
        self.device = device
        # pre-size to the expected corpus size to avoid growth reshapes
        # (each distinct capacity is a fresh XLA executable)
        self.capacity = _next_pow2(capacity)
        self.key_to_slot: dict[Any, int] = {}
        self.slot_to_key: dict[int, Any] = {}
        # insertion-sequence mint for the deterministic tie-break: equal
        # scores order by when the key was (last) inserted, so results
        # never depend on slot layout — the contract that makes sharded
        # and single-chip indexes bit-identical (tests/test_sharded_parity)
        self.key_seq: dict[Any, int] = {}
        self._next_seq = 0
        self.free_slots: list[int] = list(range(self.capacity - 1, -1, -1))
        self.vectors = jnp.zeros((self.capacity, self.dimension), jnp.float32)
        self.valid = jnp.zeros((self.capacity,), bool)
        self.sq_norms = jnp.zeros((self.capacity,), jnp.float32)
        # serializes writers against query launches (update-while-serving):
        # _write_slots DONATES the current buffers, so a reader must read
        # the array triple and enqueue its executable before the next
        # update invalidates those handles. Writers hold this lock; query
        # paths hold it across read+launch (the launch is asynchronous, so
        # the critical section is microseconds).
        self.lock = threading.Lock()
        # slot-reuse guard for in-flight queries: a hit resolved AFTER its
        # dispatch must not map a slot freed (and possibly reused) in
        # between to the new key. remove() stamps freed slots with a
        # monotonically increasing epoch; readers capture the epoch at
        # dispatch and drop hits whose slot was freed later.
        self.remove_epoch = 0
        self.slot_freed_epoch = np.full(self.capacity, -1, np.int64)
        # device fault domain (ISSUE 17): per-epoch dirty tracking for
        # delta snapshots plus the committed segment chain this index
        # extends. _dirty/_dirty_removed are insertion-ordered key sets
        # (dicts), mutually exclusive per key — a re-added key leaves
        # the removed set, a removed key leaves the dirty set.
        from pathway_tpu.persistence import index_snapshot as _isnap

        self.snapshot_name = _isnap.next_index_name("knn")
        self._dirty: dict[Any, None] = {}
        self._dirty_removed: dict[Any, None] = {}
        self._segments: list[dict] = []
        self._retired: list[list[str]] = []
        # seen compiled-shape buckets (ISSUE 20): a write/search key not
        # in this set is — by jit's cache discipline — a fresh XLA
        # compilation, ticked on device_site_recompiles_total so the
        # retrace audit's predictions pin against honest counters
        self._seen_buckets: set = set()

    # device sites reachable through this index as an external-index
    # adapter (the Device Doctor's plan-reachability hook, ISSUE 20)
    device_sites = ("knn.write", "knn.search")

    def __len__(self) -> int:
        return len(self.key_to_slot)

    # -- mutation ---------------------------------------------------------
    def _grow_to(self, n: int) -> None:
        new_cap = _next_pow2(n)
        if new_cap <= self.capacity:
            return
        pad = new_cap - self.capacity
        # HBM growth is the OOM site: allocate the doubled buffers into
        # locals and commit only on success, so a refused growth leaves
        # the index serving at its committed capacity (the failing add
        # aborts; the serving breaker browns out via notify_oom)
        try:
            from pathway_tpu.internals.faults import fault_point

            fault_point("device.oom", site="knn.grow")
            vectors = jnp.concatenate(
                [self.vectors, jnp.zeros((pad, self.dimension), jnp.float32)]
            )
            valid = jnp.concatenate([self.valid, jnp.zeros((pad,), bool)])
            sq_norms = jnp.concatenate(
                [self.sq_norms, jnp.zeros((pad,), jnp.float32)]
            )
        except BaseException as exc:
            if _devsup.classify_device_error(exc) == "oom":
                _devsup.notify_oom("knn.grow")
                raise _devsup.DeviceOom(
                    f"knn index refused growth to {new_cap} slots "
                    f"(HBM exhausted): {exc!r}"
                ) from exc
            raise
        self.vectors, self.valid, self.sq_norms = vectors, valid, sq_norms
        self.free_slots = (
            list(range(new_cap - 1, self.capacity - 1, -1)) + self.free_slots
        )
        self.slot_freed_epoch = np.concatenate(
            [self.slot_freed_epoch, np.full(pad, -1, np.int64)]
        )
        self.capacity = new_cap

    def _prepare(self, vecs):
        """Shape/dtype check; keeps device arrays on device. Normalization
        for cos happens on device inside the jitted write/search fns."""
        if not isinstance(vecs, jax.Array):
            vecs = np.asarray(vecs, dtype=np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None, :]
        if vecs.shape[-1] != self.dimension:
            raise ValueError(
                f"vector dimension {vecs.shape[-1]} != index dimension {self.dimension}"
            )
        return vecs

    def _assign_slots(self, keys: Sequence[Any]) -> np.ndarray:
        """Map keys to dense slots (upsert semantics), growing first.
        Must be called under ``self.lock`` — shared by ``add`` and the
        fused ingest chain (ops/ingest.py), which maps keys to slots
        host-side while the encoder forward + slot-write run as one
        jitted dispatch."""
        self._grow_to(len(self.key_to_slot) + len(keys))
        slots = []
        for key in keys:
            slot = self.key_to_slot.get(key)
            if slot is None:
                slot = self.free_slots.pop()
                self.key_to_slot[key] = slot
                self.slot_to_key[slot] = key
                self.key_seq[key] = self._next_seq
                self._next_seq += 1
            slots.append(slot)
            # every upserted key is dirty for the next snapshot cut;
            # this also captures the fused ingest chain, which assigns
            # slots here before the encoder+write dispatch
            self._dirty[key] = None
            self._dirty_removed.pop(key, None)
        return np.asarray(slots, dtype=np.int32)

    def add(self, keys: Sequence[Any], vecs) -> None:
        """Upsert vectors; accepts numpy or device-resident jax arrays (the
        latter avoids a host round-trip when chaining from a jitted encoder).
        Safe to call while queries are in flight (update-while-serving)."""
        vecs = self._prepare(vecs)
        if len(keys) != vecs.shape[0]:
            raise ValueError("keys/vectors length mismatch")
        with self.lock:
            slots = self._assign_slots(keys)
            slots_arr = jnp.asarray(slots)
            bucket = knn_write_bucket(len(slots), self.capacity)
            if bucket not in self._seen_buckets:
                self._seen_buckets.add(bucket)
                _DEVICE.note_recompile("knn.write")
            dev = _DEVICE.begin("knn.write") if _DEVICE.on else None

            def _launch():
                return _write_slots(
                    self.vectors, self.valid, self.sq_norms,
                    slots_arr, jnp.asarray(vecs),
                    jnp.ones((len(slots),), bool),
                    normalize=self.metric is Metric.COS,
                )

            try:
                # supervised (ISSUE 17): injected faults raise before the
                # launch so retry is safe; a real failure that consumed
                # the donated buffers classifies permanent and aborts
                self.vectors, self.valid, self.sq_norms = (
                    _devsup.supervised_dispatch("knn.write", _launch)
                )
            except BaseException:
                _DEVICE.end(dev, None, block=False)
                raise
            out_vectors = self.vectors
        if dev is not None:
            # end() OUTSIDE the lock, like the search side — its
            # block_until_ready must not serialize update-while-serving
            # (a racing writer may have re-donated out_vectors by now;
            # blocking on an invalidated array is absorbed by end()).
            # Scatter writes: touch the written rows + norms; FLOPs are
            # the optional normalize + sq-norm reduction.
            flops, acc = write_cost_model(len(slots), self.dimension)
            _DEVICE.end(
                dev, out_vectors,
                flops=flops,
                bytes_accessed=acc,
                transfer_bytes=nbytes_of(vecs) + 4 * len(slots),
            )

    def remove(self, keys: Sequence[Any]) -> None:
        with self.lock:
            slots = []
            for key in keys:
                slot = self.key_to_slot.pop(key, None)
                if slot is None:
                    continue
                del self.slot_to_key[slot]
                self.key_seq.pop(key, None)
                self.free_slots.append(slot)
                slots.append(slot)
                self._dirty_removed[key] = None
                self._dirty.pop(key, None)
            if not slots:
                return
            self.remove_epoch += 1
            self.slot_freed_epoch[np.asarray(slots)] = self.remove_epoch
            slots_arr = jnp.asarray(np.asarray(slots, dtype=np.int32))
            self.vectors, self.valid, self.sq_norms = _write_slots(
                self.vectors, self.valid, self.sq_norms,
                slots_arr,
                jnp.zeros((len(slots), self.dimension), jnp.float32),
                jnp.zeros((len(slots),), bool),
            )

    # -- snapshot / restore (ISSUE 17) ------------------------------------
    def snapshot_state(self, *, extra=None) -> dict:
        """Node state for the current persistence cut: a delta-segment
        manifest when a cut context is armed (persistence/index_snapshot),
        an inline full state otherwise. ``extra`` is an optional
        key->payload mapping that rides the segments (adapter metadata)."""
        from pathway_tpu.persistence import index_snapshot as _isnap

        return _isnap.snapshot_index(self, extra=extra)

    def load_state(self, state: dict) -> dict:
        """Rebuild HBM buffers + host maps from a committed snapshot
        (manifest chain or inline state) instead of re-embedding; returns
        the folded per-key extra payloads."""
        from pathway_tpu.persistence import index_snapshot as _isnap

        return _isnap.restore_index(self, state)

    def _load_entries(self, entries: list) -> None:
        """Replace the whole corpus with ``[(key, seq, vector), ...]``.
        Caller holds ``self.lock``. Vectors are as-committed (already
        normalized for cos), so the rewrite uses ``normalize=False`` —
        scores and the ``key_seq`` tie-break come back bit-identical."""
        n = len(entries)
        self.capacity = _next_pow2(max(n, _MIN_CAPACITY))
        self.key_to_slot = {}
        self.slot_to_key = {}
        self.key_seq = {}
        # the old corpus (and its mint position) is gone; restore_index
        # re-seats _next_seq from the snapshot so post-restore inserts
        # mint the same sequences as the uninterrupted run
        self._next_seq = 0
        self.free_slots = list(range(self.capacity - 1, -1, -1))
        self.remove_epoch = 0
        self.slot_freed_epoch = np.full(self.capacity, -1, np.int64)
        self.vectors = jnp.zeros((self.capacity, self.dimension), jnp.float32)
        self.valid = jnp.zeros((self.capacity,), bool)
        self.sq_norms = jnp.zeros((self.capacity,), jnp.float32)
        if not n:
            return
        slots = np.empty((n,), np.int32)
        rows = np.empty((n, self.dimension), np.float32)
        for i, (key, seq, row) in enumerate(entries):
            slot = self.free_slots.pop()
            self.key_to_slot[key] = slot
            self.slot_to_key[slot] = key
            self.key_seq[key] = int(seq)
            slots[i] = slot
            rows[i] = row
        self.vectors, self.valid, self.sq_norms = _write_slots(
            self.vectors, self.valid, self.sq_norms,
            jnp.asarray(slots), jnp.asarray(rows),
            jnp.ones((n,), bool), normalize=False,
        )

    # -- search -----------------------------------------------------------
    def search(self, queries, k: int) -> list[list[tuple[Any, float]]]:
        """Return per-query [(key, score)] sorted by descending score.

        Scores: cos/dot similarity, or negated squared L2 distance.
        Queries are padded to a power-of-two batch so the jitted kernel
        sees a bounded shape set.
        """
        queries = self._prepare(queries)
        n = queries.shape[0]
        if n == 0 or not self.key_to_slot:
            return [[] for _ in range(n)]
        # shared bucket key (ISSUE 20): pow2 query padding and the k
        # clamp (top_k per scored block cannot exceed the block width)
        # come from the SAME function the retrace audit enumerates with
        bucket = knn_search_bucket(n, self.capacity, k, self.chunk)
        padded_n, _, k_eff = bucket
        if bucket not in self._seen_buckets:
            self._seen_buckets.add(bucket)
            _DEVICE.note_recompile("knn.search")
        if padded_n != n:
            pad = [(0, padded_n - n), (0, 0)]
            queries = (
                jnp.pad(queries, pad)
                if isinstance(queries, jax.Array)
                else np.pad(queries, pad)
            )
        fn = _search_fn(k_eff, self.metric.value, self.chunk, self.precision)
        # device plane (ISSUE 15): one timed dispatch record per scan —
        # wall span, block_until_ready-bounded device time, the scan's
        # cost model and host->device transfer bytes. One attribute
        # check when the plane is off; end() blocks OUTSIDE the lock so
        # attribution never serializes writers.
        dev = _DEVICE.begin("knn.search") if _DEVICE.on else None
        try:
            with self.lock:  # read+launch before the next donating update
                vals, idx = _devsup.supervised_dispatch(
                    "knn.search",
                    lambda: fn(
                        jnp.asarray(queries), self.vectors, self.valid,
                        self.sq_norms,
                    ),
                )
                epoch = self.remove_epoch
                live_rows = len(self.key_to_slot)
        except BaseException:
            # close the record on the failure path too (the gateway
            # site's rule): an abandoned record leaks queue depth
            _DEVICE.end(dev, None, block=False)
            raise
        if dev is not None:
            flops, acc = topk_scan_cost(
                padded_n, self.capacity, self.dimension, k_eff
            )
            # effective FLOPs (ISSUE 16): only real queries against live
            # rows count as useful work — query padding and the empty
            # tail of the pow2 capacity buffer are visible padding waste
            flops_eff, _ = topk_scan_cost(
                n, live_rows, self.dimension, k_eff
            )
            _DEVICE.end(
                dev, (vals, idx), flops=flops,
                flops_effective=flops_eff, bytes_accessed=acc,
                transfer_bytes=nbytes_of(queries, vals, idx),
            )
        vals = np.asarray(vals)[:n]
        idx = np.asarray(idx)[:n]
        out: list[list[tuple[Any, float]]] = []
        for qi in range(n):
            hits = []
            for vv, slot in zip(vals[qi], idx[qi]):
                if not np.isfinite(vv):
                    continue
                slot = int(slot)
                # slot freed after our dispatch (possibly reused by a new
                # key): this hit's key mapping is gone — drop it, matching
                # removed-row semantics
                if self.slot_freed_epoch[slot] > epoch:
                    continue
                key = self.slot_to_key.get(slot)
                if key is None:
                    continue
                hits.append((key, float(vv)))
            # deterministic tie-break over ALL k_eff candidates before
            # truncating: equal scores order by insertion sequence, so
            # the result never depends on slot layout (which a sharded
            # index lays out differently) — see ShardedKnnIndex.search
            hits.sort(key=lambda t: (-t[1], self.key_seq.get(t[0], 0)))
            out.append(hits[:k])
        return out
