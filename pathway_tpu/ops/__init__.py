"""pathway_tpu.ops — TPU dense kernels for the framework's hot paths.

The reference implements its retrieval hot loop in native Rust
(/root/reference/src/external_integration/brute_force_knn_integration.rs:22-237
— ndarray matmul + k_smallest on CPU). Here the same role is played by
XLA/Pallas kernels: padded HBM-resident vector shards, fused
matmul + top-k scoring on the MXU, and mergeable partial top-k results for
mesh-sharded indexes (SURVEY §5 long-context mapping).
"""

from pathway_tpu.ops.topk import masked_topk, merge_topk, tree_merge_topk
from pathway_tpu.ops.knn import KnnShard, Metric
from pathway_tpu.ops.query_engine import MicroBatcher, QueryEngine

__all__ = [
    "KnnShard",
    "Metric",
    "MicroBatcher",
    "QueryEngine",
    "masked_topk",
    "merge_topk",
    "tree_merge_topk",
]


def __getattr__(name):
    # IngestPipeline pulls in the encoder stack (flax) — lazy so the
    # relational plane keeps importing pathway_tpu.ops for free
    if name == "IngestPipeline":
        from pathway_tpu.ops.ingest import IngestPipeline

        return IngestPipeline
    raise AttributeError(name)
