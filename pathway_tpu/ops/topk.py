"""Top-k primitives: masked, chunked and mergeable.

Scoring a query batch against a large vector shard must not materialize the
full [n_queries, capacity] score matrix in HBM; we score in chunks and merge
partial top-k results. The same merge is the tree-reduction step for global
top-k across mesh shards (each chip's partial top-k is exchanged and merged —
the retrieval analog of ring attention's partial-softmax merge; SURVEY §5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# plain float, like pallas_knn: a module-scope jnp.float32() would jit a
# convert_element_type at IMPORT time (slow, and it drags XLA compilation
# into processes that only need the relational plane — e.g. the ASan CI
# lane, where jaxlib's C++ exceptions abort under the preloaded runtime);
# jnp.where/jnp.full coerce it to the array dtype exactly the same way
NEG_INF = float("-inf")


def masked_topk(scores: jax.Array, valid: jax.Array, k: int):
    """Top-k of `scores` [..., n] where `valid` [..., n] (bool) gates entries.

    Returns (values [..., k], indices [..., k]); invalid entries score -inf,
    so callers must treat -inf results as missing.
    """
    scores = jnp.where(valid, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


def merge_topk(vals_a, idx_a, vals_b, idx_b, k: int):
    """Merge two partial top-k results (values desc) into one top-k.

    Index tensors may carry global ids (int32/int64); ties broken by source
    order (a first) which keeps the merge deterministic.
    """
    vals = jnp.concatenate([vals_a, vals_b], axis=-1)
    idx = jnp.concatenate([idx_a, idx_b], axis=-1)
    top_vals, pos = jax.lax.top_k(vals, k)
    top_idx = jnp.take_along_axis(idx, pos, axis=-1)
    return top_vals, top_idx


def tree_merge_topk(vals, idx, k: int, axis: str, axis_size: int):
    """Global top-k across a pow2 mesh axis by recursive doubling —
    the psum-style merge for the pod-sharded index (ISSUE 16, SURVEY
    §5): log2(n) ``ppermute`` exchange+merge rounds over ICI instead of
    one all_gather of every shard's partials. Each round ships 2·q·k
    values per link (vs (n-1)·q·k for the gather at the root), so the
    merge cost stays flat as the pod grows.

    Must run inside ``shard_map`` over ``axis``; vals/idx are one
    shard's partial top-k [q, k] (values desc). Ties at each merge are
    broken lower-rank-first (the XOR pairing keeps rank order inside
    every butterfly pair), matching the gather merge's shard-0-first
    order. Returns the REPLICATED global top-k — the butterfly is an
    all-reduce, every shard ends with the same answer.
    """
    me = jax.lax.axis_index(axis)
    step = 1
    while step < axis_size:
        perm = [(i, i ^ step) for i in range(axis_size)]
        other_vals = jax.lax.ppermute(vals, axis, perm)
        other_idx = jax.lax.ppermute(idx, axis, perm)
        # lower rank of the pair contributes first so top_k's stable
        # positional tie-break resolves by shard order, like the gather
        low = (me & step) == 0
        a_vals = jnp.where(low, vals, other_vals)
        a_idx = jnp.where(low, idx, other_idx)
        b_vals = jnp.where(low, other_vals, vals)
        b_idx = jnp.where(low, other_idx, idx)
        vals, idx = merge_topk(a_vals, a_idx, b_vals, b_idx, k)
        step *= 2
    return vals, idx


_SCORES_BUDGET_BYTES = 1 << 28  # 256 MB of f32 scores per block


def auto_chunk(cap: int, n_queries: int) -> int:
    """Largest pow2 block whose [q, chunk] f32 score matrix fits the budget.

    Small fixed chunks serialize the scan into latency-bound steps (a 1M-row
    index in 8192-row blocks is 128 sequential tiny matmuls ≈ 100+ ms); one
    block per ~256 MB keeps the MXU busy and the merge tree shallow.
    """
    rows = max(8192, _SCORES_BUDGET_BYTES // (4 * max(n_queries, 1)))
    b = 8192
    while b * 2 <= rows:
        b *= 2
    return min(b, cap)


def chunked_topk_scores(
    queries: jax.Array,   # [q, d] f32
    database: jax.Array,  # [cap, d] f32
    valid: jax.Array,     # [cap] bool
    k: int,
    *,
    chunk: int | None = None,
    sq_norms: jax.Array | None = None,  # [cap] f32, for l2 metric
    metric: str = "dot",
    precision: str = "highest",
):
    """Score queries against the database and return top-k per query.

    metric:
      - "dot": plain inner product (cos if inputs are pre-normalized)
      - "l2sq": negated squared L2 distance (so larger is better)

    precision: "highest" = exact f32 scores (reference parity — its brute
    force index is exact f64, brute_force_knn_integration.rs:150); "default"
    = backend-native fast path (bf16 MXU passes on TPU) for latency-bound
    serving where ~1e-3 score error is acceptable.

    The database is scanned in `chunk`-row blocks; per-block top-k results
    are merged, keeping peak memory at O(q * chunk) instead of O(q * cap).
    XLA fuses the matmul (MXU, bf16-friendly) with the masking per block.
    """
    q, d = queries.shape
    cap = database.shape[0]
    if chunk is None:
        chunk = auto_chunk(cap, q)
    if cap <= chunk:
        scores = _block_scores(queries, database, sq_norms, metric, precision)
        return masked_topk(scores, valid[None, :], k)

    n_blocks = cap // chunk
    assert cap % chunk == 0, "capacity must be a multiple of chunk"

    db_blocks = database.reshape(n_blocks, chunk, d)
    valid_blocks = valid.reshape(n_blocks, chunk)
    sq_blocks = (
        sq_norms.reshape(n_blocks, chunk) if sq_norms is not None else None
    )

    def body(carry, block):
        best_vals, best_idx = carry
        if sq_blocks is not None:
            db, vmask, sq, base = block
        else:
            db, vmask, base = block
            sq = None
        scores = _block_scores(queries, db, sq, metric, precision)
        vals, idx = masked_topk(scores, vmask[None, :], k)
        idx = idx.astype(jnp.int32) + base
        best_vals, best_idx = merge_topk(best_vals, best_idx, vals, idx, k)
        return (best_vals, best_idx), None

    init = (
        jnp.full((q, k), NEG_INF, dtype=jnp.float32),
        jnp.zeros((q, k), dtype=jnp.int32),
    )
    bases = (jnp.arange(n_blocks, dtype=jnp.int32) * chunk)
    xs = (
        (db_blocks, valid_blocks, sq_blocks, bases)
        if sq_blocks is not None
        else (db_blocks, valid_blocks, bases)
    )
    (vals, idx), _ = jax.lax.scan(body, init, xs)
    return vals, idx


def topk_scan_cost(
    q: int, cap: int, d: int, k: int
) -> tuple[float, float]:
    """Analytical ``(flops, hbm_bytes_accessed)`` of one chunked top-k
    scan — the device plane's fallback cost model when the compiled
    executable's own ``cost_analysis()`` is unavailable or too costly
    to obtain (re-lowering the 1M-row scan just for bookkeeping would
    compile a second executable; internals/device.py compiled_cost).

    FLOPs: the [q, cap] score matmul dominates (2·q·cap·d MACs); the
    per-block mask/compare/merge passes add ~3 ops per score. Bytes:
    one full database read (the scan streams every block from HBM
    exactly once), the query tile, validity mask + sq_norms, and the
    [q, k] result pair — per-block score tiles live in VMEM and never
    touch HBM, which is the point of the chunked design.

    This counts PADDED work: `q` is the pow2-padded query batch, `cap`
    the pow2 capacity including dead slots — what the hardware
    executed. For the effective (real-rows) number ISSUE 16's honest
    MFU reports, call it again with the real query count and live row
    count; the dispatch sites pass both to the device plane.
    """
    flops = 2.0 * q * cap * d + 3.0 * q * cap
    bytes_accessed = (
        4.0 * cap * d      # database blocks, streamed once
        + 4.0 * q * d      # query tile
        + cap              # validity mask (bool)
        + 4.0 * cap        # sq_norms (l2 metric; ~free for dot)
        + 8.0 * q * k      # merged (values, indices) result
    )
    return flops, bytes_accessed


def _block_scores(queries, db_block, sq_norms_block, metric, precision="highest"):
    scores = jnp.dot(
        queries, db_block.T,
        preferred_element_type=jnp.float32,
        precision=precision,
    )
    if metric == "l2sq":
        qn = jnp.sum(queries * queries, axis=-1, keepdims=True)
        scores = 2.0 * scores - qn - sq_norms_block[None, :]
    return scores
