"""Fused serving-path query engine: tokenize -> encode -> top-k in ONE
XLA executable with ONE packed result readback.

Latency budget (SURVEY §7 hard part 6): per-query cost is dominated by
dispatch + result readback, not FLOPs — so the whole path (encoder forward
+ fused matmul/top-k over the index shard) compiles into a single
executable, and scores+indices pack into one f32 buffer so the host pays
exactly one device-to-host transfer per query batch.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from pathway_tpu.ops.topk import chunked_topk_scores


class QueryEngine:
    """encode+search for a SentenceEncoder + KnnShard pair. The jitted
    executable is owned by the engine instance, so dropping the engine
    releases the model params and compiled closures."""

    def __init__(self, encoder, shard, *, k: int = 6):
        self.encoder = encoder
        self.shard = shard
        self.k = k
        model = encoder.model
        chunk = shard.chunk
        precision = shard.precision
        k_eff = min(k, shard.capacity, shard.chunk or 8192)
        from pathway_tpu.ops.knn import Metric

        # encoder outputs are L2-normalized, so cos == dot on the query
        # side; l2sq shards score with their cached squared norms
        metric = "l2sq" if shard.metric is Metric.L2SQ else "dot"
        use_sq = metric == "l2sq"

        @jax.jit
        def run(params, ids, mask, vectors, valid, sq_norms):
            emb = model.apply({"params": params}, ids, mask)  # [q,d] unit
            vals, idx = chunked_topk_scores(
                emb, vectors, valid, k_eff, chunk=chunk, metric=metric,
                sq_norms=sq_norms if use_sq else None,
                precision=precision,
            )
            # pack scores and indices into ONE buffer: a single readback
            return jnp.concatenate([vals, idx.astype(jnp.float32)], axis=1)

        self._fn = run

    def query(self, texts: Sequence[str]) -> list[list[tuple[Any, float]]]:
        texts = list(texts)
        if not texts or not self.shard.key_to_slot:
            return [[] for _ in texts]
        out: list[list[tuple[Any, float]]] = []
        cap = self.encoder.batch_size
        for start in range(0, len(texts), cap):
            out.extend(self._query_batch(texts[start : start + cap]))
        return out

    def _query_batch(self, texts: list[str]):
        from pathway_tpu.models.encoder import pad_batch

        ids, mask = self.encoder.tokenizer(texts)
        ids_p, mask_p, n = pad_batch(
            ids, mask, self.encoder.config.max_len, self.encoder.batch_size
        )
        # f32 packing is exact for slot ids < 2^24 (16.7M rows/shard);
        # larger shards must fall back to the two-buffer path
        if self.shard.capacity >= (1 << 24):
            raise ValueError(
                "QueryEngine packed readback supports shards < 16.7M rows"
            )
        k_eff = min(self.k, self.shard.capacity, self.shard.chunk or 8192)
        packed = self._fn(
            self.encoder.params,
            jnp.asarray(ids_p),
            jnp.asarray(mask_p),
            self.shard.vectors,
            self.shard.valid,
            self.shard.sq_norms,
        )
        packed = np.asarray(packed)[:n]  # the ONE readback
        vals = packed[:, :k_eff]
        idx = packed[:, k_eff:].astype(np.int64)
        out = []
        for qi in range(n):
            hits = []
            for vv, slot in zip(vals[qi], idx[qi]):
                if not np.isfinite(vv):
                    continue
                key = self.shard.slot_to_key.get(int(slot))
                if key is None:
                    continue
                hits.append((key, float(vv)))
                if len(hits) == self.k:
                    break
            out.append(hits)
        return out
