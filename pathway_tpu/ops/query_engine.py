"""Fused serving-path query engine: tokenize -> encode -> top-k in ONE
XLA executable with ONE packed result readback.

Latency budget (SURVEY §7 hard part 6): per-query cost is dominated by
dispatch + result readback, not FLOPs — so the whole path (encoder forward
+ fused matmul/top-k over the index shard) compiles into a single
executable, and scores+indices pack into one f32 buffer so the host pays
exactly one device-to-host transfer per query batch.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from pathway_tpu.ops.topk import chunked_topk_scores


class QueryEngine:
    """encode+search for a SentenceEncoder + KnnShard pair. The jitted
    executable is owned by the engine instance, so dropping the engine
    releases the model params and compiled closures."""

    def __init__(self, encoder, shard, *, k: int = 6):
        self.encoder = encoder
        self.shard = shard
        self.k = k
        model = encoder.model
        chunk = shard.chunk
        precision = shard.precision
        # the packed-buffer layout [vals | idx] is baked into the jitted
        # executable here; finish() must slice with THIS k_eff even if the
        # shard's capacity grows later
        k_eff = self.k_eff = min(k, shard.capacity, shard.chunk or 8192)
        from pathway_tpu.ops.knn import Metric

        # encoder outputs are L2-normalized, so cos == dot on the query
        # side; l2sq shards score with their cached squared norms
        metric = "l2sq" if shard.metric is Metric.L2SQ else "dot"
        use_sq = metric == "l2sq"

        import functools

        @functools.partial(jax.jit, static_argnames=("packed",))
        def run(params, ids, mask, vectors, valid, sq_norms, *, packed):
            emb = model.apply({"params": params}, ids, mask)  # [q,d] unit
            vals, idx = chunked_topk_scores(
                emb, vectors, valid, k_eff, chunk=chunk, metric=metric,
                sq_norms=sq_norms if use_sq else None,
                precision=precision,
            )
            if packed:
                # pack scores and indices into ONE f32 buffer: a single
                # readback (exact only for slot ids < 2^24)
                return jnp.concatenate(
                    [vals, idx.astype(jnp.float32)], axis=1
                )
            # two-buffer path for >=16.7M-row shards: i32 indices stay
            # exact; the host pays a second (concurrent) readback
            return vals, idx.astype(jnp.int32)

        self._fn = run

    def query(self, texts: Sequence[str]) -> list[list[tuple[Any, float]]]:
        texts = list(texts)
        if not texts or not self.shard.key_to_slot:
            return [[] for _ in texts]
        out: list[list[tuple[Any, float]]] = []
        cap = self.encoder.batch_size
        for start in range(0, len(texts), cap):
            out.extend(self._query_batch(texts[start : start + cap]))
        return out

    def dispatch(self, texts: list[str]):
        """Phase 1: tokenize + launch the fused executable. Returns an
        opaque (device_array, n) ticket without blocking — dispatch is
        asynchronous, so the caller can have several tickets in flight
        (the readbacks overlap on tunneled transports)."""
        from pathway_tpu.models.encoder import pad_batch

        ids, mask = self.encoder.tokenizer(texts)
        ids_p, mask_p, n = pad_batch(
            ids, mask, self.encoder.config.max_len, self.encoder.batch_size
        )
        with self.shard.lock:
            # read the array triple AND enqueue the executable before the
            # next index update donates (invalidates) these buffers —
            # update-while-serving safety; the launch is asynchronous so
            # this section is microseconds. The packed/two-buffer decision
            # and the remove-epoch are captured under the same lock so a
            # concurrent growth past 2^24 rows (or a slot-freeing remove)
            # cannot race this dispatch.
            # f32 packing is exact for slot ids < 2^24 (16.7M rows/shard);
            # larger shards take the two-buffer path (i32 indices, second
            # readback)
            packed_ok = self.shard.capacity < (1 << 24)
            result = self._fn(
                self.encoder.params,
                jnp.asarray(ids_p),
                jnp.asarray(mask_p),
                self.shard.vectors,
                self.shard.valid,
                self.shard.sq_norms,
                packed=packed_ok,
            )
            epoch = self.shard.remove_epoch
        return result, n, packed_ok, epoch

    def finish(self, ticket) -> list[list[tuple[Any, float]]]:
        """Phase 2: the device->host readback(s) + result shaping — one
        packed readback below 16.7M rows, two buffers above."""
        result, n, packed_ok, epoch = ticket
        k_eff = self.k_eff  # compiled-in layout, not current capacity
        if packed_ok:
            packed = np.asarray(result)[:n]  # the ONE readback
            vals = packed[:, :k_eff]
            idx = packed[:, k_eff:].astype(np.int64)
        else:
            vals_dev, idx_dev = result
            vals = np.asarray(vals_dev)[:n]
            idx = np.asarray(idx_dev)[:n].astype(np.int64)
        out = []
        for qi in range(n):
            hits = []
            for vv, slot in zip(vals[qi], idx[qi]):
                if not np.isfinite(vv):
                    continue
                slot = int(slot)
                # slot freed after our dispatch (possibly reused by a new
                # key): the mapping this score belongs to is gone — drop
                # the hit, matching removed-row semantics
                if self.shard.slot_freed_epoch[slot] > epoch:
                    continue
                key = self.shard.slot_to_key.get(slot)
                if key is None:
                    continue
                hits.append((key, float(vv)))
                if len(hits) == self.k:
                    break
            out.append(hits)
        return out

    def _query_batch(self, texts: list[str]):
        return self.finish(self.dispatch(texts))


class _Err:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


class MicroBatcher:
    """Concurrent serving front-end: collect in-flight queries for up to
    ``max_wait_ms`` (or ``max_batch`` queries), then ONE fused
    encode+search dispatch and ONE packed readback for the whole group.

    This is the serving-loop analog of the engine's as-of-time index
    batching (reference: src/engine/dataflow/operators/external_index.rs:
    112-155 — index and query streams are merged and batched by logical
    time); here the batch boundary is wall-clock micro-windows over
    concurrent HTTP clients instead of a logical timestamp.

    Two-stage pipeline: the collector thread tokenizes + dispatches
    (asynchronous, sub-ms), a pool of readback threads blocks on the
    device->host transfers — so on a tunneled transport several batches'
    readbacks ride the link concurrently and throughput is bounded by
    device work, not one round-trip per batch.
    """

    def __init__(
        self,
        engine: QueryEngine,
        *,
        max_wait_ms: float = 2.0,
        max_batch: int | None = None,
        readback_workers: int = 4,
    ):
        self.engine = engine
        # clamp to the encoder's padded batch capacity: _flush dispatches
        # one batch directly, bypassing query()'s cap-splitting
        self.max_batch = min(
            max_batch or engine.encoder.batch_size, engine.encoder.batch_size
        )
        self.max_wait = max_wait_ms / 1000.0
        self._q: "queue.Queue" = queue.Queue()
        self._tickets: "queue.Queue" = queue.Queue()
        self._closed = False
        self._collector = threading.Thread(target=self._collect, daemon=True)
        self._readers = [
            threading.Thread(target=self._readback, daemon=True)
            for _ in range(max(1, readback_workers))
        ]
        self._collector.start()
        for t in self._readers:
            t.start()

    # -- client API -------------------------------------------------------
    def query(self, text: str, timeout: float | None = 30.0):
        """Blocking single-query call, safe from many threads: the query
        rides the next micro-batch. Returns [(key, score), ...]."""
        if self._closed:
            raise RuntimeError("MicroBatcher is closed")
        slot: "queue.SimpleQueue" = queue.SimpleQueue()
        self._q.put((text, slot))
        res = slot.get(timeout=timeout)
        if isinstance(res, _Err):
            raise res.exc
        return res

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._collector.join(timeout=5)
        # fail any request that raced past the closed check after the
        # sentinel: an explicit error now beats an opaque timeout later
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                item[1].put(_Err(RuntimeError("MicroBatcher is closed")))
        for _ in self._readers:
            self._tickets.put(None)
        for t in self._readers:
            t.join(timeout=5)

    # -- pipeline stages --------------------------------------------------
    def _collect(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            batch = [item]
            deadline = time.monotonic() + self.max_wait
            while len(batch) < self.max_batch:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=rem)
                except queue.Empty:
                    break
                if nxt is None:
                    self._flush(batch)
                    return
                batch.append(nxt)
            self._flush(batch)

    def _flush(self, batch: list) -> None:
        texts = [t for t, _ in batch]
        slots = [s for _, s in batch]
        if not self.engine.shard.key_to_slot:
            for s in slots:
                s.put([])
            return
        try:
            ticket = self.engine.dispatch(texts)
        except Exception as exc:
            for s in slots:
                s.put(_Err(exc))
            return
        self._tickets.put((ticket, slots))

    def _readback(self) -> None:
        while True:
            got = self._tickets.get()
            if got is None:
                return
            ticket, slots = got
            try:
                results = self.engine.finish(ticket)
            except Exception as exc:
                for s in slots:
                    s.put(_Err(exc))
                continue
            for s, r in zip(slots, results):
                s.put(r)
