"""pathway CLI (reference: python/pathway/cli.py — `pathway spawn` :166,
`spawn-from-env` :284, `replay` :252).

`spawn` launches a pipeline program; --processes N sets PATHWAY_PROCESSES /
PATHWAY_PROCESS_ID per child, which on TPU maps to jax.distributed hosts
(SURVEY §2.9) rather than timely TCP workers."""

from __future__ import annotations

import argparse
import os
import runpy
import subprocess
import sys


def _spawn(args) -> int:
    env = dict(os.environ)
    env["PATHWAY_THREADS"] = str(args.threads)
    env["PATHWAY_PROCESSES"] = str(args.processes)
    env["PATHWAY_FIRST_PORT"] = str(args.first_port)
    program = args.program
    if args.processes > 1:
        procs = []
        for pid in range(args.processes):
            child_env = dict(env)
            child_env["PATHWAY_PROCESS_ID"] = str(pid)
            procs.append(
                subprocess.Popen(
                    [sys.executable, program, *args.arguments], env=child_env
                )
            )
        rc = 0
        for p in procs:
            rc = rc or p.wait()
        return rc
    env["PATHWAY_PROCESS_ID"] = "0"
    os.environ.update(env)
    sys.argv = [program, *args.arguments]
    runpy.run_path(program, run_name="__main__")
    return 0


def _replay(args) -> int:
    os.environ["PATHWAY_REPLAY_STORAGE"] = args.record_path
    os.environ["PATHWAY_SNAPSHOT_ACCESS"] = args.mode
    sys.argv = [args.program, *args.arguments]
    runpy.run_path(args.program, run_name="__main__")
    return 0


def _spawn_from_env(args) -> int:
    command = os.environ.get("PATHWAY_SPAWN_ARGS", "")
    if not command:
        print("PATHWAY_SPAWN_ARGS is not set", file=sys.stderr)
        return 1
    parts = command.split()
    return main(["spawn", *parts])


_CONNECTION_TEMPLATE = """\
source:
  docker_image: "{image}"
  config:
    # connector-specific configuration — run the connector's `spec`
    # action (or see its docs) for the full schema
# optional: remote execution through an HTTPS runner
# remote_runner:
#   url: https://runner.example.com
#   token: <bearer token>
"""


def _airbyte_create_source(args) -> int:
    """Scaffold a connection YAML (reference: python/pathway/cli.py:294
    `pathway airbyte create-source` over airbyte_serverless
    ConnectionFromFile.init_yaml_config)."""
    path = args.connection
    if not path.endswith((".yaml", ".yml")):
        path = path + ".yaml"
    if os.path.exists(path):
        print(f"{path} already exists; not overwriting", file=sys.stderr)
        return 1
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        f.write(_CONNECTION_TEMPLATE.format(image=args.image))
    print(
        f"Connection `{os.path.splitext(os.path.basename(path))[0]}` "
        f"with source `{args.image}` created successfully"
    )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="pathway")
    sub = parser.add_subparsers(dest="command", required=True)

    spawn = sub.add_parser("spawn", help="run a pathway program")
    spawn.add_argument("--threads", "-t", type=int, default=1)
    spawn.add_argument("--processes", "-n", type=int, default=1)
    spawn.add_argument("--first-port", type=int, default=10000)
    spawn.add_argument("--record", action="store_true")
    spawn.add_argument("--record-path", default="record")
    spawn.add_argument("program")
    spawn.add_argument("arguments", nargs=argparse.REMAINDER)
    spawn.set_defaults(fn=_spawn)

    replay = sub.add_parser("replay", help="replay a recorded stream")
    replay.add_argument("--record-path", required=True)
    replay.add_argument(
        "--mode", choices=["replay", "speedrun"], default="replay"
    )
    replay.add_argument("program")
    replay.add_argument("arguments", nargs=argparse.REMAINDER)
    replay.set_defaults(fn=_replay)

    sfe = sub.add_parser("spawn-from-env", help="spawn using PATHWAY_SPAWN_ARGS")
    sfe.set_defaults(fn=_spawn_from_env)

    airbyte = sub.add_parser("airbyte", help="airbyte connection tooling")
    airbyte_sub = airbyte.add_subparsers(dest="airbyte_command", required=True)
    create = airbyte_sub.add_parser(
        "create-source", help="scaffold a connection YAML"
    )
    create.add_argument("connection", help="connection file path (or name)")
    create.add_argument(
        "--image",
        default="airbyte/source-faker:0.1.4",
        help="any public Airbyte source docker image",
    )
    create.set_defaults(fn=_airbyte_create_source)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
