"""Native C++ runtime components — build + ctypes bindings.

The reference's native core is Rust (tantivy BM25, usearch HNSW,
brute-force ndarray KNN — src/external_integration/). Here the host-side
index runtimes are C++ (native/bm25.cpp, native/hnsw.cpp) compiled once
into a shared library and bound via ctypes; the dense brute-force path
stays on TPU (pathway_tpu.ops). Pure-Python fallbacks keep everything
working when no toolchain is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading
from typing import Any, Sequence

import numpy as np

_REPO_NATIVE = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
# override point for instrumented builds (scripts/sanitize_native.sh
# compiles the extensions with ASAN/TSAN into a scratch dir)
_BUILD_DIR = os.environ.get("PATHWAY_NATIVE_BUILD_DIR") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "_build"
)
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False


def _newest_mtime(src_dir: str, src: str) -> float:
    """Staleness input for an extension build: the source file plus any
    shared headers it includes (pw_blake2b.h) — a header-only change must
    trigger a rebuild too."""
    newest = os.path.getmtime(src)
    hdr = os.path.join(src_dir, "pw_blake2b.h")
    if os.path.exists(hdr):
        newest = max(newest, os.path.getmtime(hdr))
    return newest


def _sources() -> list[str]:
    src_dir = _REPO_NATIVE
    if not os.path.isdir(src_dir):
        # installed layout: sources shipped next to this package
        src_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src")
    return [
        os.path.join(src_dir, "bm25.cpp"),
        os.path.join(src_dir, "hnsw.cpp"),
    ]


def _build() -> str | None:
    sources = _sources()
    if not all(os.path.exists(s) for s in sources):
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    out = os.path.join(_BUILD_DIR, "libpathway_native.so")
    stamp = os.path.join(_BUILD_DIR, "build.stamp")
    newest_src = max(os.path.getmtime(s) for s in sources)
    if os.path.exists(out) and os.path.exists(stamp):
        if os.path.getmtime(stamp) >= newest_src:
            return out
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-o", out, *sources,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
    except Exception:
        return None
    with open(stamp, "w") as f:
        f.write("ok")
    return out


def get_lib() -> ctypes.CDLL | None:
    """Compile-on-first-use; None when no toolchain (callers fall back)."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        path = _build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.bm25_new.restype = ctypes.c_void_p
        lib.bm25_new.argtypes = [ctypes.c_double, ctypes.c_double]
        lib.bm25_free.argtypes = [ctypes.c_void_p]
        lib.bm25_add.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p]
        lib.bm25_remove.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bm25_len.restype = ctypes.c_int64
        lib.bm25_len.argtypes = [ctypes.c_void_p]
        lib.bm25_search.restype = ctypes.c_int64
        lib.bm25_search.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
        ]
        lib.hnsw_new.restype = ctypes.c_void_p
        lib.hnsw_new.argtypes = [ctypes.c_int32] * 5
        lib.hnsw_free.argtypes = [ctypes.c_void_p]
        lib.hnsw_add.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_float)
        ]
        if hasattr(lib, "hnsw_add_batch"):
            lib.hnsw_add_batch.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64,
            ]
        lib.hnsw_remove.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.hnsw_len.restype = ctypes.c_int64
        lib.hnsw_len.argtypes = [ctypes.c_void_p]
        lib.hnsw_search.restype = ctypes.c_int64
        lib.hnsw_search.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
        ]
        _LIB = lib
        return _LIB


def available() -> bool:
    return get_lib() is not None


_FASTPATH = None
_FASTPATH_TRIED = False


def get_fastpath():
    """CPython extension with the engine's per-row hot loops
    (native/fastpath.c); None when no toolchain — callers fall back to the
    pure-Python implementations."""
    global _FASTPATH, _FASTPATH_TRIED
    with _LOCK:
        if _FASTPATH_TRIED:
            return _FASTPATH
        _FASTPATH_TRIED = True
        src_dir = _REPO_NATIVE if os.path.isdir(_REPO_NATIVE) else os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "src"
        )
        src = os.path.join(src_dir, "fastpath.c")
        if not os.path.exists(src):
            return None
        os.makedirs(_BUILD_DIR, exist_ok=True)
        suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
        out = os.path.join(_BUILD_DIR, "fastpath" + suffix)
        if not (
            os.path.exists(out)
            and os.path.getmtime(out) >= _newest_mtime(src_dir, src)
        ):
            include = sysconfig.get_paths()["include"]
            cmd = [
                "gcc", "-O3", "-shared", "-fPIC",
                f"-I{include}", "-o", out, src,
            ]
            try:
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        import importlib.util

        spec = importlib.util.spec_from_file_location("fastpath", out)
        mod = importlib.util.module_from_spec(spec)
        try:
            spec.loader.exec_module(mod)
        except Exception:
            return None
        _FASTPATH = mod
        return _FASTPATH


_PWEXEC = None
_PWEXEC_TRIED = False


def get_pwexec():
    """CPython extension with the sharded native group-by executor
    (native/exec.cpp) — the multi-worker relational engine core. None when
    no toolchain; callers fall back to the Python operator path."""
    global _PWEXEC, _PWEXEC_TRIED
    with _LOCK:
        if _PWEXEC_TRIED:
            return _PWEXEC
        _PWEXEC_TRIED = True
        src_dir = _REPO_NATIVE if os.path.isdir(_REPO_NATIVE) else os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "src"
        )
        src = os.path.join(src_dir, "exec.cpp")
        if not os.path.exists(src):
            return None
        os.makedirs(_BUILD_DIR, exist_ok=True)
        suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
        out = os.path.join(_BUILD_DIR, "pwexec" + suffix)
        if not (
            os.path.exists(out)
            and os.path.getmtime(out) >= _newest_mtime(src_dir, src)
        ):
            include = sysconfig.get_paths()["include"]
            cmd = [
                "g++", "-O3", "-std=c++20", "-shared", "-fPIC", "-pthread",
                f"-I{include}", "-o", out, src,
            ]
            try:
                subprocess.run(cmd, check=True, capture_output=True, timeout=180)
            except Exception as exc:
                # a failed build silently drops the whole native executor
                # (group-by/join fall back to pure Python) — make the
                # degradation visible. g++ 10 works (exec.cpp gates its
                # C++20 library uses); g++ < 10 rejects -std=c++20
                import logging

                stderr = getattr(exc, "stderr", None) or b""
                logging.getLogger(__name__).warning(
                    "native executor build failed (%s): %s",
                    exc,
                    stderr[-500:],
                )
                return None
        import importlib.util

        spec = importlib.util.spec_from_file_location("pwexec", out)
        mod = importlib.util.module_from_spec(spec)
        try:
            spec.loader.exec_module(mod)
        except Exception:
            return None
        _PWEXEC = mod
        return _PWEXEC


class NativeBm25:
    """ctypes wrapper over the C++ BM25 index. int64 handles are minted
    per key by the caller (KeyToU64IdMapper pattern, reference
    external_integration/mod.rs)."""

    def __init__(self, k1: float = 1.2, b: float = 0.75):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.bm25_new(k1, b)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.bm25_free(self._h)
            self._h = None

    def add(self, key: int, text: str) -> None:
        self._lib.bm25_add(self._h, key, text.encode("utf-8", "replace"))

    def remove(self, key: int) -> None:
        self._lib.bm25_remove(self._h, key)

    def __len__(self) -> int:
        return self._lib.bm25_len(self._h)

    def search(self, query: str, k: int) -> list[tuple[int, float]]:
        n = max(k, 0)
        keys = (ctypes.c_int64 * n)()
        scores = (ctypes.c_double * n)()
        got = self._lib.bm25_search(
            self._h, query.encode("utf-8", "replace"), n, keys, scores
        )
        return [(keys[i], scores[i]) for i in range(got)]


_METRICS = {"cos": 0, "l2sq": 1, "ip": 2, "dot": 2}


class NativeHnsw:
    """ctypes wrapper over the C++ HNSW ANN index (usearch equivalent)."""

    def __init__(self, dim: int, metric: str = "cos", *, M: int = 16,
                 ef_build: int = 128, ef_search: int = 64):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.dim = dim
        self._h = lib.hnsw_new(dim, _METRICS[metric], M, ef_build, ef_search)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.hnsw_free(self._h)
            self._h = None

    def add(self, key: int, vec) -> None:
        v = np.ascontiguousarray(vec, dtype=np.float32)
        self._lib.hnsw_add(
            self._h, key, v.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        )

    def add_batch(self, keys, vecs) -> None:
        """Insert n rows in ONE library crossing (ISSUE 16: the
        one-doc-per-dispatch ann build was dominated by per-row call
        overhead). Falls back to per-row adds on a stale library built
        before the batch entry point existed."""
        ks = np.ascontiguousarray(keys, dtype=np.int64)
        vs = np.ascontiguousarray(vecs, dtype=np.float32)
        if vs.ndim != 2 or vs.shape[0] != ks.shape[0]:
            raise ValueError("keys/vectors shape mismatch")
        if not hasattr(self._lib, "hnsw_add_batch"):
            for k, v in zip(ks, vs):
                self.add(int(k), v)
            return
        self._lib.hnsw_add_batch(
            self._h,
            ks.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ks.shape[0],
        )

    def remove(self, key: int) -> None:
        self._lib.hnsw_remove(self._h, key)

    def __len__(self) -> int:
        return self._lib.hnsw_len(self._h)

    def search(self, vec, k: int) -> list[tuple[int, float]]:
        v = np.ascontiguousarray(vec, dtype=np.float32)
        n = max(k, 0)
        keys = (ctypes.c_int64 * n)()
        scores = (ctypes.c_double * n)()
        got = self._lib.hnsw_search(
            self._h, v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, keys, scores,
        )
        return [(keys[i], scores[i]) for i in range(got)]
