"""``.dt`` / ``.str`` / ``.num`` expression namespaces.

Reference: python/pathway/internals/expressions/{date_time,string,numerical}.py.
Each method builds a MethodCallExpression whose function the engine maps over
row batches (numeric ones vectorise through numpy in the batch evaluator).
"""

from __future__ import annotations

import datetime
import math

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ColumnExpression,
    MethodCallExpression,
    smart_coerce,
)


class _Namespace:
    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    def _method(self, name, fun, return_type, *extra):
        return MethodCallExpression(name, (self._expr, *extra), fun, return_type)


class StringNamespace(_Namespace):
    def lower(self):
        return self._method("lower", lambda s: s.lower(), dt.STR)

    def upper(self):
        return self._method("upper", lambda s: s.upper(), dt.STR)

    def reversed(self):
        return self._method("reversed", lambda s: s[::-1], dt.STR)

    def strip(self, chars=None):
        return self._method("strip", lambda s, c: s.strip(c), dt.STR, smart_coerce(chars))

    def rstrip(self, chars=None):
        return self._method("rstrip", lambda s, c: s.rstrip(c), dt.STR, smart_coerce(chars))

    def lstrip(self, chars=None):
        return self._method("lstrip", lambda s, c: s.lstrip(c), dt.STR, smart_coerce(chars))

    def len(self):
        return self._method("len", lambda s: len(s), dt.INT)

    def count(self, sub, start=None, end=None):
        return self._method(
            "count",
            lambda s, su, st, e: s.count(su, st, e),
            dt.INT,
            smart_coerce(sub),
            smart_coerce(start),
            smart_coerce(end),
        )

    def find(self, sub, start=None, end=None):
        return self._method(
            "find",
            lambda s, su, st, e: s.find(su, st, e),
            dt.INT,
            smart_coerce(sub),
            smart_coerce(start),
            smart_coerce(end),
        )

    def rfind(self, sub, start=None, end=None):
        return self._method(
            "rfind",
            lambda s, su, st, e: s.rfind(su, st, e),
            dt.INT,
            smart_coerce(sub),
            smart_coerce(start),
            smart_coerce(end),
        )

    def startswith(self, prefix):
        return self._method(
            "startswith", lambda s, p: s.startswith(p), dt.BOOL, smart_coerce(prefix)
        )

    def endswith(self, suffix):
        return self._method(
            "endswith", lambda s, p: s.endswith(p), dt.BOOL, smart_coerce(suffix)
        )

    def swapcase(self):
        return self._method("swapcase", lambda s: s.swapcase(), dt.STR)

    def title(self):
        return self._method("title", lambda s: s.title(), dt.STR)

    def replace(self, old, new, count=-1):
        return self._method(
            "replace",
            lambda s, o, n, c: s.replace(o, n, c),
            dt.STR,
            smart_coerce(old),
            smart_coerce(new),
            smart_coerce(count),
        )

    def split(self, sep=None, maxsplit=-1):
        return self._method(
            "split",
            lambda s, se, m: tuple(s.split(se, m)),
            dt.List(dt.STR),
            smart_coerce(sep),
            smart_coerce(maxsplit),
        )

    def slice(self, start, end):
        return self._method(
            "slice",
            lambda s, a, b: s[a:b],
            dt.STR,
            smart_coerce(start),
            smart_coerce(end),
        )

    def parse_int(self, optional=False):
        fun = (lambda s: _safe(int, s)) if optional else (lambda s: int(s))
        return self._method("parse_int", fun, dt.Optional(dt.INT) if optional else dt.INT)

    def parse_float(self, optional=False):
        fun = (lambda s: _safe(float, s)) if optional else (lambda s: float(s))
        return self._method(
            "parse_float", fun, dt.Optional(dt.FLOAT) if optional else dt.FLOAT
        )

    def parse_bool(self, true_values=("on", "true", "yes", "1"), false_values=("off", "false", "no", "0"), optional=False):
        def fun(s):
            low = s.lower()
            if low in true_values:
                return True
            if low in false_values:
                return False
            if optional:
                return None
            raise ValueError(f"cannot parse {s!r} as bool")

        return self._method(
            "parse_bool", fun, dt.Optional(dt.BOOL) if optional else dt.BOOL
        )


def _safe(fun, *args):
    try:
        return fun(*args)
    except (ValueError, TypeError):
        return None


class NumericalNamespace(_Namespace):
    def abs(self):
        return self._method("abs", abs, self._expr._dtype)

    def round(self, decimals=0):
        return self._method(
            "round", lambda x, d: round(x, d), self._expr._dtype, smart_coerce(decimals)
        )

    def fill_na(self, default_value):
        def fun(x, d):
            if x is None:
                return d
            if isinstance(x, float) and math.isnan(x):
                return d
            return x

        # propagate_none=False: this method's JOB is receiving the None
        # (reference: expressions/numerical.py fill_na replaces
        # None/NaN with the default)
        return MethodCallExpression(
            "fill_na",
            (self._expr, smart_coerce(default_value)),
            fun,
            dt.unoptionalize(self._expr._dtype),
            propagate_none=False,
        )


_EPOCH_NAIVE = datetime.datetime(1970, 1, 1)
_EPOCH_UTC = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)


def _strptime(s, fmt):
    return datetime.datetime.strptime(s, fmt)


class DateTimeNamespace(_Namespace):
    def nanosecond(self):
        return self._method("nanosecond", lambda d: d.microsecond * 1000, dt.INT)

    def microsecond(self):
        return self._method("microsecond", lambda d: d.microsecond, dt.INT)

    def millisecond(self):
        return self._method("millisecond", lambda d: d.microsecond // 1000, dt.INT)

    def second(self):
        return self._method("second", lambda d: d.second, dt.INT)

    def minute(self):
        return self._method("minute", lambda d: d.minute, dt.INT)

    def hour(self):
        return self._method("hour", lambda d: d.hour, dt.INT)

    def day(self):
        return self._method("day", lambda d: d.day, dt.INT)

    def month(self):
        return self._method("month", lambda d: d.month, dt.INT)

    def year(self):
        return self._method("year", lambda d: d.year, dt.INT)

    def timestamp(self, unit=None):
        """Epoch offset (reference: expressions/date_time.py:384 —
        float for explicit units; exact int nanoseconds for unit=None,
        the deprecated legacy default). Computed from exact integer
        nanoseconds either way: total_seconds() alone loses precision
        beyond ~104 days."""
        if unit is not None and unit not in ("ns", "us", "ms", "s"):
            raise ValueError(
                f"unit has to be one of 's', 'ms', 'us', 'ns' but is {unit!r}"
            )
        div = {None: 1, "ns": 1, "us": 10**3, "ms": 10**6, "s": 10**9}[unit]

        def fun(d):
            epoch = _EPOCH_UTC if d.tzinfo is not None else _EPOCH_NAIVE
            td = d - epoch
            ns = (
                (td.days * 86400 + td.seconds) * 10**9
                + td.microseconds * 10**3
            )
            if unit is None:
                return ns
            return ns / div

        return self._method(
            "timestamp", fun, dt.INT if unit is None else dt.FLOAT
        )

    def strftime(self, fmt):
        return self._method(
            "strftime", lambda d, f: d.strftime(f), dt.STR, smart_coerce(fmt)
        )

    def strptime(self, fmt, contains_timezone=False):
        return self._method(
            "strptime",
            lambda s, f: _strptime(s, f),
            dt.DATE_TIME_UTC if contains_timezone else dt.DATE_TIME_NAIVE,
            smart_coerce(fmt),
        )

    def to_utc(self, from_timezone):
        import zoneinfo

        def fun(d, tz):
            return d.replace(tzinfo=zoneinfo.ZoneInfo(tz)).astimezone(
                datetime.timezone.utc
            )

        return self._method("to_utc", fun, dt.DATE_TIME_UTC, smart_coerce(from_timezone))

    def to_naive_in_timezone(self, timezone):
        import zoneinfo

        def fun(d, tz):
            return d.astimezone(zoneinfo.ZoneInfo(tz)).replace(tzinfo=None)

        return self._method(
            "to_naive_in_timezone", fun, dt.DATE_TIME_NAIVE, smart_coerce(timezone)
        )

    def round(self, duration):
        def fun(d, dur):
            epoch = _EPOCH_UTC if d.tzinfo is not None else _EPOCH_NAIVE
            total = (d - epoch).total_seconds()
            step = dur.total_seconds()
            return epoch + datetime.timedelta(seconds=round(total / step) * step)

        return self._method("round", fun, self._expr._dtype, smart_coerce(duration))

    def floor(self, duration):
        def fun(d, dur):
            epoch = _EPOCH_UTC if d.tzinfo is not None else _EPOCH_NAIVE
            total = (d - epoch).total_seconds()
            step = dur.total_seconds()
            return epoch + datetime.timedelta(seconds=math.floor(total / step) * step)

        return self._method("floor", fun, self._expr._dtype, smart_coerce(duration))

    def nanoseconds(self):
        return self._method(
            "nanoseconds", lambda td: int(td.total_seconds() * 1e9), dt.INT
        )

    def microseconds(self):
        return self._method(
            "microseconds", lambda td: int(td.total_seconds() * 1e6), dt.INT
        )

    def milliseconds(self):
        return self._method(
            "milliseconds", lambda td: int(td.total_seconds() * 1e3), dt.INT
        )

    def seconds(self):
        return self._method("seconds", lambda td: int(td.total_seconds()), dt.INT)

    def minutes(self):
        return self._method("minutes", lambda td: int(td.total_seconds() // 60), dt.INT)

    def hours(self):
        return self._method("hours", lambda td: int(td.total_seconds() // 3600), dt.INT)

    def days(self):
        return self._method("days", lambda td: td.days, dt.INT)

    def weeks(self):
        return self._method("weeks", lambda td: td.days // 7, dt.INT)

    def from_timestamp(self, unit="s"):
        mult = {"s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}[unit]

        def fun(x):
            return _EPOCH_NAIVE + datetime.timedelta(seconds=x * mult)

        return self._method("from_timestamp", fun, dt.DATE_TIME_NAIVE)

    def utc_from_timestamp(self, unit="s"):
        mult = {"s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}[unit]

        def fun(x):
            return _EPOCH_UTC + datetime.timedelta(seconds=x * mult)

        return self._method("utc_from_timestamp", fun, dt.DATE_TIME_UTC)

    def weekday(self):
        return self._method("weekday", lambda d: d.weekday(), dt.INT)
