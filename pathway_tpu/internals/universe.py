"""Universes: key-set identity of tables (reference:
python/pathway/internals/universe.py + universe_solver.py).

A Universe represents "the set of row ids" of a family of tables.  The
solver tracks equality (union-find) and subset promises so the DSL can
validate operations like update_cells / with_universe_of / concat at
declaration time.
"""

from __future__ import annotations

import itertools

_counter = itertools.count()


class Universe:
    __slots__ = ("uid",)

    def __init__(self):
        self.uid = next(_counter)

    def __repr__(self):
        return f"Universe#{self.uid}"

    def subset(self) -> "Universe":
        u = Universe()
        SOLVER.register_subset(u, self)
        return u

    def superset(self) -> "Universe":
        u = Universe()
        SOLVER.register_subset(self, u)
        return u


class UniverseSolver:
    def __init__(self):
        self.parent: dict[int, int] = {}
        self.subsets: set[tuple[int, int]] = set()  # (sub, sup) roots
        self.disjoint: set[frozenset[int]] = set()  # promised-disjoint roots

    def _find(self, uid: int) -> int:
        root = uid
        while self.parent.get(root, root) != root:
            root = self.parent[root]
        while self.parent.get(uid, uid) != uid:
            self.parent[uid], uid = root, self.parent[uid]
        return root

    def register_as_equal(self, a: Universe, b: Universe) -> None:
        ra, rb = self._find(a.uid), self._find(b.uid)
        if ra != rb:
            self.parent[ra] = rb

    def register_subset(self, sub: Universe, sup: Universe) -> None:
        self.subsets.add((self._find(sub.uid), self._find(sup.uid)))

    def query_are_equal(self, a: Universe, b: Universe) -> bool:
        return self._find(a.uid) == self._find(b.uid)

    def register_disjoint(self, a: Universe, b: Universe) -> None:
        ra, rb = self._find(a.uid), self._find(b.uid)
        if ra == rb:
            raise ValueError(
                "cannot promise disjointness of equal universes"
            )
        self.disjoint.add(frozenset((ra, rb)))

    def _supersets(self, uid: int) -> set[int]:
        """All registered-superset roots reachable from uid (incl. itself)."""
        root = self._find(uid)
        seen = {root}
        frontier = [root]
        while frontier:
            cur = frontier.pop()
            for a, b in self.subsets:
                if self._find(a) == cur:
                    nb = self._find(b)
                    if nb not in seen:
                        seen.add(nb)
                        frontier.append(nb)
        return seen

    def query_are_disjoint(self, a: Universe, b: Universe) -> bool:
        """True iff some registered-disjoint pair covers (a, b) — i.e. a
        and b are (subsets of) universes promised pairwise disjoint.
        Disjointness is additionally VERIFIED at runtime: concat raises on
        actual id collisions, so this query is advisory (declaration-time
        diagnostics), not the safety mechanism."""
        ra, rb = self._find(a.uid), self._find(b.uid)
        if ra == rb:
            return False
        sups_a = self._supersets(ra)
        sups_b = self._supersets(rb)
        for pair in self.disjoint:
            pa, pb = tuple(pair)
            if (pa in sups_a and pb in sups_b) or (
                pb in sups_a and pa in sups_b
            ):
                return True
        return False

    def query_is_subset(self, sub: Universe, sup: Universe) -> bool:
        rs, rp = self._find(sub.uid), self._find(sup.uid)
        if rs == rp:
            return True
        # BFS over registered subset edges
        seen = {rs}
        frontier = [rs]
        while frontier:
            cur = frontier.pop()
            for a, b in self.subsets:
                if self._find(a) == cur:
                    nb = self._find(b)
                    if nb == rp:
                        return True
                    if nb not in seen:
                        seen.add(nb)
                        frontier.append(nb)
        return False

    def get_intersection(self, *universes: Universe) -> Universe:
        u = Universe()
        for x in universes:
            self.register_subset(u, x)
        return u

    def get_union(self, *universes: Universe) -> Universe:
        u = Universe()
        for x in universes:
            self.register_subset(x, u)
        return u

    def get_difference(self, a: Universe, b: Universe) -> Universe:
        u = Universe()
        self.register_subset(u, a)
        return u


SOLVER = UniverseSolver()
