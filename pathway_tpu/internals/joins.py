"""Join machinery (reference: python/pathway/internals/joins.py, 1,422 LoC;
engine side: Graph::join_tables graph.rs + JoinType graph.rs:480).

``t1.join(t2, t1.a == t2.b).select(...)`` — the JoinResult carries the two
sides and on-conditions; select lowers to the engine JoinNode (incremental,
all four join types) followed by a rowwise projection over the concatenated
left+right row.
"""

from __future__ import annotations

import enum
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import schema_from_types
from pathway_tpu.internals.universe import Universe


class JoinMode(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


class JoinResult:
    def __init__(self, left, right, on, *, id=None, how="inner", exact_match=False):
        self._left = left
        self._right = right
        self._how = how
        self._id = id
        # exact_match promises every left row matches exactly once
        # (reference: joins.py exact_match — keeps right columns
        # non-optional); types are dynamic here so it is metadata only
        self._exact_match = exact_match
        self._on: list[tuple[ColumnExpression, ColumnExpression]] = []
        for cond in on:
            cond = thisclass.desugar(cond, left_table=left, right_table=right)
            if (
                not isinstance(cond, expr_mod.ColumnBinaryOpExpression)
                or cond._symbol != "=="
            ):
                raise ValueError("join conditions must be of the form left.col == right.col")
            import builtins

            lhs, rhs = cond._left, cond._right
            l_tabs = {builtins.id(r.table) for r in lhs._deps}
            if builtins.id(right) in l_tabs:
                lhs, rhs = rhs, lhs
            for r in lhs._deps:
                if r.table is not left:
                    raise ValueError("left side of join condition must use the left table")
            for r in rhs._deps:
                if r.table is not right:
                    raise ValueError("right side of join condition must use the right table")
            self._on.append((lhs, rhs))

    # -- deferred resolution ----------------------------------------------
    def _resolve_deferred(self, name: str) -> ColumnExpression:
        if name == "id":
            return _join_id_ref(self)
        in_left = name in self._left._column_names
        in_right = name in self._right._column_names
        if in_left and in_right:
            # unified if it is an on-pair of same-named columns
            for lhs, rhs in self._on:
                if (
                    isinstance(lhs, ColumnReference)
                    and isinstance(rhs, ColumnReference)
                    and lhs.name == name
                    and rhs.name == name
                ):
                    if self._how in ("right", "outer"):
                        # padded side carries None — unify across both sides
                        return expr_mod.coalesce(
                            self._left[name], self._right[name]
                        )
                    return self._left[name]
            raise ValueError(
                f"column {name!r} exists in both sides of the join; "
                f"use pw.left/pw.right to disambiguate"
            )
        if in_left:
            return self._left[name]
        if in_right:
            return self._right[name]
        raise KeyError(name)

    @property
    def _all_column_names(self) -> list[str]:
        seen = []
        for n in self._left._column_names + self._right._column_names:
            if n not in seen:
                try:
                    self._resolve_deferred(n)
                except ValueError:
                    continue
                except KeyError:
                    continue
                seen.append(n)
        return seen

    def select(self, *args, **kwargs):
        from pathway_tpu.internals.table import Table

        names: list[str] = []
        exprs: list[ColumnExpression] = []

        def add(name, e):
            if name in names:
                exprs[names.index(name)] = e
            else:
                names.append(name)
                exprs.append(e)

        for arg in args:
            if isinstance(arg, thisclass._ThisWithout):
                for n in self._all_column_names:
                    if n not in arg._excluded:
                        add(n, self._resolve_deferred(n))
            elif isinstance(arg, thisclass.ThisClass):
                for n in self._all_column_names:
                    add(n, self._resolve_deferred(n))
            elif isinstance(arg, thisclass.ThisColumnReference):
                add(arg.name, self._desugar(arg))
            elif isinstance(arg, ColumnReference):
                add(arg.name, arg)
            else:
                raise ValueError(f"invalid select argument {arg!r}")
        for n, e in kwargs.items():
            add(n, self._desugar(expr_mod.smart_coerce(e)))

        left, right = self._left, self._right
        lw = len(left._column_names)
        rw = len(right._column_names)
        id_from_left = False
        id_from_right = False
        id_expr = None      # pointer-valued expression supplying output ids
        id_expr_side = None
        if self._id is not None:
            idref = self._id
            if isinstance(idref, thisclass.ThisColumnReference):
                idref = self._desugar(idref)
            id_deps = idref._deps
            dep_tables = {d.table for d in id_deps}
            if (
                isinstance(idref, ColumnReference)
                and idref.name == "id"
                and idref.table is left
            ):
                id_from_left = True
            elif (
                isinstance(idref, ColumnReference)
                and idref.name == "id"
                and idref.table is right
            ):
                id_from_right = True
            elif dep_tables <= {left}:
                # ids come from the VALUES of a left-side pointer expression
                id_expr, id_expr_side = idref, "left"
            elif dep_tables <= {right}:
                id_expr, id_expr_side = idref, "right"
            else:
                raise ValueError("join id= must reference one side of the join")

        out_schema = schema_from_types(**{n: e._dtype for n, e in zip(names, exprs)})
        universe = (
            left._universe
            if id_from_left
            else right._universe if id_from_right else Universe()
        )
        out = Table(out_schema, universe)
        on = self._on
        how = self._how
        self_ = self

        def lower(ctx):
            from pathway_tpu.engine.expression import compile_expression

            let = ctx.engine_table(left)
            ret = ctx.engine_table(right)

            def side_resolver(table):
                def resolver(ref):
                    if ref.name == "id":
                        return "id"
                    if ref.table is not table:
                        raise KeyError(
                            f"join key must reference {table._name}; got {ref!r}"
                        )
                    return table._column_names.index(ref.name)

                return resolver

            lfns = [
                compile_expression(lhs, side_resolver(left), ctx.runtime)
                for lhs, _ in on
            ]
            rfns = [
                compile_expression(rhs, side_resolver(right), ctx.runtime)
                for _, rhs in on
            ]

            def lkey(k, row):
                return tuple(f([k], [row])[0] for f in lfns)

            def rkey(k, row):
                return tuple(f([k], [row])[0] for f in rfns)

            # column-oriented key evaluation for the engine's batch path
            # (one compiled-expression call per batch per key column)
            def lkey_batch(keys, rows):
                cols = [f(keys, rows) for f in lfns]
                return list(zip(*cols)) if cols else [()] * len(keys)

            def rkey_batch(keys, rows):
                cols = [f(keys, rows) for f in rfns]
                return list(zip(*cols)) if cols else [()] * len(keys)

            # NativeBatch fused-chain eligibility: every join condition a
            # plain column == plain column (the shapes join_batch_nb
            # extracts straight from the columnar image); anything else —
            # expressions over the key, pw.this.id — keeps the tuple
            # path. The predicate (and the blame naming the offending
            # expression) lives in analysis/eligibility.py, shared with
            # pw.analyze so analyzer and executor cannot drift.
            from pathway_tpu.analysis import eligibility as _elig

            nb_lkidx, nb_rkidx, nb_lblame, nb_rblame = (
                _elig.join_key_indices(on, left, right)
            )
            nb_blame = (
                nb_lblame + nb_rblame
                + _elig.join_id_blame(id_expr, id_expr_side)
            )

            left_id_fn = right_id_fn = None
            if id_expr is not None:
                side_table = left if id_expr_side == "left" else right
                idf = compile_expression(
                    id_expr, side_resolver(side_table), ctx.runtime
                )

                def _id_fn(k, row):
                    return idf([k], [row])[0]

                if id_expr_side == "left":
                    left_id_fn = _id_fn
                else:
                    right_id_fn = _id_fn

            joined = self_._engine_join(
                ctx,
                let,
                ret,
                lkey,
                rkey,
                how,
                id_from_left=id_from_left,
                id_from_right=id_from_right,
                left_id_fn=left_id_fn,
                right_id_fn=right_id_fn,
                lkey_batch=lkey_batch,
                rkey_batch=rkey_batch,
                nb_lkidx=nb_lkidx,
                nb_rkidx=nb_rkidx,
                nb_blame=nb_blame,
                nb_lblame=nb_lblame,
                nb_rblame=nb_rblame,
            )

            def out_resolver(ref):
                if ref.name == "id":
                    return "id"
                if ref.table is left:
                    return left._column_names.index(ref.name)
                if ref.table is right:
                    return lw + right._column_names.index(ref.name)
                raise KeyError(
                    f"join select can only use columns of the joined tables; got {ref!r}"
                )

            fns = [compile_expression(e, out_resolver, ctx.runtime) for e in exprs]

            def batch_fn(keys, rows):
                cols = [f(keys, rows) for f in fns]
                return list(zip(*cols)) if cols else [()] * len(keys)

            # a select of plain column references is a pure projection:
            # a fused join's NativeBatch output then stays columnar
            # through this hop (RowwiseNode nb_proj_idx -> nb_project)
            nb_proj_idx, proj_blame = _elig.join_projection_indices(
                names, exprs, left, right, lw
            )

            ctx.set_engine_table(
                out,
                ctx.scope.rowwise_auto(
                    joined, batch_fn, len(fns),
                    all(e._is_deterministic for e in exprs),
                    nb_proj_idx=nb_proj_idx,
                    nb_blame=proj_blame,
                    src_exprs=exprs,
                ),
            )

        G.add_operator([left, right], [out], lower, f"join_{how}")
        return out

    def _engine_join(
        self, ctx, let, ret, lkey, rkey, how, *,
        id_from_left, id_from_right, left_id_fn, right_id_fn,
        lkey_batch=None, rkey_batch=None, nb_lkidx=None, nb_rkidx=None,
        nb_blame=(), nb_lblame=None, nb_rblame=None,
    ):
        """Engine-join construction hook; temporal joins override this
        (stdlib/temporal) while reusing the select/desugaring machinery."""
        return ctx.scope.join(
            let,
            ret,
            lkey,
            rkey,
            how,
            id_from_left=id_from_left,
            id_from_right=id_from_right,
            left_id_fn=left_id_fn,
            right_id_fn=right_id_fn,
            lkey_batch=lkey_batch,
            rkey_batch=rkey_batch,
            nb_lkidx=nb_lkidx,
            nb_rkidx=nb_rkidx,
            nb_blame=nb_blame,
            nb_lblame=nb_lblame,
            nb_rblame=nb_rblame,
        )

    def _desugar(self, e):
        def fn(x):
            if isinstance(x, thisclass.ThisColumnReference):
                if x._owner is thisclass.this:
                    return self._resolve_deferred(x.name)
                if x._owner is thisclass.left:
                    return self._left._resolve_deferred(x.name)
                if x._owner is thisclass.right:
                    return self._right._resolve_deferred(x.name)
            return None

        return thisclass.rewrite(expr_mod.smart_coerce(e), fn)

    # -- chained ops over the implicit full select -------------------------
    def _materialized(self):
        return self.select(*[
            self._resolve_deferred(n) for n in self._all_column_names
        ])

    def filter(self, e):
        return self._materialized().filter(e)

    def groupby(self, *args, **kwargs):
        return self._materialized().groupby(*args, **kwargs)

    def reduce(self, *args, **kwargs):
        return self._materialized().reduce(*args, **kwargs)


def _join_id_ref(jr: JoinResult) -> ColumnExpression:
    # pw.this.id in a join select: the joined row's output id.  We expose it
    # as a reference named "id" on the left table; the join lowering maps
    # "id" to the output key directly.
    r = ColumnReference.__new__(ColumnReference)
    ColumnExpression.__init__(r)
    r._table = jr._left
    r._name = "id"
    r._dtype = dt.POINTER
    return r
