"""Global error-log table + error helpers (reference:
parse_graph.py:183 add_error_log, Graph::error_log graph.rs:983,
remove_errors_from_table graph.rs:1005)."""

from __future__ import annotations

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ERROR
from pathway_tpu.internals.expression import apply_with_type
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import schema_from_types
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe

def global_error_log() -> Table:
    """Table(message, origin) of per-row data errors raised by UDFs while
    the pipeline ran; rows that errored carry Error poison values."""
    cached = G.cache.get("global_error_log")
    if cached is not None:
        return cached
    out = Table(
        schema_from_types(message=dt.STR, origin=dt.STR), Universe()
    )

    def lower(ctx):
        from pathway_tpu.engine import nodes as N

        node = N.SourceNode(ctx.scope, append_only=True)
        ctx.runtime.error_log_node = node
        from pathway_tpu.engine.scope import EngineTable

        ctx.set_engine_table(out, EngineTable(node, 2))

    G.add_operator([], [out], lower, "global_error_log")
    G.cache["global_error_log"] = out
    return out


def remove_errors_from_table(table: Table) -> Table:
    """Drop rows containing Error poison values (reference:
    graph.rs:1005)."""
    cols = [table[c] for c in table.column_names()]

    def row_ok(*vals) -> bool:
        return not any(v is ERROR for v in vals)

    # apply short-circuits Error args to an Error mask, which the filter
    # drops — exactly the remove-errors semantics; row_ok keeps the rest
    return table.filter(apply_with_type(row_ok, dt.BOOL, *cols))