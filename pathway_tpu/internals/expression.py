"""Column expression algebra (reference: python/pathway/internals/expression.py:88).

Expressions are built at declaration time by operator overloading on
``ColumnExpression`` and evaluated natively by the engine's batch evaluator
(:mod:`pathway_tpu.engine.expression`) — vectorised over row batches, with
numeric columns lowered to numpy/JAX where possible.  No Python per-row
dispatch happens for pure expressions; only ``pw.apply`` re-enters Python.
"""

from __future__ import annotations

import operator
from typing import TYPE_CHECKING, Any, Callable, Iterable

from pathway_tpu.internals import dtype as dt

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class ColumnExpression:
    _dtype: dt.DType

    def __init__(self):
        self._dtype = dt.ANY

    # -- arithmetics -----------------------------------------------------
    def __add__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.add, "+")

    def __radd__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.add, "+")

    def __sub__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.sub, "-")

    def __rsub__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.sub, "-")

    def __mul__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.mul, "*")

    def __rmul__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.mul, "*")

    def __truediv__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.truediv, "/")

    def __rtruediv__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.truediv, "/")

    def __floordiv__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.floordiv, "//")

    def __rfloordiv__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.floordiv, "//")

    def __mod__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.mod, "%")

    def __rmod__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.mod, "%")

    def __pow__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.pow, "**")

    def __rpow__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.pow, "**")

    def __matmul__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.matmul, "@")

    def __rmatmul__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.matmul, "@")

    def __neg__(self):
        return ColumnUnaryOpExpression(self, operator.neg, "-")

    # -- comparisons -----------------------------------------------------
    def __eq__(self, other):  # type: ignore[override]
        return ColumnBinaryOpExpression(self, other, operator.eq, "==")

    def __ne__(self, other):  # type: ignore[override]
        return ColumnBinaryOpExpression(self, other, operator.ne, "!=")

    def __lt__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.lt, "<")

    def __le__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.le, "<=")

    def __gt__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.gt, ">")

    def __ge__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.ge, ">=")

    # -- boolean ---------------------------------------------------------
    def __and__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.and_, "&")

    def __rand__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.and_, "&")

    def __or__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.or_, "|")

    def __ror__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.or_, "|")

    def __xor__(self, other):
        return ColumnBinaryOpExpression(self, other, operator.xor, "^")

    def __rxor__(self, other):
        return ColumnBinaryOpExpression(other, self, operator.xor, "^")

    def __invert__(self):
        return ColumnUnaryOpExpression(self, operator.not_, "~")

    def __abs__(self):
        return ColumnUnaryOpExpression(self, operator.abs, "abs")

    def __bool__(self):
        raise RuntimeError(
            "Cannot use a ColumnExpression as a boolean; "
            "use & | ~ instead of and/or/not"
        )

    def __hash__(self):
        return id(self)

    # -- containers ------------------------------------------------------
    def __getitem__(self, index):
        return GetExpression(self, index, check_if_exists=False)

    def get(self, index, default=None):
        return GetExpression(self, index, default=default, check_if_exists=True)

    # -- misc API --------------------------------------------------------
    def is_none(self):
        return IsNoneExpression(self)

    def is_not_none(self):
        return IsNotNoneExpression(self)

    def to_string(self):
        return MethodCallExpression("to_string", (self,), _to_string, dt.STR)

    def as_int(self, **kw):
        return ConvertExpression(self, dt.Optional(dt.INT), int)

    def as_float(self, **kw):
        return ConvertExpression(self, dt.Optional(dt.FLOAT), float)

    def as_str(self, **kw):
        return ConvertExpression(self, dt.Optional(dt.STR), str)

    def as_bool(self, **kw):
        return ConvertExpression(self, dt.Optional(dt.BOOL), bool)

    @property
    def dt(self):
        from pathway_tpu.internals.expressions import DateTimeNamespace

        return DateTimeNamespace(self)

    @property
    def str(self):
        from pathway_tpu.internals.expressions import StringNamespace

        return StringNamespace(self)

    @property
    def num(self):
        from pathway_tpu.internals.expressions import NumericalNamespace

        return NumericalNamespace(self)

    def _subexpressions(self) -> Iterable["ColumnExpression"]:
        return ()

    @property
    def _deps(self) -> tuple["ColumnReference", ...]:
        out: list[ColumnReference] = []
        stack = [self]
        while stack:
            e = stack.pop()
            if isinstance(e, ColumnReference):
                out.append(e)
            else:
                stack.extend(e._subexpressions())
        return tuple(out)

    @property
    def _is_deterministic(self) -> bool:
        """False if any apply in the tree is declared non-deterministic —
        such expressions must replay memoized outputs on retraction
        (reference: `deterministic` flag, graph.rs:751 + dataflow.rs:1480
        map_named_async_with_consistent_deletions)."""
        stack: list[ColumnExpression] = [self]
        while stack:
            e = stack.pop()
            if isinstance(e, ApplyExpression) and not e._deterministic:
                return False
            stack.extend(e._subexpressions())
        return True


def _to_string(x):
    return str(x)


def smart_coerce(arg: Any) -> ColumnExpression:
    if isinstance(arg, ColumnExpression):
        return arg
    return ColumnConstExpression(arg)


class ColumnConstExpression(ColumnExpression):
    def __init__(self, value: Any):
        super().__init__()
        self._val = value
        self._dtype = dt.dtype_of_value(value)

    def __repr__(self):
        return repr(self._val)


class ColumnReference(ColumnExpression):
    """Reference to a column of a table: ``table.colname`` / ``pw.this.colname``."""

    def __init__(self, *, table: "Table", name: str):
        super().__init__()
        self._table = table
        self._name = name
        if name == "id":
            self._dtype = dt.POINTER
        else:
            self._dtype = table.schema._dtypes().get(name, dt.ANY)

    @property
    def table(self) -> "Table":
        return self._table

    @property
    def name(self) -> str:
        return self._name

    def __repr__(self):
        return f"<{self._table._name}>.{self._name}"

    def _subexpressions(self):
        return ()


class ColumnBinaryOpExpression(ColumnExpression):
    def __init__(self, left, right, op: Callable, symbol: str):
        super().__init__()
        self._left = smart_coerce(left)
        self._right = smart_coerce(right)
        self._operator = op
        self._symbol = symbol
        self._dtype = _binary_dtype(symbol, self._left._dtype, self._right._dtype)

    def _subexpressions(self):
        return (self._left, self._right)

    def __repr__(self):
        return f"({self._left!r} {self._symbol} {self._right!r})"


def _binary_dtype(symbol: str, lt: dt.DType, rt: dt.DType) -> dt.DType:
    if symbol in ("==", "!=", "<", "<=", ">", ">="):
        return dt.BOOL
    if symbol in ("&", "|", "^") and lt is dt.BOOL and rt is dt.BOOL:
        return dt.BOOL
    if symbol == "/":
        if lt in (dt.INT, dt.FLOAT) and rt in (dt.INT, dt.FLOAT):
            return dt.FLOAT
    if symbol in ("+", "-", "*", "//", "%", "**"):
        if lt is dt.INT and rt is dt.INT:
            return dt.INT
        if lt in (dt.INT, dt.FLOAT) and rt in (dt.INT, dt.FLOAT):
            return dt.FLOAT
        if symbol == "+" and lt is dt.STR and rt is dt.STR:
            return dt.STR
    return dt.lub(lt, rt) if symbol in ("+", "-") else dt.ANY


class ColumnUnaryOpExpression(ColumnExpression):
    def __init__(self, expr, op: Callable, symbol: str):
        super().__init__()
        self._expr = smart_coerce(expr)
        self._operator = op
        self._symbol = symbol
        self._dtype = dt.BOOL if symbol == "~" else self._expr._dtype

    def _subexpressions(self):
        return (self._expr,)

    def __repr__(self):
        return f"{self._symbol}({self._expr!r})"


class ReducerExpression(ColumnExpression):
    def __init__(self, reducer, *args, **kwargs):
        super().__init__()
        self._reducer = reducer
        self._args = tuple(smart_coerce(a) for a in args)
        self._kwargs = kwargs
        self._dtype = reducer.return_type([a._dtype for a in self._args])

    def _subexpressions(self):
        return self._args

    def __repr__(self):
        return f"pathway.reducers.{self._reducer.name}({', '.join(map(repr, self._args))})"


class ApplyExpression(ColumnExpression):
    def __init__(
        self,
        fun: Callable,
        return_type: Any,
        propagate_none: bool,
        deterministic: bool,
        args: tuple,
        kwargs: dict,
        max_batch_size: int | None = None,
    ):
        super().__init__()
        self._fun = fun
        self._return_type = return_type
        self._propagate_none = propagate_none
        self._deterministic = deterministic
        self._args = tuple(smart_coerce(a) for a in args)
        self._kwargs = {k: smart_coerce(v) for k, v in kwargs.items()}
        self._max_batch_size = max_batch_size
        self._dtype = dt.wrap(return_type)

    def _subexpressions(self):
        return self._args + tuple(self._kwargs.values())

    def __repr__(self):
        return f"pathway.apply({getattr(self._fun, '__name__', self._fun)}, ...)"


class AsyncApplyExpression(ApplyExpression):
    pass


class FullyAsyncApplyExpression(AsyncApplyExpression):
    pass


class CastExpression(ColumnExpression):
    def __init__(self, return_type: Any, expr):
        super().__init__()
        self._expr = smart_coerce(expr)
        self._dtype = dt.wrap(return_type)

    def _subexpressions(self):
        return (self._expr,)


class ConvertExpression(ColumnExpression):
    """Json →scalar conversions (as_int etc.)."""

    def __init__(self, expr, target: dt.DType, fun: Callable):
        super().__init__()
        self._expr = smart_coerce(expr)
        self._fun = fun
        self._dtype = target

    def _subexpressions(self):
        return (self._expr,)


class DeclareTypeExpression(ColumnExpression):
    def __init__(self, return_type: Any, expr):
        super().__init__()
        self._expr = smart_coerce(expr)
        self._dtype = dt.wrap(return_type)

    def _subexpressions(self):
        return (self._expr,)


class CoalesceExpression(ColumnExpression):
    def __init__(self, *args):
        super().__init__()
        self._args = tuple(smart_coerce(a) for a in args)
        self._dtype = dt.lub(*(dt.unoptionalize(a._dtype) for a in self._args))

    def _subexpressions(self):
        return self._args


class RequireExpression(ColumnExpression):
    def __init__(self, val, *args):
        super().__init__()
        self._val = smart_coerce(val)
        self._args = tuple(smart_coerce(a) for a in args)
        self._dtype = dt.Optional(self._val._dtype)

    def _subexpressions(self):
        return (self._val,) + self._args


class IfElseExpression(ColumnExpression):
    def __init__(self, _if, _then, _else):
        super().__init__()
        self._if = smart_coerce(_if)
        self._then = smart_coerce(_then)
        self._else = smart_coerce(_else)
        self._dtype = dt.lub(self._then._dtype, self._else._dtype)

    def _subexpressions(self):
        return (self._if, self._then, self._else)


class IsNoneExpression(ColumnExpression):
    def __init__(self, expr):
        super().__init__()
        self._expr = smart_coerce(expr)
        self._dtype = dt.BOOL

    def _subexpressions(self):
        return (self._expr,)


class IsNotNoneExpression(ColumnExpression):
    def __init__(self, expr):
        super().__init__()
        self._expr = smart_coerce(expr)
        self._dtype = dt.BOOL

    def _subexpressions(self):
        return (self._expr,)


class PointerExpression(ColumnExpression):
    """``table.pointer_from(...)`` — derive a row id from values."""

    def __init__(self, table: "Table", *args, optional: bool = False, instance=None):
        super().__init__()
        self._table = table
        self._args = tuple(smart_coerce(a) for a in args)
        self._optional = optional
        self._instance = smart_coerce(instance) if instance is not None else None
        self._dtype = dt.Optional(dt.POINTER) if optional else dt.POINTER

    def _subexpressions(self):
        extra = (self._instance,) if self._instance is not None else ()
        return self._args + extra


class MakeTupleExpression(ColumnExpression):
    def __init__(self, *args):
        super().__init__()
        self._args = tuple(smart_coerce(a) for a in args)
        self._dtype = dt.Tuple(*(a._dtype for a in self._args))

    def _subexpressions(self):
        return self._args


class GetExpression(ColumnExpression):
    def __init__(self, obj, index, default=None, check_if_exists=True):
        super().__init__()
        self._object = smart_coerce(obj)
        self._index = smart_coerce(index)
        self._default = smart_coerce(default)
        self._check_if_exists = check_if_exists
        obj_t = self._object._dtype
        if isinstance(obj_t, dt._TupleDType) and isinstance(
            self._index, ColumnConstExpression
        ):
            idx = self._index._val
            if isinstance(idx, int) and -len(obj_t.args) <= idx < len(obj_t.args):
                self._dtype = obj_t.args[idx]
            else:
                self._dtype = dt.ANY
        elif isinstance(obj_t, dt._ListDType):
            self._dtype = obj_t.arg if not check_if_exists else dt.Optional(obj_t.arg)
        elif obj_t is dt.JSON:
            self._dtype = dt.Optional(dt.JSON) if check_if_exists else dt.JSON
        else:
            self._dtype = dt.ANY

    def _subexpressions(self):
        return (self._object, self._index, self._default)


class MethodCallExpression(ColumnExpression):
    """A .dt/.str/.num namespace method lowered to a native batch function.

    ``propagate_none=False`` lets the function see None subjects —
    required by methods whose JOB is handling None (num.fill_na)."""

    def __init__(
        self, name: str, args: tuple, fun: Callable, return_type: Any,
        propagate_none: bool = True,
    ):
        super().__init__()
        self._name = name
        self._args = tuple(smart_coerce(a) for a in args)
        self._fun = fun
        self._dtype = dt.wrap(return_type)
        self._propagate_none = propagate_none

    def _subexpressions(self):
        return self._args

    def __repr__(self):
        return f"({self._args[0]!r}).{self._name}(...)"


class UnwrapExpression(ColumnExpression):
    def __init__(self, expr):
        super().__init__()
        self._expr = smart_coerce(expr)
        self._dtype = dt.unoptionalize(self._expr._dtype)

    def _subexpressions(self):
        return (self._expr,)


class FillErrorExpression(ColumnExpression):
    def __init__(self, expr, replacement):
        super().__init__()
        self._expr = smart_coerce(expr)
        self._replacement = smart_coerce(replacement)
        self._dtype = dt.lub(self._expr._dtype, self._replacement._dtype)

    def _subexpressions(self):
        return (self._expr, self._replacement)


# -- free functions exposed as pw.* -------------------------------------


def if_else(_if, _then, _else) -> IfElseExpression:
    return IfElseExpression(_if, _then, _else)


def coalesce(*args) -> CoalesceExpression:
    return CoalesceExpression(*args)


def require(val, *args) -> RequireExpression:
    return RequireExpression(val, *args)


def cast(target_type, expr) -> CastExpression:
    return CastExpression(target_type, expr)


def declare_type(target_type, expr) -> DeclareTypeExpression:
    return DeclareTypeExpression(target_type, expr)


def unwrap(expr) -> UnwrapExpression:
    return UnwrapExpression(expr)


def fill_error(expr, replacement) -> FillErrorExpression:
    return FillErrorExpression(expr, replacement)


def make_tuple(*args) -> MakeTupleExpression:
    return MakeTupleExpression(*args)


def apply(fun, *args, **kwargs) -> ApplyExpression:
    return ApplyExpression(fun, dt.ANY, False, True, args, kwargs)


def apply_with_type(fun, ret_type, *args, **kwargs) -> ApplyExpression:
    return ApplyExpression(fun, ret_type, False, True, args, kwargs)


def apply_async(fun, *args, **kwargs) -> AsyncApplyExpression:
    return AsyncApplyExpression(fun, dt.ANY, False, True, args, kwargs)


def assert_table_has_columns(table, columns) -> None:
    """Raise AssertionError unless every name in `columns` is a column of
    `table` (reference: table presence checks used in pipeline glue)."""
    missing = [c for c in columns if c not in table.column_names()]
    assert not missing, (
        f"table is missing columns {missing}; has {table.column_names()}"
    )
