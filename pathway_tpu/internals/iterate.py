"""pw.iterate — fixed-point iteration (reference:
Graph::iterate graph.rs:941; engine impl src/engine/dataflow/
complex_columns.rs; python surface internals/common.py iterate).

The body is captured ONCE into a scoped operator list at declaration time.
At run time the IterateNode re-lowers that body onto a fresh throwaway
Runtime per fixpoint pass: feed current state as static tables, run to
completion, compare outputs; repeat until stable (or `iteration_limit`).
Whole-state recompute per *timestamp* keeps retraction semantics exact (the
node diffs the converged output against what it previously emitted) without
re-deriving differential's nested-scope compaction — the right trade for a
batch-per-timestamp scheduler. Dense per-iteration work still hits XLA
through whatever UDFs the body uses.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Any, Callable

from pathway_tpu.engine.nodes import Node
from pathway_tpu.engine.scope import EngineTable
from pathway_tpu.engine.stream import TableState, consolidate, freeze_row, negate
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.universe import Universe


class _IterateOutputNode(Node):
    """Reader for one output slot of an IterateNode. Fed directly via
    accept() (the IterateNode routes per-name outputs itself), but the
    graph edge from the IterateNode matters: the multi-process lockstep
    protocol computes downstream-reachable exchange masks over the static
    graph, and without the edge the ranks would disagree mid-timestep on
    which exchanges an iterate output can feed (runtime.py
    _exchange_reach_masks)."""

    def __init__(self, scope, iter_node):
        super().__init__(scope, [iter_node])

    def process(self, time, batches):
        return consolidate(batches[0])


class IterateNode(Node):
    def __init__(
        self,
        scope,
        input_nodes: list[Node],
        input_tables: list,            # DSL tables, same order as input_nodes
        placeholders: dict[str, Any],  # name -> placeholder DSL table
        body_ops: list,
        result_tables: dict[str, Any],  # name -> body output DSL table
        extra_tables: list,             # outer tables used by the body
        iteration_limit: int | None,
    ):
        super().__init__(scope, input_nodes)
        self.input_tables = input_tables
        self.placeholders = placeholders
        self.body_ops = body_ops
        self.result_tables = result_tables
        self.extra_tables = extra_tables
        # set via attach_outputs (output nodes need this node as their
        # graph input, so they are created after it)
        self.output_nodes: dict[str, _IterateOutputNode] = {}
        self.iteration_limit = iteration_limit
        self.states = [TableState() for _ in input_nodes]
        # name -> {key: row} last emitted output
        self.emitted: dict[str, dict] = {}

    def attach_outputs(
        self, output_nodes: dict[str, "_IterateOutputNode"]
    ) -> None:
        self.output_nodes = output_nodes
        self.emitted = {name: {} for name in output_nodes}

    def process(self, time, batches):
        for st, batch in zip(self.states, batches):
            st.apply(consolidate(batch))

        n_iter = len(self.placeholders)
        iter_state = {
            name: dict(self.states[i].rows)
            for i, name in enumerate(self.placeholders)
        }
        extra_state = {
            id(t): dict(self.states[n_iter + j].rows)
            for j, t in enumerate(self.extra_tables)
        }

        limit = self.iteration_limit
        rounds = 0
        while True:
            rounds += 1
            new_state = self._run_body(iter_state, extra_state)
            if self._same(new_state, iter_state) or (
                limit is not None and rounds >= limit
            ):
                iter_state = new_state
                break
            iter_state = new_state

        # diff converged outputs against previously emitted
        for name, out_node in self.output_nodes.items():
            prev = self.emitted[name]
            cur = iter_state[name]
            deltas = []
            for k, row in prev.items():
                if k not in cur or freeze_row(cur[k]) != freeze_row(row):
                    deltas.append((k, row, -1))
            for k, row in cur.items():
                if k not in prev or freeze_row(prev[k]) != freeze_row(row):
                    deltas.append((k, row, 1))
            self.emitted[name] = dict(cur)
            if deltas:
                out_node.accept(time, 0, deltas)
                self.scope.runtime.mark_pending(time, out_node)
        return []

    def _run_body(self, iter_state, extra_state):
        from pathway_tpu.engine.runtime import Runtime
        from pathway_tpu.internals.graph_runner import LoweringContext

        # local_only: the fixpoint body is a complete local subgraph over
        # this node's (gathered) state — it must not try to join the
        # process mesh even under PATHWAY_PROCESSES>1
        rt = Runtime(local_only=True)
        ctx = LoweringContext(rt)
        for name, ph in self.placeholders.items():
            rows = [(k, row) for k, row in iter_state[name].items()]
            width = len(ph._column_names)
            ctx.set_engine_table(ph, rt.scope.static_table(rows, width))
        for t in self.extra_tables:
            rows = [(k, row) for k, row in extra_state[id(t)].items()]
            ctx.set_engine_table(
                t, rt.scope.static_table(rows, len(t._column_names))
            )
        for op in self.body_ops:
            rt.current_trace = op.trace
            op.lower_fn(ctx)
        rt.current_trace = None
        captures = {
            name: rt.scope.capture(ctx.engine_table(t))
            for name, t in self.result_tables.items()
        }
        rt.run_static()
        return {name: dict(c.state.rows) for name, c in captures.items()}

    @staticmethod
    def _same(a, b) -> bool:
        if a.keys() != b.keys():
            return False
        for name in a:
            da, db = a[name], b[name]
            if da.keys() != db.keys():
                return False
            for k in da:
                if freeze_row(da[k]) != freeze_row(db[k]):
                    return False
        return True


def iterate(
    body: Callable,
    iteration_limit: int | None = None,
    **kwargs,
):
    """Iterate `body` to a fixed point (reference: pw.iterate).

    kwargs are the iterated tables; the body receives placeholder tables
    with the same schemas and must return a Table (single iterated value)
    or a dict/namespace with the same names as kwargs.
    """
    from pathway_tpu.internals.table import Table

    if not kwargs:
        raise ValueError("iterate() needs at least one table argument")
    tables = {name: t for name, t in kwargs.items()}
    placeholders = {
        name: Table(t._schema_cls, Universe()) for name, t in tables.items()
    }
    with G.scoped() as body_ops:
        result = body(**placeholders)

    if isinstance(result, Table):
        if len(tables) != 1:
            raise ValueError(
                "body returned a single table but iterate() got several"
            )
        result_map = {next(iter(tables)): result}
        single = True
    else:
        result_map = dict(
            result if isinstance(result, dict) else vars(result)
        )
        single = False
        if set(result_map) != set(tables):
            raise ValueError(
                f"body must return tables named {sorted(tables)}, "
                f"got {sorted(result_map)}"
            )

    body_op_ids = {id(op) for op in body_ops}
    placeholder_ids = {id(t) for t in placeholders.values()}
    extra_tables: list = []
    seen: set[int] = set()
    for op in body_ops:
        for t in op.inputs:
            if (
                id(t) not in placeholder_ids
                and id(t) not in seen
                and (t._source is None or id(t._source) not in body_op_ids)
            ):
                seen.add(id(t))
                extra_tables.append(t)

    outputs = {
        name: Table(result_map[name]._schema_cls, Universe())
        for name in result_map
    }

    def lower(ctx):
        # Multi-process: every input gathers to rank 0, the fixpoint runs
        # there over the full state, and downstream ExchangeNodes re-shard
        # the converged output — the iterate scope is a non-partitioned
        # operator, like the reference's worker-0-reads-then-exchanges
        # pattern for unpartitioned sources (SURVEY §5). The fixpoint's
        # data-dependent re-stepping therefore never has to ride the
        # lockstep exchange protocol mid-iteration.
        input_nodes = [
            ctx.scope._exchange(ctx.engine_table(t), mode="gather").node
            for t in tables.values()
        ]
        input_nodes += [
            ctx.scope._exchange(ctx.engine_table(t), mode="gather").node
            for t in extra_tables
        ]
        iter_node = IterateNode(
            ctx.scope,
            input_nodes,
            list(tables.values()),
            placeholders,
            body_ops,
            result_map,
            extra_tables,
            iteration_limit,
        )
        out_nodes = {
            name: _IterateOutputNode(ctx.scope, iter_node)
            for name in outputs
        }
        iter_node.attach_outputs(out_nodes)
        for name, t in outputs.items():
            ctx.set_engine_table(
                t, EngineTable(out_nodes[name], len(t._column_names))
            )

    G.add_operator(
        list(tables.values()) + extra_tables,
        list(outputs.values()),
        lower,
        "iterate",
    )
    if single:
        return next(iter(outputs.values()))
    return SimpleNamespace(**outputs)
