"""Flight recorder: per-node / per-wave tracing with event-time lag
watermarks and Perfetto (Chrome-trace JSON) export.

The reference engine exports OTLP spans plus input/output latency gauges
from inside the dataflow (SURVEY: src/engine/telemetry.rs, ProberStats);
this is the equivalent attribution layer for the batch-per-timestamp
engine: always compiled, armed by the ``PATHWAY_TRACE=out.json`` knob,
near-zero overhead when disarmed (one attribute check on the step path).

What gets recorded, per rank:

* **per-node spans** from the runtime's step loop (engine/runtime.py
  ``_step_node``): node id + Plan Doctor provenance, commit timestamp,
  rows in, batch representation (columnar NativeBatch vs materialized
  tuples) and self-time — ``process()`` does not recurse into children
  (delivery only buffers), so its duration IS the node's self-time;
* **native batch timers** from the GIL-free regions of native/exec.cpp
  (monotonic clock into a preallocated per-thread ring buffer, no Py*
  calls — ``trace_ring_*``), drained between engine steps;
* **per-wave mesh events** from parallel/procgroup.py: one span per
  exchange wave, per-peer send frames with byte counts, receiver-thread
  decode spans, plus heartbeat/rollback/epoch instant marks;
* **event-time lag watermarks**: connectors stamp ingest time at flush
  (io/_connector.py), sinks report commit→emit latency — the per-output
  freshness histogram also lands on OpenMetrics
  (internals/monitoring.py ``output_lag_ms``).

Export: one track per rank×thread. Multi-rank runs write per-rank
partials (``<path>.r<rank>``) that rank 0 merges at shutdown — clock
offsets between ranks are sampled during the epoch's clock handshake
(runtime ``("tsync",)`` round) and RESAMPLED at every epoch commit
(per-segment offsets, so multi-minute runs don't skew late-run span
alignment as the monotonic clocks drift) so merged per-track
timestamps stay monotonic; ``parallel/supervisor.py`` re-merges as a
fallback after rollback recoveries. All timestamps are ``time.perf_counter_ns()`` /
C++ ``steady_clock`` — the same CLOCK_MONOTONIC timebase.

``python -m pathway_tpu.analysis --profile trace.json`` joins the trace
back onto the plan's NBDecision verdicts (analysis/profile.py).
"""

from __future__ import annotations

import json
import os
import time as _time
from bisect import bisect_right
from typing import Any

# native ring tags (exec.cpp enum TraceTag)
NATIVE_TAGS = {
    1: "gb_apply",
    2: "join_apply",
    3: "shard_partition",
    4: "nb_encode",
    5: "nb_decode",
    6: "nb_concat",
    7: "arrow_export",  # columnar egress: capture collect + Arrow export
}

TRACE_SCHEMA_VERSION = 1


def trace_path() -> str | None:
    """The PATHWAY_TRACE knob: path of the Perfetto JSON to write."""
    return os.environ.get("PATHWAY_TRACE") or None


def ring_capacity() -> int:
    try:
        return int(os.environ.get("PATHWAY_TRACE_RING_EVENTS", "") or 65536)
    except ValueError:
        return 65536


def max_events() -> int:
    try:
        return int(
            os.environ.get("PATHWAY_TRACE_MAX_EVENTS", "") or 2_000_000
        )
    except ValueError:
        return 2_000_000


def partial_path(path: str, rank: int) -> str:
    return f"{path}.r{rank}"


class FlightRecorder:
    """Low-overhead in-memory event log for ONE rank's run.

    Hot-path contract: every ``note_*`` is one perf_counter read plus a
    tuple append (list.append is GIL-atomic, so procgroup receiver
    threads may note concurrently with the main loop). Everything
    else — metadata, Chrome-trace conversion, merging — happens once at
    shutdown.
    """

    def __init__(self, path: str, rank: int = 0, world: int = 1):
        import collections

        self.path = path
        self.rank = rank
        self.world = world
        # offset to rank 0's timebase, as SEGMENTS: (start_mono_ns,
        # offset_ns) — the epoch's clock handshake opens segment 0 and
        # every epoch commit resamples (monotonic clocks drift apart
        # over multi-minute runs; a single handshake-time offset skews
        # late-run span alignment in the merged trace). Events convert
        # with the offset that was current when they were recorded.
        self._offset_segments: list[tuple[int, int]] = [(0, 0)]
        # bounded (PATHWAY_TRACE_MAX_EVENTS): a long-running traced
        # streaming pipeline must not grow heap without limit until the
        # shutdown dump — the deque keeps the NEWEST events (the tail is
        # what a post-mortem wants) and the dump records that the head
        # was capped
        self.max_events = max_events()
        self.events: "collections.deque[tuple]" = collections.deque(
            maxlen=self.max_events
        )
        self.native_events: "collections.deque[tuple]" = collections.deque(
            maxlen=self.max_events
        )
        # events evicted at the deque's maxlen (a full-but-never-
        # overflowed deque is NOT capped — len alone can't tell)
        self.dropped = 0
        # wall/mono anchors: map monotonic event times onto wall clock
        # (OTLP span export; merge fallback when no tsync ran)
        self.wall_anchor_ns = _time.time_ns()
        self.mono_anchor_ns = _time.perf_counter_ns()
        self._ring_armed = False
        self.dumped = False

    @classmethod
    def from_env(cls, local_only: bool = False) -> "FlightRecorder | None":
        """Armed iff PATHWAY_TRACE names an output path. ``local_only``
        runtimes (iterate fixpoint bodies) never record — they would
        clobber the owning run's file."""
        path = trace_path()
        if path is None or local_only:
            return None
        from pathway_tpu.internals.config import get_pathway_config

        c = get_pathway_config()
        return cls(path, rank=c.process_id, world=max(1, c.processes))

    # -- clock offsets ----------------------------------------------------
    # bound on retained tsync samples: one per epoch commit, so a
    # commit-per-second pipeline would otherwise grow this without limit
    # (like the event deque, the NEWEST samples matter — evicted ones
    # correspond to events the bounded deque has already dropped)
    _SEGMENT_CAP = 8192

    @property
    def clock_offset_ns(self) -> int:
        """The CURRENT offset to rank 0's timebase (latest sample)."""
        return self._offset_segments[-1][1]

    @clock_offset_ns.setter
    def clock_offset_ns(self, offset_ns: int) -> None:
        # the epoch handshake's first tsync sample, anchored at the
        # sample instant (events before it convert with this offset
        # unshifted; later samples interpolate forward from here)
        self._offset_segments = [
            (_time.perf_counter_ns(), int(offset_ns))
        ]

    def resample_clock_offset(
        self, offset_ns: int, at_ns: int | None = None
    ) -> None:
        """Record a fresh tsync sample at `at_ns` (now by default).
        Conversion interpolates LINEARLY between consecutive samples
        (constant outside them): the linear-drift model keeps
        multi-minute multi-rank traces aligned without stretching one
        stale handshake offset over the run, and — unlike a step
        function — it is continuous and monotone (|Δoffset| between
        commits is microseconds against seconds of wall, so the
        conversion slope stays ~1), so a resample can never step a
        track's converted timestamps backwards. Out-of-order samples
        are dropped to keep the list sorted."""
        at = _time.perf_counter_ns() if at_ns is None else int(at_ns)
        if at <= self._offset_segments[-1][0]:
            return
        self._offset_segments.append((at, int(offset_ns)))
        if len(self._offset_segments) > self._SEGMENT_CAP:
            # drop the second sample, keeping the first as the baseline
            # anchor for whatever pre-history the event deque retains
            del self._offset_segments[1]

    def _offset_at(self, ns: int) -> int:
        segs = self._offset_segments
        i = bisect_right(segs, (ns, float("inf"))) - 1
        if i < 0:
            return segs[0][1]
        if i + 1 >= len(segs):
            return segs[i][1]
        t0, o0 = segs[i]
        t1, o1 = segs[i + 1]
        return o0 + (o1 - o0) * (ns - t0) // (t1 - t0)

    # -- hot-path notes ---------------------------------------------------
    # (kind, ...) tuples; perf_counter_ns timestamps throughout

    def _note(self, ev: tuple) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1  # deque evicts the head on this append
        self.events.append(ev)

    def note_node(self, nid, t_commit, t0, t1, rows, nb) -> None:
        self._note(("node", nid, t_commit, t0, t1, rows, nb))

    def note_step(self, t_commit, t0, t1) -> None:
        self._note(("step", t_commit, t0, t1))

    def note_wave(self, t_commit, wave_no, t0, t1, n_nodes) -> None:
        self._note(("wave", t_commit, wave_no, t0, t1, n_nodes))

    def note_send(self, peer, t0, t1, nbytes) -> None:
        self._note(("send", peer, t0, t1, nbytes))

    def note_recv_wait(self, peer, t0, t1) -> None:
        self._note(("recvw", peer, t0, t1))

    def note_decode(self, peer, t0, t1, nbytes) -> None:
        # called from procgroup receiver threads (append is GIL-atomic)
        self._note(("decode", peer, t0, t1, nbytes))

    def note_decompress(self, peer, t0, t1, wire_bytes, raw_bytes) -> None:
        # receiver-thread sub-span of a frame decode (ISSUE 13): the
        # codec's share of the decode leg plus its byte ratio. The span
        # is synthetic-contiguous (per-segment inflations interleave
        # with segment decodes; duration is exact, placement starts at
        # the first inflation).
        self._note(("dzip", peer, t0, t1, wire_bytes, raw_bytes))

    def note_dispatch(
        self, site, seq, node, t_commit, t0, t_ret, t_done,
        flops, bytes_accessed, transfer_bytes, depth,
        flops_effective=None,
    ) -> None:
        # device plane (ISSUE 15; internals/device.py): one record per
        # JAX dispatch an engine site issued — wall span [t0, t_done],
        # enqueue boundary t_ret (device time = t_done - t_ret, bounded
        # by block_until_ready), compiled-cost FLOPs/bytes, transfer
        # bytes and the dispatch-queue depth at launch. `node` is the
        # enclosing engine node (None for off-engine dispatches like the
        # gateway's window commit) — the correlation key back to the
        # node span on the engine track. flops_effective (ISSUE 16) is
        # the real-row share of flops (None = fully effective) — the
        # profile's effective-MFU column rides the trace with it.
        self._note(
            ("disp", site, seq, node, t_commit, t0, t_ret, t_done,
             flops, bytes_accessed, transfer_bytes, depth,
             flops if flops_effective is None else flops_effective)
        )

    def note_mark(self, name: str, **args: Any) -> None:
        self._note(("mark", name, _time.perf_counter_ns(), args))

    def note_lag(self, label, t_commit, t_ns, lag_ms, rows) -> None:
        self._note(("lag", label, t_commit, t_ns, lag_ms, rows))

    # -- native ring ------------------------------------------------------
    def arm_native_ring(self) -> None:
        """Preallocate the exec.cpp per-thread rings (no-op without the
        toolchain)."""
        ex = self._pwexec()
        if ex is None or not hasattr(ex, "trace_ring_enable"):
            return
        from pathway_tpu.internals.config import get_pathway_config

        try:
            ex.trace_ring_enable(
                ring_capacity(), get_pathway_config().threads + 1
            )
            self._ring_armed = True
        except Exception:
            pass

    def drain_native(self) -> None:
        """Pull buffered GIL-free batch timers out of the C rings —
        called between engine steps so long runs can't wrap the ring.
        The rings are process-global: under the emulated-rank CI lane
        (several thread-ranks per process) whichever rank drains next
        claims the buffered events, so per-rank native attribution in
        that lane is approximate; real multi-rank runs are separate
        processes and attribute exactly."""
        if not self._ring_armed:
            return
        ex = self._pwexec()
        if ex is None:
            return
        try:
            evs = ex.trace_ring_drain()
        except Exception:
            return
        if evs:
            overflow = (
                len(self.native_events) + len(evs) - self.max_events
            )
            if overflow > 0:
                self.dropped += min(overflow, len(self.native_events))
            self.native_events.extend(evs)

    def disarm_native_ring(self) -> None:
        if not self._ring_armed:
            return
        self._ring_armed = False
        ex = self._pwexec()
        if ex is not None and hasattr(ex, "trace_ring_disable"):
            try:
                ex.trace_ring_disable()
            except Exception:
                pass

    @staticmethod
    def _pwexec():
        try:
            from pathway_tpu.native import get_pwexec

            return get_pwexec()
        except Exception:
            return None

    # -- metadata ---------------------------------------------------------
    def node_meta(self, scope) -> dict:
        """Per-node metadata joined onto the trace: label, declaring
        user frame (Plan Doctor provenance), and the NBDecision verdict
        — the SAME objects the executor gates its columnar paths on, so
        measured and static verdicts cannot drift."""
        meta: dict[str, dict] = {}
        if scope is None:
            return meta
        for i, node in enumerate(scope.nodes):
            ent: dict[str, Any] = {
                "label": f"{type(node).__name__}#{i}",
                "kind": type(node).__name__,
            }
            tr = getattr(node, "trace", None)
            if tr is not None:
                ent["provenance"] = (
                    f"{getattr(tr, 'filename', '?')}:"
                    f"{getattr(tr, 'lineno', '?')} in "
                    f"{getattr(tr, 'name', '?')}"
                )
            dec = getattr(node, "nb_decision", None)
            if dec is not None:
                ent["verdict"] = "fused" if getattr(dec, "ok", False) else (
                    "degraded"
                )
                blame = getattr(dec, "blame", ()) or ()
                if blame:
                    ent["blame"] = list(blame)[:4]
            if getattr(node, "device_node", False):
                # this node's process() issues JAX dispatches (engine/
                # nodes.py Node.device_node): the device plane's spans
                # correlate to it, and --profile joins its roofline
                # verdict here
                ent["device"] = True
            kind = type(node).__name__
            if kind in ("OutputNode", "CaptureNode"):
                ent["sink"] = True
                # egress verdict keyed on the CONSUMER's declared
                # capability (ISSUE 14): an Arrow-batch consumer (or a
                # CaptureNode with the columnar export door) consumes
                # NativeBatch output without row expansion. row_expanding
                # marks the sinks that pay PER-ROW Python work they could
                # avoid: a per-row on_change callback (always), a rows
                # consumer over a statically-columnar chain (every
                # C-owned batch materializes), or a doorless CaptureNode.
                # A batched rows consumer of an already-tuple chain is
                # NOT row-expanding — the rows were never columnar.
                try:
                    from pathway_tpu.analysis.eligibility import (
                        sink_consumer_columnar,
                        sink_row_expands,
                    )

                    ent["egress"] = (
                        "columnar"
                        if sink_consumer_columnar(node).ok
                        else "rows"
                    )
                    if sink_row_expands(node):
                        ent["row_expanding"] = True
                except Exception:
                    ent["egress"] = "rows"
                    ent["row_expanding"] = True
            meta[str(i)] = ent
        return meta

    # -- Chrome-trace conversion ------------------------------------------
    def _us(self, ns: int) -> float:
        # ns precision in µs units = 3 decimals; rounding keeps json
        # reprs short (encode time is part of the measured run). The
        # offset applied is the tsync sample that was CURRENT when the
        # event was recorded (per-segment; resampled at epoch commits).
        return round((ns + self._offset_at(ns)) / 1000.0, 3)

    def chrome_events(self, scope=None) -> list[dict]:
        """Convert the raw event log into Chrome-trace events (ts/dur in
        microseconds, clock offset to rank 0 applied). One track per
        rank×thread: tid 0 = engine step loop, 100+w = native executor
        threads, 200+peer = receiver threads."""
        pid = self.rank
        out: list[dict] = [
            {
                "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                "args": {"name": f"rank {pid}"},
            },
            {
                "ph": "M", "pid": pid, "tid": 0, "name": "thread_name",
                "args": {"name": "engine"},
            },
        ]
        named_tids: set[int] = {0}

        def tid_named(tid: int, name: str) -> int:
            if tid not in named_tids:
                named_tids.add(tid)
                out.append(
                    {
                        "ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name", "args": {"name": name},
                    }
                )
            return tid

        dispatch_tids: dict[str, int] = {}
        labels: dict[int, str] = {}
        if scope is not None:
            labels = {
                i: f"{type(n).__name__}#{i}"
                for i, n in enumerate(scope.nodes)
            }
        # snapshot first: receiver threads may still append decode notes
        # (deques raise on mutation during iteration; list(deque) is a
        # single C-level copy under the GIL)
        for ev in list(self.events):
            kind = ev[0]
            if kind == "node":
                _, nid, t_commit, t0, t1, rows, nb = ev
                out.append(
                    {
                        "name": labels.get(nid, f"node#{nid}"),
                        "cat": "node", "ph": "X", "pid": pid, "tid": 0,
                        "ts": self._us(t0),
                        "dur": _dur_us(t0, t1),
                        "args": {
                            "node": nid, "t": t_commit, "rows": rows,
                            "rep": "nb" if nb else "tuple",
                        },
                    }
                )
            elif kind == "step":
                _, t_commit, t0, t1 = ev
                out.append(
                    {
                        "name": "step", "cat": "step", "ph": "X",
                        "pid": pid, "tid": 0, "ts": self._us(t0),
                        "dur": _dur_us(t0, t1),
                        "args": {"t": t_commit},
                    }
                )
            elif kind == "wave":
                _, t_commit, wave_no, t0, t1, n_nodes = ev
                out.append(
                    {
                        "name": f"wave {wave_no}", "cat": "wave",
                        "ph": "X", "pid": pid, "tid": 0,
                        "ts": self._us(t0),
                        "dur": _dur_us(t0, t1),
                        "args": {"t": t_commit, "exchanges": n_nodes},
                    }
                )
            elif kind == "send":
                # sender-thread track (ISSUE 13): sends drain off the
                # engine loop, so their spans overlap node/wave spans —
                # a dedicated per-peer track keeps every track's spans
                # properly nested for the schema check
                _, peer, t0, t1, nbytes = ev
                tid = tid_named(300 + peer, f"send peer {peer}")
                out.append(
                    {
                        "name": f"send→{peer}", "cat": "mesh", "ph": "X",
                        "pid": pid, "tid": tid, "ts": self._us(t0),
                        "dur": _dur_us(t0, t1),
                        "args": {"bytes": nbytes, "peer": peer},
                    }
                )
            elif kind == "recvw":
                _, peer, t0, t1 = ev
                out.append(
                    {
                        "name": f"recv-wait←{peer}", "cat": "mesh",
                        "ph": "X", "pid": pid, "tid": 0,
                        "ts": self._us(t0),
                        "dur": _dur_us(t0, t1),
                        "args": {"peer": peer},
                    }
                )
            elif kind == "decode":
                _, peer, t0, t1, nbytes = ev
                tid = tid_named(200 + peer, f"recv peer {peer}")
                out.append(
                    {
                        "name": f"decode←{peer}", "cat": "mesh", "ph": "X",
                        "pid": pid, "tid": tid, "ts": self._us(t0),
                        "dur": _dur_us(t0, t1),
                        "args": {"bytes": nbytes, "peer": peer},
                    }
                )
            elif kind == "dzip":
                # decompress sub-span, nested inside its frame's decode
                # span on the same receiver track (ISSUE 13)
                _, peer, t0, t1, wire_b, raw_b = ev
                tid = tid_named(200 + peer, f"recv peer {peer}")
                out.append(
                    {
                        "name": f"decompress←{peer}", "cat": "mesh",
                        "ph": "X", "pid": pid, "tid": tid,
                        "ts": self._us(t0),
                        "dur": _dur_us(t0, t1),
                        "args": {
                            "peer": peer, "bytes": wire_b, "raw": raw_b,
                        },
                    }
                )
            elif kind == "disp":
                # device dispatch span (ISSUE 15): one track per
                # dispatch SITE (tid 400+) so device work reads as its
                # own lane under the engine track in Perfetto.
                # Concurrent async dispatches legitimately overlap, so
                # cat "device" is — like "native" — a sample stream,
                # exempt from the nesting check (validate_trace).
                (_, site, seq, node, t_commit, t0, t_ret, t_done,
                 flops, bytes_acc, xfer, depth, *rest) = ev
                flops_eff = rest[0] if rest else flops
                sidx = dispatch_tids.setdefault(
                    site, 400 + len(dispatch_tids)
                )
                tid = tid_named(sidx, f"device {site}")
                out.append(
                    {
                        "name": site, "cat": "device", "ph": "X",
                        "pid": pid, "tid": tid, "ts": self._us(t0),
                        "dur": _dur_us(t0, t_done),
                        "args": {
                            "dispatch": seq,
                            "node": node,
                            "t": t_commit,
                            # block_until_ready-bounded device share of
                            # the wall span (µs); wall - device = host
                            # assembly + enqueue
                            "device_us": _dur_us(t_ret, t_done),
                            "flops": flops,
                            "flops_effective": flops_eff,
                            "bytes_accessed": bytes_acc,
                            "transfer_bytes": xfer,
                            "queue_depth": depth,
                        },
                    }
                )
            elif kind == "mark":
                _, name, t_ns, args = ev
                out.append(
                    {
                        "name": name, "cat": "mark", "ph": "i",
                        "pid": pid, "tid": 0, "ts": self._us(t_ns),
                        "s": "p", "args": dict(args),
                    }
                )
            elif kind == "lag":
                _, label, t_commit, t_ns, lag_ms, rows = ev
                out.append(
                    {
                        "name": f"freshness {label}", "cat": "lag",
                        "ph": "C", "pid": pid, "tid": 0,
                        "ts": self._us(t_ns),
                        "args": {"lag_ms": round(lag_ms, 3)},
                    }
                )
        for tag, thr, t0, t1, rows in list(self.native_events):
            name = NATIVE_TAGS.get(tag, f"native{tag}")
            tid = tid_named(
                100 + thr, "native entry" if thr == 0 else f"native w{thr - 1}"
            )
            out.append(
                {
                    "name": name, "cat": "native", "ph": "X", "pid": pid,
                    "tid": tid, "ts": self._us(t0),
                    "dur": _dur_us(t0, t1),
                    "args": {"rows": rows},
                }
            )
        return out

    # -- summaries --------------------------------------------------------
    def node_aggregates(self) -> dict[int, dict]:
        """node id -> {self_s, rows, batches, nb_batches} over this
        rank's events (the profile pass re-derives the same from the
        merged file; this feeds the OTLP per-node span export)."""
        agg: dict[int, dict] = {}
        for ev in list(self.events):
            if ev[0] != "node":
                continue
            _, nid, _t, t0, t1, rows, nb = ev
            a = agg.setdefault(
                nid,
                {
                    "self_s": 0.0, "rows": 0, "batches": 0,
                    "nb_batches": 0, "first_ns": t0, "last_ns": t1,
                },
            )
            a["self_s"] += max(0, t1 - t0) / 1e9
            a["rows"] += max(0, rows)
            a["batches"] += 1
            if nb:
                a["nb_batches"] += 1
            a["first_ns"] = min(a["first_ns"], t0)
            a["last_ns"] = max(a["last_ns"], t1)
        return agg

    def otlp_node_spans(self, scope=None) -> list[dict]:
        """One aggregate span per node for the OTLP flush-on-shutdown
        path (internals/otlp.py drain): wall-clock times via the
        recorder's anchors, self-time/rows/rep as attributes."""
        wall0 = self.wall_anchor_ns - self.mono_anchor_ns
        meta = self.node_meta(scope)
        spans = []
        for nid, a in sorted(self.node_aggregates().items()):
            m = meta.get(str(nid), {})
            spans.append(
                {
                    "name": f"node.{m.get('label', nid)}",
                    "start_ns": wall0 + a["first_ns"],
                    "end_ns": wall0 + a["last_ns"],
                    "attrs": {
                        "node.id": nid,
                        "node.self_s": round(a["self_s"], 6),
                        "node.rows": a["rows"],
                        "node.batches": a["batches"],
                        "node.nb_batches": a["nb_batches"],
                        **(
                            {"node.verdict": m["verdict"]}
                            if "verdict" in m
                            else {}
                        ),
                    },
                }
            )
        return spans

    # -- dump / merge -----------------------------------------------------
    def _doc(self, scope=None) -> dict:
        # dropped counts actual head evictions — a deque that is full
        # but never overflowed is NOT capped
        capped = self.dropped > 0
        if capped:
            import logging

            logging.getLogger(__name__).warning(
                "flight recorder hit PATHWAY_TRACE_MAX_EVENTS=%d: the "
                "trace keeps only the newest events (%d dropped)",
                self.max_events, self.dropped,
            )
        # device-plane platform stamp (ISSUE 15 satellite): which
        # backend/device this rank measured, plus the peak rates its
        # MFU/roofline numbers used — None when jax never loaded here
        # (pure relational run; platform_info never imports jax itself)
        from pathway_tpu.internals.device import platform_info

        # per-site recompile counters (ISSUE 20): the Device Doctor's
        # --profile join diffs these measured counts against its static
        # shape-bucket predictions (predicted-vs-measured drift verdict)
        recompiles: dict = {}
        stats = getattr(
            getattr(getattr(scope, "runtime", None), "stats", None),
            "device_recompiles", None,
        )
        if stats:
            recompiles = dict(stats)
        return {
            "schema": TRACE_SCHEMA_VERSION,
            "rank": self.rank,
            "world": self.world,
            "event_cap": self.max_events,
            "capped": capped,
            "dropped_events": self.dropped,
            "platform": platform_info(),
            "device_recompiles": recompiles,
            "clock_offset_ns": self.clock_offset_ns,
            "offset_segments": [
                [s, o] for s, o in self._offset_segments
            ],
            "wall_anchor_ns": self.wall_anchor_ns,
            "mono_anchor_ns": self.mono_anchor_ns,
            "events": self.chrome_events(scope),
            "nodes": self.node_meta(scope),
        }

    def dump_partial(self, scope=None) -> str:
        """Write this rank's partial (<path>.r<rank>) — merged by rank 0
        (or the MeshSupervisor fallback) into the final file."""
        self.drain_native()
        p = partial_path(self.path, self.rank)
        doc = self._doc(scope)
        doc["partial"] = True
        _atomic_write_json(p, doc)
        self.dumped = True
        return p

    def dump(self, scope=None) -> str:
        """Single-rank export: write the final Perfetto-loadable file."""
        self.drain_native()
        doc = self._doc(scope)
        out = {
            "traceEvents": _ts_sorted(doc.pop("events")),
            "displayTimeUnit": "ms",
            "pathway": doc,
        }
        _atomic_write_json(self.path, out)
        self.dumped = True
        return self.path

    def merge(self, scope=None) -> str | None:
        """Rank 0: merge every rank's partial (own events inline) into
        the final file. Missing partials (a rank that crashed before its
        dump) are skipped — the merge records which ranks contributed."""
        return merge_trace_files(
            self.path,
            self.world,
            own_doc=self._doc(scope),
        )


def _dur_us(t0: int, t1: int) -> float:
    return round(max(0, t1 - t0) / 1000.0, 3)


def _ts_sorted(events: list[dict]) -> list[dict]:
    """Time-sort the event array (metadata records first): raw events
    append parent spans AFTER their children (the span closes when the
    parent's timer stops), and merged files interleave ranks — sorting
    by offset-shifted ts makes per-track timestamps monotonic in file
    order, which the trace-schema tests pin."""
    return sorted(events, key=lambda e: e.get("ts", -1.0))


def _atomic_write_json(path: str, doc: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        # dumps + write, NOT json.dump: the fp variant always runs the
        # pure-Python iterencode path (44 ms for a small trace — 10% of
        # a bench run, measured), dumps uses the C encoder
        f.write(json.dumps(doc, separators=(",", ":")))
    os.replace(tmp, path)


def merge_trace_files(
    path: str, world: int, own_doc: dict | None = None
) -> str | None:
    """Merge ``<path>.r<rank>`` partials into the final Chrome-trace
    file at ``path``. ``own_doc`` supplies rank 0's events directly
    (runtime shutdown path); the supervisor fallback passes None and
    reads every rank — including 0 — from its partial file. Partials'
    events already carry their tsync clock offsets, so per-track
    timestamps stay monotonic after the merge."""
    events: list[dict] = []
    nodes: dict = {}
    ranks: list[int] = []
    meta: dict[str, Any] = {}
    for rank in range(world):
        doc = None
        if own_doc is not None and rank == own_doc.get("rank"):
            doc = own_doc
        else:
            try:
                with open(partial_path(path, rank)) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
        ranks.append(rank)
        events.extend(doc.get("events", ()))
        if not nodes:
            nodes = doc.get("nodes", {})
        meta[f"rank{rank}"] = {
            "clock_offset_ns": doc.get("clock_offset_ns", 0),
            # per-segment tsync samples (resampled at epoch commits);
            # already applied to the partial's event timestamps at
            # conversion — recorded here for post-mortems only
            "offset_segments": doc.get("offset_segments"),
            "wall_anchor_ns": doc.get("wall_anchor_ns"),
            # what hardware this rank measured (device plane, ISSUE 15)
            "platform": doc.get("platform"),
        }
    if not ranks:
        return None
    out = {
        "traceEvents": _ts_sorted(events),
        "displayTimeUnit": "ms",
        "pathway": {
            "schema": TRACE_SCHEMA_VERSION,
            "world": world,
            "merged_ranks": ranks,
            "nodes": nodes,
            "rank_meta": meta,
        },
    }
    _atomic_write_json(path, out)
    # partials are merged in; leave them on disk only when some rank is
    # missing (a later supervisor re-merge may still want them)
    if len(ranks) == world:
        for rank in range(world):
            try:
                os.remove(partial_path(path, rank))
            except OSError:
                pass
    return path
