"""User-facing datetime types (reference:
python/pathway/internals/datetime_types.py — DateTimeNaive/DateTimeUtc/
Duration extend the pandas timestamp family, usable BOTH as schema
annotations and as constructors: ``pw.Duration(days=1)``)."""

from __future__ import annotations

import pandas as pd


class DateTimeNaive(pd.Timestamp):
    """Datetime without timezone information (extends pandas.Timestamp)."""


class DateTimeUtc(pd.Timestamp):
    """Datetime with a timezone (extends pandas.Timestamp)."""


class Duration(pd.Timedelta):
    """A span of time (extends pandas.Timedelta)."""
