"""Schema system (reference: python/pathway/internals/schema.py:913).

``class MySchema(pw.Schema): x: int = pw.column_definition(...)`` declares
column names, dtypes, primary keys and defaults.  Schemas are classes whose
metaclass collects annotations into ordered ``ColumnDefinition``s.
"""

from __future__ import annotations

import typing
from dataclasses import dataclass
from typing import Any, Mapping

from pathway_tpu.internals import dtype as dt

_NO_DEFAULT = object()


@dataclass
class ColumnDefinition:
    dtype: dt.DType = dt.ANY
    primary_key: bool = False
    default_value: Any = _NO_DEFAULT
    name: str | None = None
    append_only: bool | None = None

    @property
    def has_default_value(self) -> bool:
        return self.default_value is not _NO_DEFAULT


def column_definition(
    *,
    primary_key: bool = False,
    default_value: Any = _NO_DEFAULT,
    dtype: Any = None,
    name: str | None = None,
    append_only: bool | None = None,
) -> Any:
    return ColumnDefinition(
        dtype=dt.wrap(dtype) if dtype is not None else dt.ANY,
        primary_key=primary_key,
        default_value=default_value,
        name=name,
        append_only=append_only,
    )


class SchemaMetaclass(type):
    __columns__: dict[str, ColumnDefinition]

    def __new__(mcs, name, bases, namespace, append_only=False, **kwargs):
        cls = super().__new__(mcs, name, bases, namespace)
        columns: dict[str, ColumnDefinition] = {}
        for base in bases:
            columns.update(getattr(base, "__columns__", {}))
        annotations = namespace.get("__annotations__", {})
        hints: dict[str, Any] = {}
        for col, annotation in annotations.items():
            try:
                hints[col] = typing.get_type_hints(
                    type("..", (), {"__annotations__": {col: annotation}})
                )[col]
            except Exception:
                hints[col] = annotation
        for col, annotation in annotations.items():
            definition = namespace.get(col, None)
            if not isinstance(definition, ColumnDefinition):
                definition = ColumnDefinition(
                    default_value=definition if col in namespace else _NO_DEFAULT
                )
            definition.dtype = dt.wrap(hints.get(col, annotation))
            definition.name = definition.name or col
            if definition.append_only is None:
                definition.append_only = append_only
            columns[definition.name] = definition
        cls.__columns__ = columns
        return cls

    def __init__(cls, name, bases, namespace, **kwargs):
        super().__init__(name, bases, namespace)

    # -- introspection ----------------------------------------------------
    def columns(cls) -> Mapping[str, ColumnDefinition]:
        return dict(cls.__columns__)

    def column_names(cls) -> list[str]:
        return list(cls.__columns__.keys())

    def _dtypes(cls) -> dict[str, dt.DType]:
        return {name: c.dtype for name, c in cls.__columns__.items()}

    def typehints(cls) -> dict[str, Any]:
        return {name: c.dtype for name, c in cls.__columns__.items()}

    def primary_key_columns(cls) -> list[str] | None:
        pkeys = [name for name, c in cls.__columns__.items() if c.primary_key]
        return pkeys or None

    def default_values(cls) -> dict[str, Any]:
        return {
            name: c.default_value
            for name, c in cls.__columns__.items()
            if c.has_default_value
        }

    def keys(cls):
        return cls.__columns__.keys()

    def __getitem__(cls, name: str) -> ColumnDefinition:
        return cls.__columns__[name]

    def __or__(cls, other: "SchemaMetaclass") -> "SchemaMetaclass":
        cols = dict(cls.__columns__)
        cols.update(other.__columns__)
        return schema_builder(cols, name=f"{cls.__name__}|{other.__name__}")

    def with_types(cls, **kwargs) -> "SchemaMetaclass":
        cols = {n: ColumnDefinition(**vars(c)) for n, c in cls.__columns__.items()}
        for name, t in kwargs.items():
            if name not in cols:
                raise ValueError(f"unknown column {name}")
            cols[name].dtype = dt.wrap(t)
        return schema_builder(cols, name=cls.__name__)

    def without(cls, *names) -> "SchemaMetaclass":
        names = {n if isinstance(n, str) else n.name for n in names}
        cols = {n: c for n, c in cls.__columns__.items() if n not in names}
        return schema_builder(cols, name=cls.__name__)

    def update_properties(cls, **kwargs) -> "SchemaMetaclass":
        return cls

    def __repr__(cls):
        fields = ", ".join(f"{n}: {c.dtype!r}" for n, c in cls.__columns__.items())
        return f"<pathway.Schema types={{{fields}}}>"


class Schema(metaclass=SchemaMetaclass):
    """Base class for user-declared schemas."""


def schema_from_types(_name: str = "Schema", **kwargs) -> type[Schema]:
    cols = {
        name: ColumnDefinition(dtype=dt.wrap(t), name=name) for name, t in kwargs.items()
    }
    return schema_builder(cols, name=_name)


def schema_from_dict(
    columns: Mapping[str, Any], *, name: str = "Schema"
) -> type[Schema]:
    cols = {}
    for cname, spec in columns.items():
        if isinstance(spec, ColumnDefinition):
            spec.name = spec.name or cname
            cols[cname] = spec
        elif isinstance(spec, dict):
            cols[cname] = ColumnDefinition(
                dtype=dt.wrap(spec.get("dtype", dt.ANY)),
                primary_key=spec.get("primary_key", False),
                default_value=spec.get("default_value", _NO_DEFAULT),
                name=cname,
            )
        else:
            cols[cname] = ColumnDefinition(dtype=dt.wrap(spec), name=cname)
    return schema_builder(cols, name=name)


def schema_builder(
    columns: Mapping[str, ColumnDefinition], *, name: str = "custom_schema", properties=None
) -> type[Schema]:
    cls = SchemaMetaclass(name, (Schema,), {})
    cls.__columns__ = dict(columns)
    return cls


def schema_from_pandas(df, *, id_from=None, name: str = "schema_from_pandas") -> type[Schema]:
    import numpy as np

    cols = {}
    for cname in df.columns:
        series = df[cname]
        # extension dtypes (pandas StringDtype/Int64 etc.) are not numpy
        # dtypes and crash np.issubdtype — use the dtype kind; nullable
        # EXTENSION columns (Int64 carrying pd.NA) fall to value inference
        # so they type as Optional (numpy float NaN stays plain FLOAT)
        kind = getattr(series.dtype, "kind", None)
        is_ext = not isinstance(series.dtype, np.dtype)
        ext_na = is_ext and len(series) and bool(series.isna().any())
        if kind in ("i", "u") and not ext_na:
            t: Any = dt.INT
        elif kind == "f" and not ext_na:
            t = dt.FLOAT
        elif kind == "b" and not ext_na:
            t = dt.BOOL
        else:
            t = dt.lub(*(dt.dtype_of_value(v) for v in series)) if len(series) else dt.ANY
        cols[cname] = ColumnDefinition(
            dtype=t, name=cname, primary_key=bool(id_from and cname in id_from)
        )
    return schema_builder(cols, name=name)
