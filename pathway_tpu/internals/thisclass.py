"""``pw.this`` / ``pw.left`` / ``pw.right`` deferred references
(reference: python/pathway/internals/thisclass.py:313) and the desugaring
rewriter (reference: internals/desugaring.py).
"""

from __future__ import annotations

import copy
from typing import Any, Callable

from pathway_tpu.internals.expression import ColumnExpression


class ThisColumnReference(ColumnExpression):
    def __init__(self, owner: "ThisClass", name: str):
        super().__init__()
        self._owner = owner
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    def __repr__(self):
        return f"{self._owner._repr}.{self._name}"

    def _subexpressions(self):
        return ()


class ThisClass:
    _expelled = ("_repr",)

    def __init__(self, repr_name: str):
        self._repr = repr_name

    def __getattr__(self, name: str) -> ThisColumnReference:
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        return ThisColumnReference(self, name)

    def __getitem__(self, arg):
        if isinstance(arg, (list, tuple)):
            return [self[a] for a in arg]
        if isinstance(arg, str):
            return ThisColumnReference(self, arg)
        if isinstance(arg, ThisColumnReference):
            return arg
        from pathway_tpu.internals.expression import ColumnReference

        if isinstance(arg, ColumnReference):
            return ThisColumnReference(self, arg.name)
        raise TypeError(f"cannot index pw.this with {arg!r}")

    @property
    def id(self) -> ThisColumnReference:
        return ThisColumnReference(self, "id")

    def without(self, *columns):
        names = frozenset(
            c if isinstance(c, str) else c.name for c in columns
        )
        return _ThisWithout(self, names)

    def __iter__(self):
        raise TypeError(f"{self._repr} is not iterable at declaration time")


class _ThisWithout:
    """Marker for ``pw.this.without(cols)`` used in select(*args)."""

    def __init__(self, owner: ThisClass, excluded: frozenset[str]):
        self._owner = owner
        self._excluded = excluded


this = ThisClass("<this>")
left = ThisClass("<left>")
right = ThisClass("<right>")


def rewrite(e: Any, fn: Callable[[ColumnExpression], ColumnExpression | None]) -> Any:
    """Rebuild an expression tree applying `fn`; fn returns replacement or None."""
    if not isinstance(e, ColumnExpression):
        return e
    replaced = fn(e)
    if replaced is not None:
        return replaced
    new = copy.copy(e)
    for attr, value in vars(e).items():
        if isinstance(value, ColumnExpression):
            setattr(new, attr, rewrite(value, fn))
        elif isinstance(value, tuple) and any(
            isinstance(v, ColumnExpression) for v in value
        ):
            setattr(new, attr, tuple(rewrite(v, fn) for v in value))
        elif isinstance(value, dict) and any(
            isinstance(v, ColumnExpression) for v in value.values()
        ):
            setattr(new, attr, {k: rewrite(v, fn) for k, v in value.items()})
    # rebinding children can sharpen their dtypes (pw.this.x is ANY until
    # the table context resolves it): recompute inferable result dtypes so
    # int+int comes out INT post-desugar, matching reference inference
    from pathway_tpu.internals import expression as _expr

    if isinstance(new, _expr.ColumnBinaryOpExpression):
        new._dtype = _expr._binary_dtype(
            new._symbol, new._left._dtype, new._right._dtype
        )
    elif isinstance(new, _expr.ColumnUnaryOpExpression):
        new._dtype = (
            _expr.dt.BOOL if new._symbol == "~" else new._expr._dtype
        )
    return new


def desugar(e: Any, this_table=None, left_table=None, right_table=None) -> Any:
    """Replace pw.this/left/right deferred refs with concrete column refs."""

    def fn(x: ColumnExpression):
        if isinstance(x, ThisColumnReference):
            if x._owner is this:
                if this_table is None:
                    raise ValueError("pw.this used without a table context")
                return this_table._resolve_deferred(x._name)
            if x._owner is left:
                if left_table is None:
                    raise ValueError("pw.left used outside of a join")
                return left_table._resolve_deferred(x._name)
            if x._owner is right:
                if right_table is None:
                    raise ValueError("pw.right used outside of a join")
                return right_table._resolve_deferred(x._name)
        return None

    return rewrite(e, fn)
