"""Host-plane memory accountant + degradation ladder (ISSUE 19).

Nothing in the engine used to bound memory under overload: connectors
ingest as fast as they can read and the only pushback in the data plane
(``io/_connector.py`` ``_BACKLOG_CAP``) silently *weakens delivery
semantics* instead of slowing down. This module is the governed
alternative: per-component byte accounting — connector backlog, exchange
send/recv queues, native-store state (``exec.cpp store_nbytes`` /
``join_store_nbytes`` GIL-free probes), capture pending, txn staging —
summed against a budget (``PATHWAY_MEM_BUDGET_MB`` with
``PATHWAY_MEM_HIGH`` / ``PATHWAY_MEM_LOW`` watermarks) and stepped
through the pure
degradation ladder ``parallel/protocol.py mem_ladder``:

    ok -> pacing (pausable sources stop reading)
       -> brownout (serving sheds; breaker consumes the memory signal)
       -> abort (epoch abort — the last resort, sticky until restore)

The accountant owns NO policy: every verdict comes from the protocol
transitions it binds from ``protocol.TRANSITIONS`` (same objects the
pacing model checker ``analysis/meshcheck.py check_pacing`` explores —
the anti-drift identity pin in ``tests/test_backpressure.py``). The
runtime's connector-health pass calls :meth:`MemoryAccountant.sample`
once per cadence; everything else just reports bytes into it.

``sample()`` is a ``mem.pressure`` fault point with a twist: a firing
``raise`` rule is CAUGHT here and read as a synthetic over-high-
watermark sample, so pressure episodes — including the minimal traces
the pacing checker renders for a caught mutant — replay
deterministically through the standard ``PATHWAY_FAULT_PLAN``
machinery (``scripts/fault_matrix.py --pressure`` / ``--from-trace``).

With the budget unset the ladder never leaves ``"ok"`` and every legacy
behavior (including the ``_BACKLOG_CAP`` at-least-once overflow path)
is preserved bit-for-bit.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Mapping

from pathway_tpu.parallel import protocol as _protocol
from pathway_tpu.internals.faults import InjectedFault, fault_point

# Accounted component names, fixed so the OpenMetrics gauge set (and the
# metrics-registry drift pin) cannot wander: every ``set_component`` call
# must name one of these.
COMPONENTS = (
    "connector_backlog",   # io/_connector.py unjournaled ledger + pending
    "exchange_send",       # parallel/procgroup.py per-peer send queues
    "exchange_recv",       # parallel/procgroup.py reassembled recv frames
    "store",               # native GroupStore/JoinStore bytes (exec.cpp)
    "capture_pending",     # operator-snapshot capture staging
    "txn_staging",         # io/txn.py staged egress units
)


def resolve_watermarks(
    environ: Mapping[str, str] | None = None,
) -> tuple[int, int, int]:
    """``(low_bytes, high_bytes, budget_bytes)`` from the memory
    knobs; ``(0, 0, 0)`` when governance is disabled (budget unset, 0,
    or unparseable). A low fraction above the high one is clamped down
    to it — an inverted hysteresis band would flap forever."""
    env = os.environ if environ is None else environ
    raw = (env.get("PATHWAY_MEM_BUDGET_MB") or "").strip()
    try:
        budget_mb = int(raw) if raw else 0
    except ValueError:
        budget_mb = 0
    if budget_mb <= 0:
        return (0, 0, 0)
    budget = budget_mb * 1024 * 1024

    def _frac(name: str, default: float) -> float:
        try:
            return float((env.get(name) or "").strip() or default)
        except ValueError:
            return default

    high = _frac("PATHWAY_MEM_HIGH", 0.8)
    low = min(_frac("PATHWAY_MEM_LOW", 0.6), high)
    return (int(budget * low), int(budget * high), budget)


def approx_nbytes(obj: object, _depth: int = 3) -> int:
    """Cheap recursive payload-size estimate for accounting (NOT a
    precise heap measure): container ``sys.getsizeof`` plus element
    sizes down to a small depth. Used for connector rows and capture
    payloads where exact native sizes don't exist; the native stores
    report exact bytes through their own probes instead."""
    try:
        n = sys.getsizeof(obj)
    except TypeError:
        return 64
    if _depth <= 0:
        return n
    if isinstance(obj, (tuple, list, set, frozenset)):
        for item in obj:
            n += approx_nbytes(item, _depth - 1)
    elif isinstance(obj, dict):
        for k, v in obj.items():
            n += approx_nbytes(k, _depth - 1)
            n += approx_nbytes(v, _depth - 1)
    return n


class MemoryAccountant:
    """Byte registry + cached ladder state for ONE runtime.

    Thread-safe: reporters (connector driver threads, exchange pumps)
    call :meth:`set_component` concurrently with the runtime loop's
    :meth:`sample`. Reads of :attr:`state` are a plain attribute load —
    cheap enough for per-request serving checks."""

    def __init__(
        self,
        environ: Mapping[str, str] | None = None,
        abort_streak: int = 4,
    ):
        self.low_bytes, self.high_bytes, self.budget_bytes = (
            resolve_watermarks(environ)
        )
        self.abort_streak = abort_streak
        self._lock = threading.Lock()
        self._components: dict[str, int] = {}
        # the protocol transitions, bound from the table so the engine
        # provably drives the same objects the checker explores
        self._ladder = _protocol.TRANSITIONS["mem_ladder"]
        self._pace_decide = _protocol.TRANSITIONS["pace_decide"]
        self._pace_resume = _protocol.TRANSITIONS["pace_resume"]
        self.state = "ok"
        self.total_bytes = 0
        self.peak_bytes = 0
        self.over_streak = 0
        self.samples = 0
        self.pressure_injections = 0

    @property
    def enabled(self) -> bool:
        return self.budget_bytes > 0

    def set_component(self, name: str, nbytes: int) -> None:
        if name not in COMPONENTS:
            raise ValueError(
                f"unknown memory component {name!r} (not in COMPONENTS)"
            )
        with self._lock:
            self._components[name] = max(0, int(nbytes))

    def components(self) -> dict[str, int]:
        with self._lock:
            return dict(self._components)

    def total(self) -> int:
        with self._lock:
            return sum(self._components.values())

    def sample(self) -> str:
        """One accounting sample: sum components, step the ladder, cache
        the verdict. The ``mem.pressure`` fault point fires here (phase
        ``sample``); a ``raise`` rule is caught and read as a synthetic
        at-high-watermark sample, a ``crash`` rule kills the rank as
        usual."""
        synthetic = False
        try:
            fault_point("mem.pressure", phase="sample")
        except InjectedFault:
            synthetic = True
        with self._lock:
            total = sum(self._components.values())
            if synthetic and self.enabled:
                self.pressure_injections += 1
                total = max(total, self.high_bytes)
            prev = self.state
            state = self._ladder(
                total,
                self.low_bytes,
                self.high_bytes,
                self.budget_bytes,
                prev=prev,
                over_streak=self.over_streak,
                abort_streak=self.abort_streak,
            )
            if self.enabled and total >= self.budget_bytes:
                self.over_streak += 1
            else:
                self.over_streak = 0
            self.total_bytes = total
            self.peak_bytes = max(self.peak_bytes, total)
            self.state = state
            self.samples += 1
            return state

    def reset(self) -> None:
        """Post-restore reset: a rolled-back epoch starts over with a
        fresh ladder (this is the ONLY exit from the sticky ``abort``
        rung) — the restored components re-report their real sizes on
        the next cadence."""
        with self._lock:
            self._components.clear()
            self.state = "ok"
            self.total_bytes = 0
            self.over_streak = 0


# -- the process-current accountant -----------------------------------------
# One runtime owns one accountant; the serving gateway and the exchange
# layer reach it through this slot rather than threading a handle through
# every constructor. ``None`` (no runtime, or governance never installed)
# reads as "disabled" everywhere.

_current: MemoryAccountant | None = None
_current_lock = threading.Lock()


def install(acct: MemoryAccountant | None) -> None:
    global _current
    with _current_lock:
        _current = acct


def current() -> MemoryAccountant | None:
    return _current


def ladder_state() -> str:
    """The cached ladder verdict, ``"ok"`` when no accountant is
    installed — the cheap read serving admission uses per request."""
    acct = _current
    return acct.state if acct is not None else "ok"
