"""YAML template loader (reference:
python/pathway/internals/yaml_loader.py — AI-pipeline templates
instantiate python objects from YAML via `!pw....` class tags and
`$variable` references; docs/2.developers/6.ai-pipelines/40.configure-yaml.md).
"""

from __future__ import annotations

import builtins
import importlib
import os
import re
from typing import Any, IO

import yaml

_VAR_RE = re.compile(r"^\$([A-Za-z_][A-Za-z0-9_]*)$")


def import_object(path: str) -> Any:
    """'pw.xpacks.llm.llms.OpenAIChat' or 'module:attr.path' -> object."""
    if path.startswith("pw.") or path.startswith("pw:"):
        path = "pathway_tpu" + path.removeprefix("pw")
    module_path, colon, attribute_path = path.partition(":")
    attributes = attribute_path.split(".") if attribute_path else []
    module: Any = builtins
    if not colon:
        names = module_path.split(".")
        for index in range(len(names), 0, -1):
            prefix = ".".join(names[:index])
            try:
                module = importlib.import_module(prefix)
                attributes = names[index:]
                break
            except ImportError:
                continue
        else:
            raise ImportError(f"cannot import {path!r}")
    else:
        module = importlib.import_module(module_path)
    obj = module
    for attr in attributes:
        obj = getattr(obj, attr)
    return obj


class _Tagged:
    def __init__(self, path: str, value: Any):
        self.path = path
        self.value = value


class _Loader(yaml.SafeLoader):
    pass


def _multi_constructor(loader: _Loader, tag_suffix: str, node):
    if isinstance(node, yaml.MappingNode):
        value = loader.construct_mapping(node, deep=True)
    elif isinstance(node, yaml.SequenceNode):
        value = loader.construct_sequence(node, deep=True)
    else:
        value = loader.construct_scalar(node)
        if value == "":
            value = None
    return _Tagged(tag_suffix, value)


_Loader.add_multi_constructor("!", _multi_constructor)


def _resolve(value: Any, variables: dict[str, Any]) -> Any:
    if isinstance(value, _Tagged):
        obj = import_object(value.path)
        inner = _resolve(value.value, variables)
        if inner is None:
            return obj() if callable(obj) else obj
        if isinstance(inner, dict):
            return obj(**inner)
        if isinstance(inner, list):
            return obj(*inner)
        return obj(inner)
    if isinstance(value, dict):
        return {k: _resolve(v, variables) for k, v in value.items()}
    if isinstance(value, list):
        return [_resolve(v, variables) for v in value]
    if isinstance(value, str):
        m = _VAR_RE.match(value)
        if m:
            name = m.group(1)
            if name in variables:
                return variables[name]
            if name in os.environ:
                return os.environ[name]
            raise KeyError(f"undefined template variable ${name}")
    return value


def resolve_config_path(path: str, config_path: str) -> str:
    """Resolve a path from a template config relative to the config
    file's own directory (shared by the example apps)."""
    if os.path.isabs(path):
        return path
    return os.path.join(
        os.path.dirname(os.path.abspath(config_path)), path
    )


def load_yaml(stream: str | IO) -> Any:
    """Parse a template: `$name:` top-level keys define variables (resolved
    in order); `!dotted.path` tags instantiate objects with the nested
    mapping as kwargs."""
    raw = yaml.load(stream, Loader=_Loader)
    if not isinstance(raw, dict):
        return _resolve(raw, {})
    variables: dict[str, Any] = {}
    out: dict[str, Any] = {}
    for key, value in raw.items():
        m = _VAR_RE.match(str(key))
        if m:
            variables[m.group(1)] = _resolve(value, variables)
        else:
            out[key] = _resolve(value, variables)
    return out
