"""Live-REPL mode (reference: python/pathway/internals/interactive.py:222
— ``pw.enable_interactive_mode`` keeps a background run alive and lets the
REPL inspect LIVE tables, including tables first looked at AFTER the run
started; the reference does this by exporting every worker's tables and
re-subscribing on demand, and its LiveTable is itself a Table other
programs can import and build on).

Re-subscription model here (VERDICT r4 #9): live handles resolve their
recorder by a STABLE KEY (explicit ``name=`` or the table's name +
column signature), not by table object identity — so after the REPL
edits the program and reruns (``pw.interactive.wait()`` /
``pw.interactive.reset()`` + rebuild + ``pw.run()``), the SAME handle
attaches to the updated table in the new run. ``handle.to_table()``
materializes the current live snapshot as a source in the CURRENT
program, the snapshot-level analog of the reference's LiveTable import:
derived pipelines build on live state captured from a previous run.
"""

from __future__ import annotations

import threading
import time
from typing import Any

_state: dict[str, Any] = {"enabled": False, "thread": None, "started": False}
# id(table) -> _Recorder for the CURRENT program (the engine graph is
# fixed at run time, so post-start inspection works by recording every
# reachable table up front — the reference's export-everything move)
_recorders: dict[int, "_Recorder"] = {}
# stable key -> _Recorder, refreshed each start(): the re-subscription
# registry that lets handles outlive a rerun
_by_key: dict[Any, "_Recorder"] = {}
_lock = threading.Lock()


def _table_key(table, name: str | None = None):
    # auto keys use the column signature (table _names are fresh per
    # program, so they can't survive a rerun); two same-signature tables
    # shadow each other — pin ``name=`` for precise identity
    if name is not None:
        return ("named", name)
    return ("auto", tuple(table.column_names()))


class _Recorder:
    def __init__(self, table):
        self.table = table
        self.rows: dict = {}
        self.frontier = 0  # latest engine time seen
        self.done = False
        self.lock = threading.Lock()
        import pathway_tpu as pw

        def on_change(key, row, time_, is_addition):
            with self.lock:
                self.frontier = max(self.frontier, time_)
                if is_addition:
                    self.rows[key] = row
                else:
                    self.rows.pop(key, None)

        def on_end():
            with self.lock:
                self.done = True

        pw.io.subscribe(self.table, on_change=on_change, on_end=on_end)


class LiveTableHandle:
    """Snapshot accessor over a live table (refreshed by the background
    run). Handles survive reruns: they re-resolve their recorder by
    stable key, so after the program is rebuilt and rerun the same
    handle shows the updated table."""

    def __init__(self, key):
        self._key = key

    @property
    def _rec(self) -> _Recorder:
        rec = _by_key.get(self._key)
        if rec is None:
            raise RuntimeError(
                f"no live table registered under {self._key!r} in the "
                "current program"
            )
        return rec

    @property
    def table(self):
        return self._rec.table

    def snapshot(self) -> list[dict]:
        rec = self._rec
        with rec.lock:
            return list(rec.rows.values())

    def frontier(self) -> int:
        """Latest engine timestamp this view has seen (reference:
        ExportedTable.frontier)."""
        rec = self._rec
        with rec.lock:
            return rec.frontier

    def done(self) -> bool:
        rec = self._rec
        with rec.lock:
            return rec.done

    def to_table(self):
        """Materialize the CURRENT snapshot as a static table in the
        current program — the snapshot-level analog of the reference's
        LiveTable import (ImportDataSource, interactive.py:142): derived
        pipelines build on live state from a previous or running run."""
        import pathway_tpu as pw

        rec = self._rec
        schema = rec.table.schema
        cols = rec.table.column_names()
        with rec.lock:
            rows = [
                (key,) + tuple(row.get(c) for c in cols)
                for key, row in rec.rows.items()
            ]
        return pw.debug.table_from_rows(schema, rows)

    def __repr__(self):
        cols = self.table.column_names()
        lines = [" | ".join(cols)] + [
            " | ".join(str(row.get(c)) for c in cols)
            for row in self.snapshot()
        ]
        return "\n".join(lines)


def interactive_mode_enabled() -> bool:
    return bool(_state["enabled"])


def enable_interactive_mode() -> None:
    """pw.run() will start on a background daemon thread, leaving the REPL
    responsive; inspect tables via pw.live(table) handles — before OR
    after the run has started."""
    _state["enabled"] = True


def live(table, name: str | None = None) -> LiveTableHandle:
    """Live view of a table. Before the run: registers a recorder. After
    the run started: attaches to the recorder pre-registered for every
    reachable table at launch. ``name=`` pins a stable identity so the
    handle re-attaches to the same logical table across reruns."""
    key = _table_key(table, name)
    with _lock:
        rec = _recorders.get(id(table))
        if rec is None:
            if _state["started"]:
                raise RuntimeError(
                    "this table was not reachable when the interactive run "
                    "started; build it before pw.run() (the dataflow graph "
                    "is fixed at launch)"
                )
            rec = _recorders[id(table)] = _Recorder(table)
        _by_key[key] = rec
    return LiveTableHandle(key)


def wait(timeout: float | None = None) -> None:
    """Block until the background run finishes (its sources exhaust).
    After this, the REPL may rebuild the program (pw.interactive.reset())
    and pw.run() again — existing live handles re-attach."""
    t = _state.get("thread")
    if t is not None:
        t.join(timeout)


def reset() -> None:
    """Clear the captured program so the REPL can build a fresh one.
    Recorders for the finished run stay resolvable (handles keep serving
    the last snapshot) until the next start() re-registers their keys."""
    from pathway_tpu.internals.parse_graph import G

    wait(timeout=30)
    t = _state.get("thread")
    if t is not None and t.is_alive():
        raise RuntimeError(
            "the interactive run is still active (its sources have not "
            "finished); wait for it to drain before reset()"
        )
    _state["started"] = False
    _state["thread"] = None
    with _lock:
        _recorders.clear()
    G.clear()


def start(**run_kwargs) -> threading.Thread:
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G

    if _state["started"]:
        raise RuntimeError(
            "an interactive run is already active; pw.interactive.reset() "
            "(or wait()) before rerunning"
        )

    # record every table in the graph so the REPL can open live views
    # after the run is already streaming (reference: export_callback per
    # worker table, interactive.py LiveTableState); re-register stable
    # keys so handles from a previous run re-attach to the new tables
    with _lock:
        for op in list(G.operators):
            for t in getattr(op, "outputs", []):
                if id(t) not in _recorders and hasattr(t, "column_names"):
                    try:
                        rec = _Recorder(t)
                    except Exception:
                        continue  # non-subscribable artifacts stay dark
                    _recorders[id(t)] = rec
                    _by_key[_table_key(t)] = rec

    t = threading.Thread(
        target=lambda: pw.run(_interactive_bypass=True, **run_kwargs),
        daemon=True,
    )
    t.start()
    _state["thread"] = t
    _state["started"] = True
    time.sleep(0.2)
    return t
