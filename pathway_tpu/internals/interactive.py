"""Experimental live-REPL mode (reference:
python/pathway/internals/interactive.py:222 — `pw.enable_interactive_mode`
keeps a background run alive and lets the REPL inspect live tables)."""

from __future__ import annotations

import threading
import time
from typing import Any

_state: dict[str, Any] = {"enabled": False, "thread": None}


class LiveTableHandle:
    """Snapshot accessor over a live table (refreshed by the background
    run). pw.io.subscribe delivers rows as {column: value} dicts."""

    def __init__(self, table):
        self.table = table
        self._rows: dict = {}
        self._lock = threading.Lock()
        import pathway_tpu as pw

        def on_change(key, row, time_, is_addition):
            with self._lock:
                if is_addition:
                    self._rows[key] = row
                else:
                    self._rows.pop(key, None)

        pw.io.subscribe(self.table, on_change=on_change)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._rows.values())

    def __repr__(self):
        cols = self.table.column_names()
        lines = [" | ".join(cols)] + [
            " | ".join(str(row.get(c)) for c in cols)
            for row in self.snapshot()
        ]
        return "\n".join(lines)


def interactive_mode_enabled() -> bool:
    return bool(_state["enabled"])


def enable_interactive_mode() -> None:
    """pw.run() will start on a background daemon thread, leaving the REPL
    responsive; inspect tables via pw.live(table) handles."""
    _state["enabled"] = True


def live(table) -> LiveTableHandle:
    """Register a live view; call BEFORE pw.run()."""
    return LiveTableHandle(table)


def start(**run_kwargs) -> threading.Thread:
    import pathway_tpu as pw

    t = threading.Thread(
        target=lambda: pw.run(_interactive_bypass=True, **run_kwargs),
        daemon=True,
    )
    t.start()
    _state["thread"] = t
    time.sleep(0.2)
    return t
