"""Live-REPL mode (reference: python/pathway/internals/interactive.py:222
— ``pw.enable_interactive_mode`` keeps a background run alive and lets the
REPL inspect LIVE tables, including tables first looked at AFTER the run
started; the reference does this by exporting every worker's tables and
re-subscribing on demand)."""

from __future__ import annotations

import threading
import time
from typing import Any

_state: dict[str, Any] = {"enabled": False, "thread": None, "started": False}
# id(table) -> _Recorder attached before the run launched (the engine
# graph is fixed at run time, so post-start inspection works by recording
# every reachable table up front — the reference's export-everything move)
_recorders: dict[int, "_Recorder"] = {}


class _Recorder:
    def __init__(self, table):
        self.table = table
        self.rows: dict = {}
        self.lock = threading.Lock()
        import pathway_tpu as pw

        def on_change(key, row, time_, is_addition):
            with self.lock:
                if is_addition:
                    self.rows[key] = row
                else:
                    self.rows.pop(key, None)

        pw.io.subscribe(self.table, on_change=on_change)


class LiveTableHandle:
    """Snapshot accessor over a live table (refreshed by the background
    run)."""

    def __init__(self, recorder: _Recorder):
        self._rec = recorder
        self.table = recorder.table

    def snapshot(self) -> list[dict]:
        with self._rec.lock:
            return list(self._rec.rows.values())

    def __repr__(self):
        cols = self.table.column_names()
        lines = [" | ".join(cols)] + [
            " | ".join(str(row.get(c)) for c in cols)
            for row in self.snapshot()
        ]
        return "\n".join(lines)


def interactive_mode_enabled() -> bool:
    return bool(_state["enabled"])


def enable_interactive_mode() -> None:
    """pw.run() will start on a background daemon thread, leaving the REPL
    responsive; inspect tables via pw.live(table) handles — before OR
    after the run has started."""
    _state["enabled"] = True


def live(table) -> LiveTableHandle:
    """Live view of a table. Before the run: registers a recorder. After
    the run started: attaches to the recorder pre-registered for every
    reachable table at launch."""
    rec = _recorders.get(id(table))
    if rec is None:
        if _state["started"]:
            raise RuntimeError(
                "this table was not reachable when the interactive run "
                "started; build it before pw.run() (the dataflow graph "
                "is fixed at launch)"
            )
        rec = _recorders[id(table)] = _Recorder(table)
    return LiveTableHandle(rec)


def start(**run_kwargs) -> threading.Thread:
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G

    # record every table in the graph so the REPL can open live views
    # after the run is already streaming (reference: export_callback per
    # worker table, interactive.py LiveTableState)
    for op in list(G.operators):
        for t in getattr(op, "outputs", []):
            if id(t) not in _recorders and hasattr(t, "column_names"):
                try:
                    _recorders[id(t)] = _Recorder(t)
                except Exception:
                    continue  # non-subscribable artifacts stay uninstrumented

    t = threading.Thread(
        target=lambda: pw.run(_interactive_bypass=True, **run_kwargs),
        daemon=True,
    )
    t.start()
    _state["thread"] = t
    _state["started"] = True
    time.sleep(0.2)
    return t
