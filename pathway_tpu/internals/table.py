"""The Table DSL (reference: python/pathway/internals/table.py:52, 2,675 LoC).

Every method is declarative: it appends an Operator to the global ParseGraph
``G`` with a ``lower_fn`` that knows how to build the corresponding engine
nodes.  Rows live as schema-ordered tuples in the engine; ids are 128-bit
Pointers.
"""

from __future__ import annotations

import itertools
from typing import Any, Iterable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.api import Pointer, ref_scalar
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    PointerExpression,
)
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema, schema_from_types
from pathway_tpu.internals.universe import SOLVER, Universe

_table_counter = itertools.count()


class TableLike:
    _universe: Universe


class Table(TableLike):
    def __init__(
        self,
        schema: type[Schema],
        universe: Universe | None = None,
        name: str | None = None,
    ):
        self._schema_cls = schema
        self._universe = universe if universe is not None else Universe()
        self._name = name or f"table_{next(_table_counter)}"
        self._column_names: list[str] = list(schema.column_names())
        self._source = None  # producing Operator
        self._id_dtype = dt.POINTER

    # -- basic introspection ----------------------------------------------
    @property
    def schema(self) -> type[Schema]:
        return self._schema_cls

    def column_names(self) -> list[str]:
        return list(self._column_names)

    def keys(self):
        return list(self._column_names)

    def typehints(self):
        return self._schema_cls.typehints()

    @property
    def id(self) -> ColumnReference:
        return ColumnReference(table=self, name="id")

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self.__dict__.get("_column_names", ()):
            raise AttributeError(
                f"Table has no column {name!r}; columns: {self._column_names}"
            )
        return ColumnReference(table=self, name=name)

    def __getitem__(self, args):
        if isinstance(args, (list, tuple)):
            return self.select(*[self[a] for a in args])
        if isinstance(args, str):
            if args == "id":
                return self.id
            if args not in self._column_names:
                raise KeyError(args)
            return ColumnReference(table=self, name=args)
        if isinstance(args, thisclass.ThisColumnReference):
            return self[args.name]
        if isinstance(args, ColumnReference):
            return self[args.name]
        raise TypeError(f"cannot index Table with {args!r}")

    def __iter__(self):
        return iter([self[name] for name in self._column_names])

    def _resolve_deferred(self, name: str) -> ColumnReference:
        if name == "id":
            return self.id
        return self[name]

    def __repr__(self):
        return f"<pathway.Table {self._name} schema={self._schema_cls!r}>"

    def _ipython_key_completions_(self):
        return list(self._column_names)

    # -- helpers -----------------------------------------------------------
    def _desugar(self, e: Any) -> Any:
        return thisclass.desugar(e, this_table=self)

    def _select_output(
        self, args: tuple, kwargs: dict
    ) -> tuple[list[str], list[ColumnExpression]]:
        names: list[str] = []
        exprs: list[ColumnExpression] = []

        def add(name, e):
            if name in names:
                idx = names.index(name)
                exprs[idx] = e
            else:
                names.append(name)
                exprs.append(e)

        for arg in args:
            if isinstance(arg, thisclass._ThisWithout):
                for cname in self._column_names:
                    if cname not in arg._excluded:
                        add(cname, self[cname])
            elif isinstance(arg, thisclass.ThisClass):
                for cname in self._column_names:
                    add(cname, self[cname])
            elif isinstance(arg, thisclass.ThisColumnReference):
                add(arg.name, self._desugar(arg))
            elif isinstance(arg, ColumnReference):
                add(arg.name, arg)
            else:
                raise ValueError(
                    f"positional select() arguments must be column references, got {arg!r}"
                )
        for name, e in kwargs.items():
            add(name, self._desugar(expr_mod.smart_coerce(e)))
        return names, exprs

    def _output_schema(self, names: list[str], exprs: list[ColumnExpression]):
        return schema_from_types(
            **{n: e._dtype for n, e in zip(names, exprs)}
        )

    def _dep_tables(self, exprs: Iterable[ColumnExpression]) -> list["Table"]:
        """All tables referenced by the expressions (for tree-shaking)."""
        out: list[Table] = [self]
        seen = {id(self)}
        for e in exprs:
            for ref in expr_mod.smart_coerce(e)._deps:
                if id(ref.table) not in seen:
                    seen.add(id(ref.table))
                    out.append(ref.table)
        return out

    # -- projections -------------------------------------------------------
    def select(self, *args, **kwargs) -> "Table":
        names, exprs = self._select_output(args, kwargs)
        out = Table(self._output_schema(names, exprs), self._universe)
        self_ = self

        deterministic = all(e._is_deterministic for e in exprs)

        def lower(ctx):
            inp, fn = ctx.rowwise_eval(self_, exprs)
            ctx.set_engine_table(
                out,
                ctx.scope.rowwise_auto(
                    inp, fn, len(exprs), deterministic, src_exprs=exprs
                ),
            )

        G.add_operator(self._dep_tables(exprs), [out], lower, "select")
        return out

    def with_columns(self, *args, **kwargs) -> "Table":
        all_args = (thisclass.this,) + args
        return self.select(*all_args, **kwargs)

    def __add__(self, other: "Table") -> "Table":
        if not SOLVER.query_are_equal(self._universe, other._universe):
            raise ValueError("can only add tables with the same universe")
        kwargs = {n: other[n] for n in other._column_names}
        return self.select(*self, **kwargs)

    def copy(self) -> "Table":
        return self.select(*self)

    def without(self, *columns) -> "Table":
        excluded = {c if isinstance(c, str) else c.name for c in columns}
        return self.select(
            *[self[c] for c in self._column_names if c not in excluded]
        )

    def rename_columns(self, **kwargs) -> "Table":
        mapping = {}
        for new, old in kwargs.items():
            old_name = old if isinstance(old, str) else old.name
            mapping[old_name] = new
        cols = {}
        for c in self._column_names:
            cols[mapping.get(c, c)] = self[c]
        return self.select(**cols)

    def rename_by_dict(self, names_mapping: dict) -> "Table":
        mapping = {
            (k if isinstance(k, str) else k.name): v for k, v in names_mapping.items()
        }
        cols = {}
        for c in self._column_names:
            cols[mapping.get(c, c)] = self[c]
        return self.select(**cols)

    def rename(self, names_mapping: dict | None = None, **kwargs) -> "Table":
        if names_mapping is not None:
            return self.rename_by_dict(names_mapping)
        return self.rename_columns(**kwargs)

    def with_prefix(self, prefix: str) -> "Table":
        return self.select(**{prefix + c: self[c] for c in self._column_names})

    def with_suffix(self, suffix: str) -> "Table":
        return self.select(**{c + suffix: self[c] for c in self._column_names})

    def update_types(self, **kwargs) -> "Table":
        out = self.select(*self)
        out._schema_cls = out._schema_cls.with_types(**kwargs)
        return out

    def cast_to_types(self, **kwargs) -> "Table":
        cols = {}
        for c in self._column_names:
            if c in kwargs:
                cols[c] = expr_mod.cast(kwargs[c], self[c])
            else:
                cols[c] = self[c]
        return self.select(**cols)

    # -- filtering ---------------------------------------------------------
    def filter(self, filter_expression: ColumnExpression) -> "Table":
        e = self._desugar(expr_mod.smart_coerce(filter_expression))
        out = Table(self._schema_cls, self._universe.subset())
        self_ = self
        width = len(self._column_names)

        def lower(ctx):
            combined, mask_fn = ctx.mask_eval(self_, e)
            filtered = ctx.scope.filter_table(combined, mask_fn)
            if combined.width != width:
                filtered = ctx.scope.rowwise(
                    filtered, lambda keys, rows: [r[:width] for r in rows], width
                )
            ctx.set_engine_table(out, filtered)

        G.add_operator(self._dep_tables([e]), [out], lower, "filter")
        return out

    def split(self, split_expression):
        pos = self.filter(split_expression)
        neg = self.filter(~expr_mod.smart_coerce(self._desugar(split_expression)))
        return pos, neg

    # -- universes ---------------------------------------------------------
    def difference(self, other: "Table") -> "Table":
        out = Table(self._schema_cls, self._universe.subset())
        self_ = self

        def lower(ctx):
            ctx.set_engine_table(
                out,
                ctx.scope.difference(
                    ctx.engine_table(self_), ctx.engine_table(other)
                ),
            )

        G.add_operator([self, other], [out], lower, "difference")
        return out

    def intersect(self, *tables: "Table") -> "Table":
        out = Table(self._schema_cls, self._universe.subset())
        self_ = self

        def lower(ctx):
            ctx.set_engine_table(
                out,
                ctx.scope.intersect(
                    ctx.engine_table(self_), [ctx.engine_table(t) for t in tables]
                ),
            )

        G.add_operator([self, *tables], [out], lower, "intersect")
        return out

    def restrict(self, other: TableLike) -> "Table":
        out = Table(self._schema_cls, other._universe)
        self_ = self

        def lower(ctx):
            ctx.set_engine_table(
                out,
                ctx.scope.intersect(
                    ctx.engine_table(self_), [ctx.engine_table(other)]
                ),
            )

        G.add_operator([self, other], [out], lower, "restrict")
        return out

    def _having(self, indexer: ColumnReference) -> "Table":
        keys_table = indexer.table
        out = Table(self._schema_cls, self._universe.subset())
        self_ = self
        name = indexer.name

        def lower(ctx):
            # keep rows of self whose id appears as a value of indexer
            keys_et, key_one = ctx.row_fn(keys_table, [indexer])
            projected = ctx.scope.reindex(
                keys_et, lambda k, row, f=key_one: f(k, row)[0]
            )
            ctx.set_engine_table(
                out, ctx.scope.intersect(ctx.engine_table(self_), [projected])
            )

        G.add_operator([self, keys_table], [out], lower, "having")
        return out

    def with_universe_of(self, other: TableLike) -> "Table":
        """Reindex onto ``other``'s key set, with the reference's runtime
        checks (test_errors.py:573): keys of other missing here become
        ERROR rows and keys here missing in other are dropped — both
        logged to the global error log. A valid promise passes through
        unchanged."""
        out = Table(self._schema_cls, other._universe)
        self_ = self

        def lower(ctx):
            ctx.set_engine_table(
                out,
                ctx.scope.reuniverse(
                    ctx.engine_table(self_), ctx.engine_table(other)
                ),
            )

        G.add_operator([self, other], [out], lower, "with_universe_of")
        return out

    def _unsafe_promise_universe(self, other: TableLike) -> "Table":
        """Check-free universe relabel: the caller GUARANTEES the key
        sets match (the reference's unsafe variant). No state, no
        runtime verification — internal callers whose universes are
        equal by construction use this; user code should prefer
        with_universe_of."""
        out = Table(self._schema_cls, other._universe)
        self_ = self

        def lower(ctx):
            ctx.set_engine_table(out, ctx.engine_table(self_))

        G.add_operator([self], [out], lower, "promise_universe")
        return out

    # -- groupby / reduce --------------------------------------------------
    def groupby(self, *args, id=None, instance=None, sort_by=None, **kwargs):
        from pathway_tpu.internals.groupbys import GroupedTable

        if kwargs:
            raise TypeError(
                f"groupby() got unexpected keyword arguments {sorted(kwargs)}"
            )
        if id is not None:
            # reference semantics (table.py groupby id=): group by a Pointer
            # column whose values become the output row ids
            if args:
                raise ValueError("groupby() takes either positional columns or id=")
            grouping = [self._desugar(expr_mod.smart_coerce(id))]
            return GroupedTable(
                self, grouping, sort_by=sort_by, id_from_first_group_col=True
            )
        grouping = [self._desugar(a) for a in args]
        if instance is not None:
            grouping.append(self._desugar(expr_mod.smart_coerce(instance)))
        return GroupedTable(self, grouping, sort_by=sort_by)

    def reduce(self, *args, **kwargs) -> "Table":
        return self.groupby().reduce(*args, **kwargs)

    def deduplicate(
        self,
        *,
        value,
        instance=None,
        acceptor,
        persistent_id=None,
        name=None,
    ) -> "Table":
        value_e = self._desugar(expr_mod.smart_coerce(value))
        instance_e = (
            self._desugar(expr_mod.smart_coerce(instance))
            if instance is not None
            else expr_mod.ColumnConstExpression(None)
        )
        out = Table(self._schema_cls, Universe())
        self_ = self

        def lower(ctx):
            et, vfn = ctx.row_fn(self_, [value_e, instance_e])
            ctx.set_engine_table(
                out,
                ctx.scope.deduplicate(
                    et,
                    instance_fn=lambda k, row: vfn(k, row)[1],
                    value_fn=lambda k, row: vfn(k, row)[0],
                    acceptor=acceptor,
                ),
            )

        G.add_operator(self._dep_tables([value_e, instance_e]), [out], lower, "deduplicate")
        return out

    # -- joins -------------------------------------------------------------
    _ALLOWED_JOIN_KWARGS = {"left_instance", "right_instance", "exact_match"}

    def join(
        self,
        other: "Table",
        *on,
        id=None,
        how="inner",
        left_instance=None,
        right_instance=None,
        exact_match: bool = False,
        **kwargs,
    ):
        from pathway_tpu.internals.joins import JoinResult

        if kwargs:
            raise TypeError(
                f"join() got unexpected keyword arguments {sorted(kwargs)}"
            )
        on = list(on)
        if (left_instance is None) != (right_instance is None):
            raise ValueError(
                "left_instance and right_instance must be given together"
            )
        if left_instance is not None:
            # instance partitioning = an extra equality condition
            on.append(
                self._desugar(expr_mod.smart_coerce(left_instance))
                == other._desugar(expr_mod.smart_coerce(right_instance))
            )
        how_str = how.value if hasattr(how, "value") else str(how)
        return JoinResult(
            self, other, on, id=id, how=how_str, exact_match=exact_match
        )

    def join_inner(self, other, *on, id=None, **kwargs):
        return self.join(other, *on, id=id, how="inner", **kwargs)

    def join_left(self, other, *on, id=None, **kwargs):
        return self.join(other, *on, id=id, how="left", **kwargs)

    def join_right(self, other, *on, id=None, **kwargs):
        return self.join(other, *on, id=id, how="right", **kwargs)

    def join_outer(self, other, *on, id=None, **kwargs):
        return self.join(other, *on, id=id, how="outer", **kwargs)

    # -- asof / temporal entry points (stdlib.temporal wires the real ones) --
    def windowby(self, time_expr, *, window, instance=None, behavior=None, **kwargs):
        from pathway_tpu.stdlib.temporal import windowby as _windowby

        return _windowby(
            self, time_expr, window=window, instance=instance, behavior=behavior
        )

    def interval_join(self, other, self_time, other_time, interval, *on, **kwargs):
        from pathway_tpu.stdlib.temporal import interval_join as _ij

        return _ij(self, other, self_time, other_time, interval, *on, **kwargs)

    def interval_join_inner(self, other, self_time, other_time, interval, *on, **kw):
        return self.interval_join(other, self_time, other_time, interval, *on, how="inner", **kw)

    def interval_join_left(self, other, self_time, other_time, interval, *on, **kw):
        return self.interval_join(other, self_time, other_time, interval, *on, how="left", **kw)

    def interval_join_right(self, other, self_time, other_time, interval, *on, **kw):
        return self.interval_join(other, self_time, other_time, interval, *on, how="right", **kw)

    def interval_join_outer(self, other, self_time, other_time, interval, *on, **kw):
        return self.interval_join(other, self_time, other_time, interval, *on, how="outer", **kw)

    def asof_join(self, other, self_time, other_time, *on, **kwargs):
        from pathway_tpu.stdlib.temporal import asof_join as _aj

        return _aj(self, other, self_time, other_time, *on, **kwargs)

    def asof_join_left(self, other, self_time, other_time, *on, **kw):
        return self.asof_join(other, self_time, other_time, *on, how="left", **kw)

    def asof_join_right(self, other, self_time, other_time, *on, **kw):
        return self.asof_join(other, self_time, other_time, *on, how="right", **kw)

    def asof_join_outer(self, other, self_time, other_time, *on, **kw):
        return self.asof_join(other, self_time, other_time, *on, how="outer", **kw)

    def asof_now_join(self, other, *on, **kwargs):
        from pathway_tpu.stdlib.temporal import asof_now_join as _anj

        return _anj(self, other, *on, **kwargs)

    def window_join(self, other, self_time, other_time, window, *on, **kwargs):
        from pathway_tpu.stdlib.temporal import window_join as _wj

        return _wj(self, other, self_time, other_time, window, *on, **kwargs)

    # -- concat / update ---------------------------------------------------
    def concat(self, *others: "Table") -> "Table":
        out = Table(
            self._schema_cls,
            SOLVER.get_union(self._universe, *[o._universe for o in others]),
        )
        tables = [self, *others]
        col_names = self._column_names

        def lower(ctx):
            ets = []
            for t in tables:
                et = ctx.engine_table(t)
                if t._column_names != col_names:
                    order = [t._column_names.index(c) for c in col_names]
                    et = ctx.scope.rowwise(
                        et,
                        lambda keys, rows, order=order: [
                            tuple(r[i] for i in order) for r in rows
                        ],
                        len(order),
                    )
                ets.append(et)
            ctx.set_engine_table(out, ctx.scope.concat(ets))

        G.add_operator(tables, [out], lower, "concat")
        return out

    def concat_reindex(self, *tables: "Table") -> "Table":
        reindexed = [
            t._reindex_with_salt(i) for i, t in enumerate([self, *tables])
        ]
        return reindexed[0].concat(*reindexed[1:])

    def _reindex_with_salt(self, salt: int) -> "Table":
        out = Table(self._schema_cls, Universe())
        self_ = self

        def lower(ctx):
            ctx.set_engine_table(
                out,
                ctx.scope.reindex(
                    ctx.engine_table(self_),
                    lambda k, row: ref_scalar(k, salt),
                ),
            )

        G.add_operator([self], [out], lower, "reindex_salt")
        return out

    def update_rows(self, other: "Table") -> "Table":
        out = Table(
            self._schema_cls, SOLVER.get_union(self._universe, other._universe)
        )
        self_ = self
        col_names = self._column_names

        def lower(ctx):
            right = ctx.engine_table(other)
            if other._column_names != col_names:
                order = [other._column_names.index(c) for c in col_names]
                right = ctx.scope.rowwise(
                    right,
                    lambda keys, rows, order=order: [
                        tuple(r[i] for i in order) for r in rows
                    ],
                    len(order),
                )
            ctx.set_engine_table(
                out, ctx.scope.update_rows(ctx.engine_table(self_), right)
            )

        G.add_operator([self, other], [out], lower, "update_rows")
        return out

    def __lshift__(self, other: "Table") -> "Table":
        return self.update_cells(other)

    def update_cells(self, other: "Table", _stacklevel: int = 1) -> "Table":
        positions = []
        for c in other._column_names:
            if c not in self._column_names:
                raise ValueError(f"update_cells: unknown column {c!r}")
            positions.append(self._column_names.index(c))
        out = Table(self._schema_cls, self._universe)
        self_ = self

        def lower(ctx):
            ctx.set_engine_table(
                out,
                ctx.scope.update_cells(
                    ctx.engine_table(self_), ctx.engine_table(other), positions
                ),
            )

        G.add_operator([self, other], [out], lower, "update_cells")
        return out

    # -- reindexing --------------------------------------------------------
    def with_id_from(self, *args, instance=None) -> "Table":
        exprs = [self._desugar(expr_mod.smart_coerce(a)) for a in args]
        if instance is not None:
            exprs.append(self._desugar(expr_mod.smart_coerce(instance)))
        out = Table(self._schema_cls, Universe())
        self_ = self
        width = len(self._column_names)

        def lower(ctx):
            et, fn = ctx.row_fn(self_, exprs)
            reindexed = ctx.scope.reindex_checked(
                et, lambda k, row, f=fn: ref_scalar(*f(k, row))
            )
            if reindexed.width != width:
                reindexed = ctx.scope.rowwise(
                    reindexed, lambda keys, rows: [r[:width] for r in rows], width
                )
            ctx.set_engine_table(out, reindexed)

        G.add_operator(self._dep_tables(exprs), [out], lower, "with_id_from")
        return out

    def with_id(self, new_index: ColumnReference) -> "Table":
        return self._with_id_impl(new_index, checked=True)

    def _with_id_unchecked(self, new_index: ColumnReference) -> "Table":
        """Check-free rekey for internal callers whose keys are unique by
        construction (round-tripped row ids): skips CheckedReindexNode's
        per-key row state."""
        return self._with_id_impl(new_index, checked=False)

    def _with_id_impl(self, new_index: ColumnReference, checked: bool) -> "Table":
        e = self._desugar(new_index)
        out = Table(self._schema_cls, Universe())
        self_ = self
        width = len(self._column_names)

        def lower(ctx):
            et, fn = ctx.row_fn(self_, [e])
            rekey = (
                ctx.scope.reindex_checked if checked else ctx.scope.reindex
            )
            reindexed = rekey(et, lambda k, row, f=fn: f(k, row)[0])
            if reindexed.width != width:
                reindexed = ctx.scope.rowwise(
                    reindexed, lambda keys, rows: [r[:width] for r in rows], width
                )
            ctx.set_engine_table(out, reindexed)

        G.add_operator(self._dep_tables([e]), [out], lower, "with_id")
        return out

    # -- pointer ops -------------------------------------------------------
    def pointer_from(self, *args, optional=False, instance=None) -> PointerExpression:
        return PointerExpression(
            self,
            *[self._desugar(expr_mod.smart_coerce(a)) for a in args],
            optional=optional,
            instance=instance,
        )

    def ix(self, expression, *, optional: bool = False, context=None) -> "Table":
        e = expression
        if isinstance(e, thisclass.ThisColumnReference):
            raise ValueError("t.ix(pw.this.col) requires explicit table context")
        keys_table = _origin_table(e)
        out = Table(self._schema_cls, keys_table._universe)
        self_ = self

        def lower(ctx):
            keys_et, fn = ctx.row_fn(keys_table, [e])
            ctx.set_engine_table(
                out,
                ctx.scope.ix(
                    ctx.engine_table(self_),
                    keys_et,
                    key_fn=lambda k, row, f=fn: f(k, row)[0],
                    optional=optional,
                    strict=True,
                ),
            )

        G.add_operator([self, keys_table], [out], lower, "ix")
        return out

    def ix_ref(self, *args, optional: bool = False, context=None, instance=None):
        keys_tables = {
            r.table
            for a in args
            if isinstance(a, ColumnExpression)
            for r in expr_mod.smart_coerce(a)._deps
        }
        if not keys_tables:
            raise ValueError("ix_ref needs at least one column argument")
        keys_table = next(iter(keys_tables))
        return self.ix(
            self.pointer_from(*args, instance=instance)._rebind(keys_table),
            optional=optional,
        )

    # -- structure ---------------------------------------------------------
    def flatten(self, to_flatten: ColumnReference, origin_id: str | None = None) -> "Table":
        e = self._desugar(to_flatten)
        name = e.name
        idx = self._column_names.index(name)
        inner_t = self._schema_cls._dtypes().get(name, dt.ANY)
        if isinstance(inner_t, dt._ListDType):
            elem_t = inner_t.arg
        elif isinstance(inner_t, dt._TupleDType) and inner_t.args:
            elem_t = dt.lub(*inner_t.args)
        elif inner_t is dt.STR:
            elem_t = dt.STR
        else:
            elem_t = dt.ANY
        new_types = dict(self._schema_cls._dtypes())
        new_types[name] = elem_t
        out = Table(schema_from_types(**new_types), Universe())
        self_ = self

        def lower(ctx):
            ctx.set_engine_table(
                out, ctx.scope.flatten(ctx.engine_table(self_), idx)
            )

        G.add_operator([self], [out], lower, "flatten")
        return out

    def _time_gate(self, kind: str, threshold, time_expr) -> "Table":
        threshold_e = self._desugar(expr_mod.smart_coerce(threshold))
        time_e = self._desugar(expr_mod.smart_coerce(time_expr))
        out = Table(self._schema_cls, Universe())
        self_ = self

        def lower(ctx):
            et, fn = ctx.row_fn(self_, [threshold_e, time_e])
            ctx.set_engine_table(out, getattr(ctx.scope, kind)(et, fn))

        G.add_operator(
            self._dep_tables([threshold_e, time_e]), [out], lower, kind
        )
        return out

    def _buffer(self, threshold, time_expr) -> "Table":
        """Hold rows until the operator watermark reaches `threshold`
        (reference: Table._buffer -> time_column.rs postpone_core)."""
        return self._time_gate("buffer", threshold, time_expr)

    def _freeze(self, threshold, time_expr) -> "Table":
        """Ignore updates arriving after `threshold` passed (reference:
        Table._freeze -> TimeColumnFreeze)."""
        return self._time_gate("freeze", threshold, time_expr)

    def _forget(self, threshold, time_expr, mark_forgetting: bool = True) -> "Table":
        """Retract rows once the watermark passes `threshold` (reference:
        Table._forget -> TimeColumnForget)."""
        return self._time_gate("forget", threshold, time_expr)

    def _gradual_broadcast(
        self, threshold_table, lower_column, value_column, upper_column
    ) -> "Table":
        """Append `apx_value` apportioning a slowly-changing threshold
        (reference: table.py:631 -> gradual_broadcast.rs)."""
        exprs = [
            threshold_table._desugar(expr_mod.smart_coerce(c))
            for c in (lower_column, value_column, upper_column)
        ]
        schema_cols = dict(self.schema.typehints())
        schema_cols["apx_value"] = dt.ANY
        out = Table(schema_from_types(**schema_cols), self._universe)
        self_ = self

        def lower(ctx):
            let = ctx.engine_table(self_)
            tet, resolver = ctx._combined_view(threshold_table, exprs)
            from pathway_tpu.engine.expression import compile_expression

            fns = [compile_expression(e, resolver, ctx.runtime) for e in exprs]

            def triplet_fn(k, row):
                return tuple(f([k], [row])[0] for f in fns)

            ctx.set_engine_table(
                out, ctx.scope.gradual_broadcast(let, tet, triplet_fn)
            )

        G.add_operator(
            [self, threshold_table], [out], lower, "gradual_broadcast"
        )
        return out

    def _forget_immediately(self) -> "Table":
        """Rows pass through and are retracted at the next timestamp
        (reference: internals/table.py _forget_immediately — as-of-now
        query plumbing, stdlib/indexing/data_index.py:46-120)."""
        out = Table(self._schema_cls, Universe())
        self_ = self

        def lower(ctx):
            ctx.set_engine_table(
                out, ctx.scope.forget_immediately(ctx.engine_table(self_))
            )

        G.add_operator([self], [out], lower, "forget_immediately")
        return out

    def sort(self, key: ColumnExpression, instance: ColumnExpression | None = None) -> "Table":
        key_e = self._desugar(expr_mod.smart_coerce(key))
        inst_e = (
            self._desugar(expr_mod.smart_coerce(instance))
            if instance is not None
            else expr_mod.ColumnConstExpression(None)
        )
        out = Table(
            schema_from_types(
                prev=dt.Optional(dt.POINTER), next=dt.Optional(dt.POINTER)
            ),
            self._universe,
        )
        self_ = self

        def lower(ctx):
            et, fn = ctx.row_fn(self_, [key_e, inst_e])
            ctx.set_engine_table(
                out,
                ctx.scope.sort(
                    et,
                    key_fn=lambda k, row, f=fn: f(k, row)[0],
                    instance_fn=lambda k, row, f=fn: f(k, row)[1],
                ),
            )

        G.add_operator(self._dep_tables([key_e, inst_e]), [out], lower, "sort")
        return out

    def diff(self, timestamp: ColumnExpression, *values, instance=None) -> "Table":
        from pathway_tpu.stdlib.ordered import diff as _diff

        return _diff(self, timestamp, *values, instance=instance)

    # -- constructors ------------------------------------------------------
    @staticmethod
    def empty(**kwargs) -> "Table":
        schema = schema_from_types(**kwargs)
        out = Table(schema, Universe())

        def lower(ctx):
            ctx.set_engine_table(out, ctx.scope.empty_table(len(kwargs)))

        G.add_operator([], [out], lower, "empty")
        return out

    @staticmethod
    def from_columns(*args, **kwargs) -> "Table":
        all_refs: list[ColumnReference] = []
        names = []
        for a in args:
            all_refs.append(a)
            names.append(a.name)
        for n, a in kwargs.items():
            all_refs.append(a)
            names.append(n)
        if not all_refs:
            raise ValueError("from_columns needs at least one column")
        base = all_refs[0].table
        return base.select(**{n: r for n, r in zip(names, all_refs)})

    # -- misc --------------------------------------------------------------
    def _materialize(self, universe: Universe) -> "Table":
        out = Table(self._schema_cls, universe)
        self_ = self

        def lower(ctx):
            ctx.set_engine_table(out, ctx.engine_table(self_))

        G.add_operator([self], [out], lower, "materialize")
        return out

    @property
    def slice(self):
        return _TableSlice(self)

    @property
    def C(self) -> "_ColumnNamespace":
        """Column accessor namespace (reference: Joinable.C — reach
        columns whose names collide with Table methods: ``t.C.select``).
        Unlike ``slice``, carries no helper methods at all, so even
        columns named ``keys``/``without`` resolve."""
        return _ColumnNamespace(self)

    # -- reference surface conveniences -----------------------------------
    def debug(self, name: str) -> "Table":
        """Print this table's change stream during the run, prefixed with
        ``name`` (reference: Table.debug / DebugOperator)."""
        from pathway_tpu.io import subscribe as _subscribe

        cols = self.column_names()

        def on_change(key, row, time, diff):
            sign = "+" if diff > 0 else "-"
            vals = ", ".join(f"{c}={row.get(c)!r}" for c in cols)
            print(f"[debug:{name}] {sign} {key!r} {vals} @ {time}")

        _subscribe(self, on_change=on_change)
        return self

    def eval_type(self, expression) -> Any:
        """Resolved dtype of ``expression`` against this table (reference:
        Table.eval_type)."""
        return self._desugar(expr_mod.smart_coerce(expression))._dtype

    def live(self, name: str | None = None):
        """Interactive live view of this table (reference: Table.live —
        here a LiveTableHandle; pw.enable_interactive_mode first).
        ``name=`` pins a stable identity so the handle re-attaches to the
        same logical table across REPL reruns."""
        from pathway_tpu.internals.interactive import live as _live

        return _live(self, name=name)

    def remove_errors(self) -> "Table":
        """Drop rows containing ERROR values (method form of
        pw.remove_errors_from_table; reference: Table.remove_errors)."""
        from pathway_tpu.internals.error_log import remove_errors_from_table

        return remove_errors_from_table(self)

    def to(self, sink) -> None:
        """Send this table to a sink (reference: Table.to(DataSink)).
        Accepts any callable sink factory: ``t.to(lambda tb: pw.io.csv.
        write(tb, path))`` or a writer partial."""
        if callable(sink):
            sink(self)
            return
        raise TypeError(
            "Table.to expects a callable sink (e.g. a pw.io.*.write "
            "partial); got " + type(sink).__name__
        )

    def update_id_type(self, id_type, *, id_append_only=None) -> "Table":
        """Annotate the id column's Pointer type (reference:
        Table.update_id_type). Ids here are untyped 128-bit Pointers, so
        this is a schema-level annotation pass-through."""
        return self.copy()


class _ColumnNamespace:
    """Pure column accessor (Table.C): NOTHING but column resolution, so
    columns named like helper methods (``keys``, ``without``) still
    resolve — the collision case C exists to solve."""

    __slots__ = ("_ns_table",)

    def __init__(self, table: Table):
        object.__setattr__(self, "_ns_table", table)

    def __getattr__(self, name):
        try:
            return self._ns_table[name]
        except KeyError:
            # AttributeError keeps hasattr/getattr-with-default protocols
            # (and introspection machinery probing dunders) working
            raise AttributeError(name) from None

    def __getitem__(self, name):
        return self._ns_table[name]


class _TableSlice(_ColumnNamespace):
    def without(self, *cols):
        names = {c if isinstance(c, str) else c.name for c in cols}
        return [
            self._ns_table[c]
            for c in self._ns_table._column_names
            if c not in names
        ]

    def keys(self):
        return self._ns_table.column_names()


def _origin_table(e: ColumnExpression) -> Table:
    tables = {id(r.table): r.table for r in expr_mod.smart_coerce(e)._deps}
    if len(tables) != 1:
        raise ValueError("expression must reference exactly one table")
    return next(iter(tables.values()))


def _rebind_pointer(self: PointerExpression, table: Table) -> PointerExpression:
    self._table = table
    return self


PointerExpression._rebind = _rebind_pointer  # type: ignore[attr-defined]
