"""Device plane of the flight recorder (ISSUE 15).

The host-side observability stack (PRs 8/10: flight recorder, cluster
observatory, critical-path analyzer) goes dark at every JAX dispatch:
an ``ExternalIndexNode`` KNN scan or an embedder forward is one opaque
slab of node self-time, with no way to tell whether a slow node needs a
kernel (device-bound) or needs the host path fixed (device idle while
the host assembles batches). This module is the missing plane: engine
dispatch sites (ops/knn.py, ops/pallas_knn.py, models/encoder.py, the
serving gateway's fused window dispatch) wrap every device launch in a
**timed dispatch record** —

* wall span of the whole dispatch (host assembly + enqueue + wait);
* ``jax.block_until_ready``-bounded device time (enqueue-return to
  results-ready — the device's share of the wall span);
* compiled ``cost_analysis()`` FLOPs / bytes-accessed when obtainable
  (cached per shape key; analytical cost models are the fallback, so a
  backend without cost analysis still produces honest numbers);
* host<->device transfer bytes and the dispatch-queue depth at launch;
* the ENCLOSING ENGINE NODE (runtime step context), so device spans in
  the merged Perfetto trace correlate to their node span by dispatch id.

Records feed three consumers: the flight recorder's new per-rank
**device tracks** (internals/flight.py ``note_dispatch``), the
OpenMetrics ``device_*`` families + ``device_mfu`` /
``device_hbm_{live,peak}_bytes`` gauges (internals/monitoring.py,
aggregated into ``/metrics/cluster``), and the roofline verdicts of
``--profile`` / ``--critical-path`` (analysis/profile.py consumes the
same pure ``roofline_verdict`` below — no drift).

Discipline matches PR 8: armed only while the runtime's profiling plane
is on (``PATHWAY_TRACE`` or a live /metrics endpoint), ONE attribute
check (``PLANE.on``) on every dispatch path when off, and the
``block_until_ready`` sync happens only on armed runs (an armed run
trades dispatch-pipelining for attribution — the documented cost).

This module never imports jax at module scope: the relational plane
(and the ASan/fork CI lanes, where importing jaxlib is fatal) must be
able to load it for free.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time as _time
from typing import Any

# -- peak-rate tables --------------------------------------------------------
# per-device-kind peak dense FLOP/s (bf16 MXU) and HBM bandwidth. Used as
# the MFU denominator and the roofline ridge; PATHWAY_DEVICE_PEAK_FLOPS /
# PATHWAY_DEVICE_PEAK_GBPS override for hardware the table has not met.
# Substring-matched against jax's device_kind, most specific first.
_PEAK_TABLE: tuple[tuple[str, float, float], ...] = (
    # (device_kind substring, peak FLOP/s, peak HBM bytes/s)
    ("v6", 918e12, 1638e9),   # TPU v6e (Trillium)
    ("v5p", 459e12, 2765e9),
    ("v5", 197e12, 819e9),    # v5e / "TPU v5 lite"
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
)
# CPU / unknown backend: a deliberately modest single-chip estimate so
# CPU-lane MFU numbers read as a sanity signal, not hardware truth
_PEAK_FLOPS_FALLBACK = 2e11
_PEAK_BW_FALLBACK = 50e9

_HOST_BOUND_SHARE_DEFAULT = 0.35


def _env_float(name: str) -> float | None:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw.strip() else None
    except ValueError:
        return None


def _env_off(name: str) -> bool:
    return str(os.environ.get(name, "1")).strip().lower() in (
        "0", "false", "no",
    )


def device_kind() -> str:
    """The local device's kind string — only when jax is ALREADY loaded
    (this plane must never be the reason jax imports)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return ""
    try:
        devs = jax.local_devices()
        return str(devs[0].device_kind) if devs else ""
    except Exception:
        return ""


def peak_flops(kind: str | None = None) -> float:
    """MFU denominator: PATHWAY_DEVICE_PEAK_FLOPS, else the device-kind
    table, else the CPU fallback."""
    override = _env_float("PATHWAY_DEVICE_PEAK_FLOPS")
    if override is not None:
        return override
    kind = device_kind() if kind is None else kind
    low = kind.lower()
    for sub, fl, _bw in _PEAK_TABLE:
        if sub in low:
            return fl
    return _PEAK_FLOPS_FALLBACK


def peak_bandwidth(kind: str | None = None) -> float:
    """Roofline ridge denominator (bytes/s): PATHWAY_DEVICE_PEAK_GBPS
    (GB/s), else the device-kind table, else the CPU fallback."""
    override = _env_float("PATHWAY_DEVICE_PEAK_GBPS")
    if override is not None:
        return override * 1e9
    kind = device_kind() if kind is None else kind
    low = kind.lower()
    for sub, _fl, bw in _PEAK_TABLE:
        if sub in low:
            return bw
    return _PEAK_BW_FALLBACK


def host_bound_share() -> float:
    """Device-busy share of a dispatch site's wall below which the site
    reads host-bound (PATHWAY_DEVICE_HOST_BOUND_SHARE)."""
    v = _env_float("PATHWAY_DEVICE_HOST_BOUND_SHARE")
    if v is None or not (0.0 <= v <= 1.0):
        return _HOST_BOUND_SHARE_DEFAULT
    return v


def roofline_verdict(
    wall_s: float,
    device_s: float,
    flops: float,
    bytes_accessed: float,
    pk_flops: float | None = None,
    pk_bw: float | None = None,
    host_share: float | None = None,
) -> str:
    """The per-site/per-node verdict of the device plane, pure so the
    offline analyzers (analysis/profile.py, analysis/critical_path.py)
    and the live plane compute the SAME answer:

    * ``host-bound`` — the device was idle for most of the dispatch wall
      (the host was assembling batches / expanding rows): fixing this
      node means fixing the host path, not writing a kernel;
    * ``compute-bound`` — arithmetic intensity (FLOPs per HBM byte) at
      or above the roofline ridge: the MXU is the limiter, a faster
      kernel or lower precision is the lever;
    * ``bandwidth-bound`` — intensity below the ridge: HBM traffic is
      the limiter (fuse, cache, or shrink the working set).
    """
    share = host_bound_share() if host_share is None else host_share
    if wall_s > 0 and device_s < share * wall_s:
        return "host-bound"
    if flops <= 0:
        # no modeled device arithmetic at all: whatever time this site
        # took was host work by definition
        return "host-bound"
    if bytes_accessed <= 0:
        return "compute-bound"
    pf = peak_flops() if pk_flops is None else pk_flops
    pb = peak_bandwidth() if pk_bw is None else pk_bw
    ridge = pf / max(pb, 1.0)
    return (
        "compute-bound"
        if (flops / bytes_accessed) >= ridge
        else "bandwidth-bound"
    )


def mfu(flops: float, device_s: float, pk_flops: float | None = None) -> float:
    """Model FLOPs utilization of a dispatch set: achieved FLOP/s over
    the device-kind peak. Callers pick which FLOPs they feed: padded
    FLOPs (what the hardware executed, including bucket padding) or
    effective FLOPs (only real rows/tokens — the honest utilization
    number ISSUE 16 reports as ``device_mfu``, with the padded variant
    kept alongside as ``device_mfu_padded``)."""
    if device_s <= 0 or flops <= 0:
        return 0.0
    return (flops / device_s) / (peak_flops() if pk_flops is None else pk_flops)


# -- device memory (absent-stat-safe) ----------------------------------------

def memory_stats() -> dict | None:
    """``jax.local_devices()[0].memory_stats()`` with every absence mode
    folded to None: jax not imported, no devices, the backend has no
    allocator stats (CPU), or the call raises. Callers must treat None
    as "no HBM story on this backend", not an error."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        devs = jax.local_devices()
        if not devs:
            return None
        ms = devs[0].memory_stats()
        return ms if ms else None
    except Exception:
        return None


def platform_info() -> dict | None:
    """Trace metadata: what hardware this rank actually measured —
    backend platform, device kind and the peak rates the MFU/roofline
    numbers were computed against. None when jax never loaded in this
    process (a pure relational run has no device story). Embedded into
    the trace's ``rank_meta`` so a merged multi-rank file says per rank
    what it ran on (ISSUE 15 satellite)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "?"
    kind = device_kind()
    return {
        "backend": backend,
        "device_kind": kind,
        "peak_flops": peak_flops(kind),
        "peak_bandwidth": peak_bandwidth(kind),
    }


# -- compiled cost analysis (cached per shape key) ---------------------------

_COST_CACHE: dict = {}


def _cost_cache_cap() -> int:
    """PATHWAY_DEVICE_COST_CACHE_CAP: entry bound on the per-shape-key
    compiled-cost cache. Well-behaved sites keep bounded shape sets by
    design, but an adversarial shape stream (a bucket leak upstream of
    the cost lookup) would otherwise grow the cache without limit —
    eviction is insertion-ordered (oldest shape key first)."""
    raw = os.environ.get("PATHWAY_DEVICE_COST_CACHE_CAP", "")
    try:
        v = int(raw) if raw.strip() else 512
    except ValueError:
        v = 512
    return max(1, v)


def compiled_cost(
    key: tuple,
    fn: Any,
    args: tuple,
    fallback: tuple[float, float],
) -> tuple[float, float]:
    """``(flops, bytes_accessed)`` for a jitted callable at one shape,
    preferring the compiled executable's own ``cost_analysis()`` and
    falling back to the caller's analytical model. Cached per ``key`` —
    dispatch sites keep bounded shape sets by design (pow2 batch
    buckets, capacity doublings), so the AOT lower+compile runs once
    per shape, not per dispatch; the cache itself is bounded (ISSUE 20:
    ``PATHWAY_DEVICE_COST_CACHE_CAP``, oldest-first eviction) so an
    adversarial shape stream cannot grow it without limit. ``fn=None``
    skips the attempt entirely (sites whose executables are too big to
    recompile for bookkeeping, e.g. the 1M-row KNN scan).
    """
    hit = _COST_CACHE.get(key)
    if hit is not None:
        return hit
    flops, nbytes = float(fallback[0]), float(fallback[1])
    if fn is not None and not _env_off("PATHWAY_DEVICE_COST_ANALYSIS"):
        try:
            ca = fn.lower(*args).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            ca_flops = float(ca.get("flops", 0.0) or 0.0)
            ca_bytes = float(ca.get("bytes accessed", 0.0) or 0.0)
            if ca_flops > 0:
                flops = ca_flops
            if ca_bytes > 0:
                nbytes = ca_bytes
        except Exception:
            pass
    cap = _cost_cache_cap()
    while len(_COST_CACHE) >= cap:
        _COST_CACHE.pop(next(iter(_COST_CACHE)))
    _COST_CACHE[key] = (flops, nbytes)
    return flops, nbytes


def nbytes_of(*arrays: Any) -> int:
    """Sum of ``nbytes`` over array-likes (None / scalar leaves are
    free) — the transfer-bytes estimate dispatch sites report."""
    total = 0
    for a in arrays:
        n = getattr(a, "nbytes", None)
        if n is not None:
            try:
                total += int(n)
            except (TypeError, ValueError):
                pass
    return total


# -- device-site registry (ISSUE 20) -----------------------------------------
# Every dispatch site declares itself here at import time: its analytical
# cost model, the dtypes its device buffers carry, which inputs it donates
# and where the dispatch lives. The Device Doctor (analysis/device_plan.py)
# walks THIS registry — not a parallel hand-maintained list — so a site
# added in ops/ without a registration is registry drift, caught by
# scripts/lint_gil.py pass 4.


@dataclasses.dataclass(frozen=True)
class DeviceSite:
    """One registered device-dispatch site.

    ``cost_model`` is the SAME callable the runtime site feeds into its
    dispatch records (``-> (flops, bytes_accessed)``) — the anti-drift
    contract: analyzer predictions and runtime attribution compute from
    one object. ``donates`` names the buffers the site's jitted callable
    donates (empty for read-only / host-only sites)."""

    name: str
    cost_model: Any
    dtypes: tuple
    where: str = ""
    donates: tuple = ()
    description: str = ""


_SITE_REGISTRY: dict[str, DeviceSite] = {}


def device_site(
    name: str,
    *,
    cost_model: Any,
    dtypes: Any,
    where: str = "",
    donates: Any = (),
    description: str = "",
) -> DeviceSite:
    """Register (or re-register — module reloads are idempotent) one
    dispatch site. Keyword-only by design: lint_gil pass 4 checks every
    registration names its ``cost_model=`` and ``dtypes=`` explicitly."""
    site = DeviceSite(
        name, cost_model, tuple(dtypes), where, tuple(donates), description
    )
    _SITE_REGISTRY[name] = site
    return site


def registered_sites() -> dict[str, DeviceSite]:
    """Snapshot of the registry (name -> DeviceSite)."""
    return dict(_SITE_REGISTRY)


# -- shared shape-bucket models (ISSUE 20) -----------------------------------
# The bucket functions the dispatch sites pad with ARE the functions the
# retrace audit enumerates with (the eligibility.py discipline: predicates
# the analyzer gates on are the objects the runtime consumes). Sites alias
# these — tests pin the identities — so the predicted shape-bucket set and
# the runtime's seen-bucket keys cannot drift.


def batch_bucket(n: int, floor: int, cap: int) -> int:
    """Pow2 batch bucket from ``floor``, capped — the encoder's batch
    padding (models/encoder.py ``pad_batch``)."""
    b = floor
    while b < n and b < cap:
        b *= 2
    return min(b, cap)


def seq_bucket(L: int, cap: int) -> int:
    """Multiple-of-32 sequence bucket (floor 16), capped — the encoder's
    sequence padding."""
    if L <= 16:
        return 16
    return min(((L + 31) // 32) * 32, cap)


def pow2_capacity(n: int, floor: int = 128) -> int:
    """Pow2 index capacity from the 128-slot floor — KnnShard's growth
    schedule (each distinct capacity is a fresh XLA executable)."""
    p = floor
    while p < n:
        p *= 2
    return p


def query_pad(n: int) -> int:
    """Pow2 query-batch padding from 1 — the search sites' batch set."""
    p = 1
    while p < n:
        p *= 2
    return p


def knn_search_bucket(
    n: int, capacity: int, k: int, chunk: int | None
) -> tuple:
    """Compiled-shape key of one ``knn.search`` dispatch: (padded query
    batch, capacity, effective k). Effective k mirrors the site's own
    clamp — top_k per scored block cannot exceed the block width."""
    k_eff = min(k, capacity, chunk or 8192)
    return (query_pad(n), capacity, k_eff)


def knn_write_bucket(nrows: int, capacity: int) -> tuple:
    """Compiled-shape key of one ``knn.write`` slot-write dispatch. The
    row count is data-driven (writes are not padded), so an unbounded
    write-batch-size distribution IS an unbounded executable set — the
    retrace audit flags exactly that."""
    return (nrows, capacity)


def pallas_bucket(
    q: int, cap: int, d: int, k: int, block: int, interpret: bool = False
) -> tuple:
    """Compiled-shape key of one ``pallas.topk`` kernel launch (every
    field is a static arg or an input dim of the pallas_call)."""
    return (q, cap, d, k, block, bool(interpret))


def sharded_search_bucket(
    n: int, n_shards: int, local_cap: int, k: int, chunk: int | None
) -> tuple:
    """Compiled-shape key of one ``knn.sharded_search`` dispatch —
    effective k mirrors ShardedKnnIndex.search's clamp (per-shard
    partial k capped by shard rows, merged up to total capacity)."""
    k_eff = min(k, n_shards * min(local_cap, chunk or local_cap))
    return (query_pad(n), n_shards * local_cap, k_eff)


def sharded_write_bucket(nrows: int, capacity: int) -> tuple:
    """Compiled-shape key of one ``knn.sharded_write`` dispatch."""
    return (nrows, capacity)


def ingest_bucket(nb: int, Lb: int, capacity: int, ids_dtype: str) -> tuple:
    """Compiled-shape key of one ``ingest.fused`` chain dispatch (batch
    bucket x seq bucket x index capacity x wire dtype)."""
    return (nb, Lb, capacity, ids_dtype)


def encoder_bucket(nb: int, Lb: int, compact: bool) -> tuple:
    """Compiled-shape key of one ``encoder.forward`` dispatch."""
    return (nb, Lb, bool(compact))


# -- static HBM budget (ISSUE 20) --------------------------------------------
# Per-device-kind HBM capacity for the Device Doctor's static footprint
# check; PATHWAY_DEVICE_HBM_BYTES overrides (the CPU/CI lever — model a
# v5e budget on a devbox), allocator stats win when the backend has them.
_HBM_TABLE: tuple[tuple[str, float], ...] = (
    ("v6", 32e9),
    ("v5p", 95e9),
    ("v5", 16e9),
    ("v4", 32e9),
    ("v3", 32e9),
    ("v2", 16e9),
)
_HBM_FALLBACK = 8 * 1024**3


def device_hbm_bytes(kind: str | None = None) -> int:
    """Per-chip HBM budget in bytes: ``PATHWAY_DEVICE_HBM_BYTES`` wins,
    then the backend's own allocator limit, then the device-kind table,
    then a deliberately small 8 GiB fallback (CPU/CI: the budget check
    still means something on a host with no HBM story)."""
    raw = os.environ.get("PATHWAY_DEVICE_HBM_BYTES", "")
    if raw.strip():
        try:
            v = int(float(raw))
            if v > 0:
                return v
        except ValueError:
            pass
    ms = memory_stats()
    if ms is not None:
        try:
            lim = int(ms.get("bytes_limit", 0) or 0)
        except (TypeError, ValueError):
            lim = 0
        if lim > 0:
            return lim
    kind = device_kind() if kind is None else kind
    low = kind.lower()
    for sub, b in _HBM_TABLE:
        if sub in low:
            return int(b)
    return _HBM_FALLBACK


def index_shard_bytes(capacity: int, dim: int, *, donated: bool = True) -> float:
    """Steady-state HBM of one index shard's buffer triple: f32 vectors
    [capacity, dim] + bool valid [capacity] + f32 sq_norms [capacity].
    An UN-donated write keeps the old triple alive across the dispatch
    — the doctor's donation audit bills exactly this doubling."""
    steady = 4.0 * capacity * dim + 1.0 * capacity + 4.0 * capacity
    return steady if donated else 2.0 * steady


def ingest_staging_bytes(
    nb: int, Lb: int, ids_itemsize: int = 2, *, depth: int = 2
) -> float:
    """H2D staging footprint of the tokenize-ahead ingest loop: ``depth``
    in-flight batches of (ids [nb, Lb] at the wire itemsize + i32
    lengths [nb])."""
    per = float(nb) * float(Lb) * float(ids_itemsize) + 4.0 * nb
    return float(depth) * per


def snapshot_staging_bytes(capacity: int, dim: int) -> float:
    """Worst-case staging of an epoch-aligned index snapshot cut: one
    host-bound copy of the buffer triple in flight."""
    return 4.0 * capacity * dim + 1.0 * capacity + 4.0 * capacity


# -- the plane ---------------------------------------------------------------


class _Dispatch:
    """One in-flight dispatch record (``PLANE.begin`` ... ``end``)."""

    __slots__ = (
        "site", "seq", "node", "t_commit", "t0", "t_ret", "t_done",
        "depth",
    )

    def __init__(self, site: str, seq: int, node, t_commit, t0: int,
                 depth: int):
        self.site = site
        self.seq = seq
        self.node = node
        self.t_commit = t_commit
        self.t0 = t0
        self.t_ret = t0
        self.t_done = t0
        self.depth = depth


class DevicePlane:
    """Process-wide device-dispatch recorder.

    Armed/disarmed by the runtime around each run (like the native
    trace rings, the plane is process-global: under the emulated-rank
    CI lane several thread-ranks share it and rank 0's recorder claims
    the records — approximate there, exact on real multi-rank meshes).
    ``on`` is the ONE attribute dispatch sites check when the plane is
    off.
    """

    # memory_stats() walks the allocator — sample at most this often
    _MEM_POLL_S = 0.5

    def __init__(self):
        self.on = False
        self.recorder = None
        self.stats = None
        self._seq = 0
        self._inflight = 0
        self._lock = threading.Lock()
        self._node_ctx = threading.local()
        self._last_mem_poll = 0.0

    # -- lifecycle (runtime) ----------------------------------------------
    def arm(self, recorder, stats) -> None:
        """Attach this run's flight recorder (may be None: metrics-only
        runs still feed the gauges) and ProberStats. PATHWAY_DEVICE_TRACE=0
        keeps the plane off even on an armed run — the opt-out for
        pipelines where the per-dispatch ``block_until_ready`` sync
        costs more than the visibility buys."""
        if _env_off("PATHWAY_DEVICE_TRACE"):
            return
        self.recorder = recorder
        self.stats = stats
        if stats is not None:
            stats.set_device_peak_flops(peak_flops())
        self._last_mem_poll = 0.0
        # a dispatch site that raised between begin() and end() in a
        # PREVIOUS run left its record open — re-zero so queue-depth
        # reporting starts honest for this run
        with self._lock:
            self._inflight = 0
        self.on = True

    def disarm(self) -> None:
        self.on = False
        self.recorder = None
        self.stats = None

    # -- engine-node context (runtime step loop) --------------------------
    def set_node(self, nid: int, t_commit: int) -> None:
        self._node_ctx.v = (nid, t_commit)

    def clear_node(self) -> None:
        self._node_ctx.v = None

    def _current_node(self):
        return getattr(self._node_ctx, "v", None)

    # -- dispatch records --------------------------------------------------
    def begin(self, site: str) -> _Dispatch | None:
        """Open a dispatch record (None when the plane is off — sites
        guard on ``PLANE.on`` first, so the off path is one attribute
        check and no call at all)."""
        if not self.on:
            return None
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._inflight += 1
            depth = self._inflight
        ctx = self._current_node()
        nid, t_commit = ctx if ctx is not None else (None, None)
        return _Dispatch(site, seq, nid, t_commit,
                         _time.perf_counter_ns(), depth)

    def enqueued(self, d: _Dispatch | None) -> None:
        """Mark the enqueue boundary explicitly (optional — ``end``
        stamps it from its ``t_ret`` argument path otherwise)."""
        if d is not None:
            d.t_ret = _time.perf_counter_ns()

    def end(
        self,
        d: _Dispatch | None,
        outputs: Any = None,
        *,
        flops: float = 0.0,
        flops_effective: float | None = None,
        bytes_accessed: float = 0.0,
        transfer_bytes: int = 0,
        block: bool = True,
        cost_fn: Any = None,
        effective_share: float | None = None,
    ) -> None:
        """Close a dispatch record: ``outputs`` (a jax array / pytree)
        is blocked on so the device time is bounded, the record lands on
        the flight recorder's device track and the OpenMetrics device
        families. Host-only dispatch sites (the serving gateway's window
        commit) pass ``outputs=None, block=False`` — wall-only records
        whose device time is honestly zero. ``cost_fn`` (-> (flops,
        bytes_accessed)) runs AFTER the wall span is stamped — the home
        for ``compiled_cost``, whose first call per shape bucket pays an
        AOT lower+compile that must not be charged into the record as
        host time.

        MFU honesty (ISSUE 16): ``flops`` is what the hardware executed
        — padded rows/tokens included. ``flops_effective`` is the share
        of it spent on REAL rows; sites that pad batches to pow2
        buckets pass it (or ``effective_share`` in [0, 1], applied
        after ``cost_fn`` resolves the padded number) so bucket-padding
        waste is visible instead of inflating the MFU gauge. Defaults
        to ``flops`` — an unpadded site is 100% effective."""
        if d is None:
            return
        if d.t_ret == d.t0:
            d.t_ret = _time.perf_counter_ns()
        if block and outputs is not None:
            jax = sys.modules.get("jax")
            if jax is not None:
                try:
                    jax.block_until_ready(outputs)
                except Exception:
                    pass
        d.t_done = _time.perf_counter_ns()
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
        if cost_fn is not None:
            try:
                flops, bytes_accessed = cost_fn()
            except Exception:
                pass
        if flops_effective is None:
            flops_effective = (
                flops * min(max(effective_share, 0.0), 1.0)
                if effective_share is not None
                else flops
            )
        flops_effective = min(flops_effective, flops)
        wall_s = max(0, d.t_done - d.t0) / 1e9
        device_s = max(0, d.t_done - d.t_ret) / 1e9
        stats = self.stats
        if stats is not None:
            stats.on_device_dispatch(
                d.site, wall_s, device_s, flops, bytes_accessed,
                transfer_bytes, d.depth, flops_effective,
            )
        rec = self.recorder
        if rec is not None:
            rec.note_dispatch(
                d.site, d.seq, d.node, d.t_commit, d.t0, d.t_ret,
                d.t_done, flops, bytes_accessed, transfer_bytes, d.depth,
                flops_effective,
            )
        self._sample_memory_throttled()

    def note_recompile(self, site: str) -> None:
        """One fresh XLA compilation observed at a dispatch site (a new
        shape bucket entered its compiled-fn cache). Feeds the
        ``device_recompiles_total`` counter so a silent recompile storm
        — a shape-bucket leak re-lowering every batch — shows on the
        TUI/cluster view instead of only as mysterious wall time."""
        stats = self.stats
        if stats is not None:
            stats.on_device_recompile(site)

    # -- HBM gauges --------------------------------------------------------
    def _sample_memory_throttled(self) -> None:
        now = _time.monotonic()
        if now - self._last_mem_poll < self._MEM_POLL_S:
            return
        self._last_mem_poll = now
        self.sample_memory()

    def sample_memory(self) -> None:
        """Pull ``memory_stats()`` into the HBM gauges; a backend with
        no allocator stats (CPU) leaves the gauges at their absent-safe
        zeros with ``available`` false."""
        stats = self.stats
        if stats is None:
            return
        ms = memory_stats()
        if ms is None:
            stats.set_device_memory(0, 0, available=False)
            return
        stats.set_device_memory(
            int(ms.get("bytes_in_use", 0) or 0),
            int(ms.get("peak_bytes_in_use", 0) or 0),
            available=True,
        )


PLANE = DevicePlane()


# -- dispatch supervision (ISSUE 17, device fault domain) --------------------
# Before this, a device dispatch had exactly two outcomes: success, or an
# exception that killed the whole pipelined run (with a hung dispatch only
# dying by the 300s MeshTimeout backstop). Supervised sites route their
# launch through :func:`supervised_dispatch`: failures are classified
# (transient / oom / permanent) and the pure
# ``protocol.device_dispatch_decide`` transition picks retry-with-backoff,
# brownout, or epoch abort — the connector ``SupervisorPolicy`` semantics
# (io/_connector.py) applied to the device plane. An optional watchdog
# deadline (``PATHWAY_DEVICE_DISPATCH_TIMEOUT_S``; 0 = off, the default —
# the hot path stays a plain call) bounds a hung dispatch well under the
# mesh op timeout.

_RETRY_BACKOFF_BASE_S = 0.05
_RETRY_BACKOFF_CAP_S = 2.0

# transient XLA/runtime failure markers: worth a bounded retry. OOM
# markers are matched FIRST — RESOURCE_EXHAUSTED must never retry into
# the same full allocator.
_OOM_MARKERS = ("resource_exhausted", "resource exhausted", "out of memory",
                "oom ", "allocating ")
_TRANSIENT_MARKERS = (
    "unavailable", "deadline_exceeded", "deadline exceeded", "aborted",
    "connection reset", "temporarily", "try again", "internal: failed",
)
# a failed dispatch may have consumed its donated input buffers — a
# retry would compute on deleted arrays; classify as permanent so the
# epoch rolls back to buffers the snapshot actually holds
_PERMANENT_MARKERS = ("donated", "deleted", "invalid buffer")


class DeviceOom(RuntimeError):
    """HBM exhaustion (real RESOURCE_EXHAUSTED or injected
    ``device.oom``): growth was refused, the index keeps serving at its
    committed capacity and the serving breaker browns out."""


class WatchdogTimeout(RuntimeError):
    """A supervised dispatch exceeded PATHWAY_DEVICE_DISPATCH_TIMEOUT_S.
    The hung launch thread is abandoned (XLA offers no cancel); the
    caller's epoch aborts well under the mesh op timeout backstop."""


def classify_device_error(exc: BaseException) -> str:
    """``"transient"`` | ``"oom"`` | ``"permanent"`` — the input to the
    pure ``device_dispatch_decide`` transition. Injected faults carry
    their class explicitly (``device.oom`` point -> oom, ``retryable``
    -> transient); real errors classify by message markers, permanent
    winning on donation/deletion evidence (retrying on consumed buffers
    can only corrupt)."""
    from pathway_tpu.internals.faults import InjectedFault

    if isinstance(exc, WatchdogTimeout):
        return "permanent"
    if isinstance(exc, InjectedFault):
        if exc.point == "device.oom":
            return "oom"
        return "transient" if exc.retryable else "permanent"
    if isinstance(exc, MemoryError):
        return "oom"
    low = f"{type(exc).__name__}: {exc}".lower()
    if any(m in low for m in _PERMANENT_MARKERS):
        return "permanent"
    if any(m in low for m in _OOM_MARKERS):
        return "oom"
    if any(m in low for m in _TRANSIENT_MARKERS):
        return "transient"
    return "permanent"


def dispatch_timeout_s() -> float:
    """Watchdog deadline for supervised dispatches; 0 disables (the
    default: unsupervised hangs still die by the mesh op timeout)."""
    v = _env_float("PATHWAY_DEVICE_DISPATCH_TIMEOUT_S")
    return v if v is not None and v > 0 else 0.0


def dispatch_retries() -> int:
    raw = os.environ.get("PATHWAY_DEVICE_RETRIES", "")
    try:
        v = int(raw) if raw.strip() else 2
    except ValueError:
        v = 2
    return max(0, v)


# serving-plane OOM listeners: the HTTP gateway registers a callback
# that flips its breaker into brownout (answers `Degraded: true` from
# the last committed index) the moment any device site reports OOM
_OOM_LISTENERS: list = []
_OOM_LOCK = threading.Lock()


def on_oom(listener) -> None:
    with _OOM_LOCK:
        if listener not in _OOM_LISTENERS:
            _OOM_LISTENERS.append(listener)


def remove_oom_listener(listener) -> None:
    with _OOM_LOCK:
        if listener in _OOM_LISTENERS:
            _OOM_LISTENERS.remove(listener)


def notify_oom(site: str) -> None:
    """Tick the oom counter and brown out every registered serving
    gateway. Listener errors are swallowed — OOM handling must never
    make the failure worse."""
    stats = PLANE.stats
    if stats is not None:
        stats.on_device_oom(site)
    with _OOM_LOCK:
        listeners = list(_OOM_LISTENERS)
    for listener in listeners:
        try:
            listener(site)
        except Exception:
            pass


def _run_with_watchdog(site: str, thunk, timeout: float):
    """Run the launch on a worker thread with a deadline. A trip
    abandons the hung thread (daemon) — the record is the
    ``device_watchdog_trips_total`` counter plus the raised
    :class:`WatchdogTimeout`, which classifies permanent so the epoch
    aborts instead of waiting out the 300s mesh backstop."""
    box: list = []

    def worker():
        try:
            box.append(("ok", thunk()))
        except BaseException as e:  # noqa: BLE001 - re-raised on the caller
            box.append(("err", e))

    t = threading.Thread(
        target=worker, name=f"device-dispatch:{site}", daemon=True
    )
    t.start()
    t.join(timeout)
    if not box:
        stats = PLANE.stats
        if stats is not None:
            stats.on_device_watchdog_trip(site)
        raise WatchdogTimeout(
            f"device dispatch at {site} exceeded the "
            f"{timeout:g}s watchdog deadline"
        )
    status, value = box[0]
    if status == "err":
        raise value
    return value


def supervised_dispatch(site: str, thunk):
    """Run one device launch under supervision: the ``device.dispatch``
    fault point fires first (with ``site=`` context), then the thunk;
    classified failures take the ``device_dispatch_decide`` verdict —
    bounded-backoff retry, OOM brownout, or abort. Idempotence contract:
    the thunk must be safe to re-run (searches are; writes are upserts
    whose donation failures classify permanent)."""
    from pathway_tpu.internals import faults as _faults
    from pathway_tpu.parallel import protocol as _proto

    timeout = dispatch_timeout_s()
    retries = dispatch_retries()
    attempt = 0
    while True:
        try:
            _faults.fault_point("device.dispatch", site=site)
            if timeout > 0:
                return _run_with_watchdog(site, thunk, timeout)
            return thunk()
        except BaseException as exc:  # noqa: BLE001 - classified below
            kind = classify_device_error(exc)
            verdict = _proto.device_dispatch_decide(kind, attempt, retries)
            stats = PLANE.stats
            if verdict[0] == "retry":
                attempt = verdict[1]
                if stats is not None:
                    stats.on_device_dispatch_retry(site)
                _time.sleep(min(
                    _RETRY_BACKOFF_CAP_S,
                    _RETRY_BACKOFF_BASE_S * (2 ** (attempt - 1)),
                ))
                continue
            if stats is not None:
                stats.on_device_dispatch_failure(site)
            if verdict[0] == "brownout":
                notify_oom(site)
                if isinstance(exc, DeviceOom):
                    raise
                raise DeviceOom(
                    f"device dispatch at {site} hit HBM exhaustion: {exc!r}"
                ) from exc
            raise
