"""Groupby/reduce machinery (reference: python/pathway/internals/groupbys.py
+ graph_runner reduce lowering).

``t.groupby(cols).reduce(out=reducer(...))`` lowers to the engine's
GroupByNode: per-group multisets, affected-group rediff, output keyed by
``ref_scalar(*grouping_values)`` (reference: Graph::group_by_table,
graph.rs:885).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    ReducerExpression,
)
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.reducers import StatefulReducer
from pathway_tpu.internals.schema import schema_from_types
from pathway_tpu.internals.universe import Universe


class GroupedTable:
    def __init__(
        self,
        table,
        grouping: list[ColumnExpression],
        sort_by=None,
        id_from_first_group_col: bool = False,
    ):
        self._table = table
        self._grouping = [expr_mod.smart_coerce(g) for g in grouping]
        self._sort_by = (
            table._desugar(expr_mod.smart_coerce(sort_by)) if sort_by is not None else None
        )
        # groupby(id=ptr_col): output row ids ARE the grouping values
        self._id_from_first_group_col = id_from_first_group_col

    def _resolve_deferred(self, name: str):
        return self._table._resolve_deferred(name)

    def reduce(self, *args, **kwargs):
        from pathway_tpu.internals.table import Table

        base = self._table
        names: list[str] = []
        out_exprs: list[ColumnExpression] = []
        for arg in args:
            if isinstance(arg, thisclass.ThisColumnReference):
                names.append(arg.name)
                out_exprs.append(base._desugar(arg))
            elif isinstance(arg, ColumnReference):
                names.append(arg.name)
                out_exprs.append(arg)
            else:
                raise ValueError(
                    "positional reduce() arguments must be column references"
                )
        for n, e in kwargs.items():
            names.append(n)
            out_exprs.append(base._desugar(expr_mod.smart_coerce(e)))

        grouping = self._grouping
        grouping_ids = {id(g) for g in grouping}
        grouping_refs = {
            (id(g.table), g.name): j
            for j, g in enumerate(grouping)
            if isinstance(g, ColumnReference)
        }

        # synthetic result namespace: g0..gN grouping cols, r0..rM reducers
        reducers: list[ReducerExpression] = []
        gtable_cols: dict[str, dt.DType] = {
            f"g{j}": g._dtype for j, g in enumerate(grouping)
        }

        gtable = Table.__new__(Table)  # bare namespace table, never lowered
        gtable._name = "groupby_result"
        gtable._column_names = []
        gtable._schema_cls = None

        def gref(name: str, dtype: dt.DType) -> ColumnReference:
            r = ColumnReference.__new__(ColumnReference)
            ColumnExpression.__init__(r)
            r._table = gtable
            r._name = name
            r._dtype = dtype
            return r

        def _same_structure(a: ColumnExpression, b: ColumnExpression) -> bool:
            # reduce() may repeat the grouping expression as a new object
            # (reference: groupbys.py matches by expression structure).
            # Applies/UDFs are excluded: their reprs elide function identity
            # and arguments, so repr equality would false-positive.
            if type(a) is not type(b) or repr(a) != repr(b):
                return False
            if not (a._is_deterministic and b._is_deterministic):
                return False

            def has_apply(e):
                from pathway_tpu.internals.expression import ApplyExpression

                stack = [e]
                while stack:
                    x = stack.pop()
                    if isinstance(x, ApplyExpression):
                        return True
                    stack.extend(x._subexpressions())
                return False

            if has_apply(a) or has_apply(b):
                return False
            return [(id(r.table), r.name) for r in a._deps] == [
                (id(r.table), r.name) for r in b._deps
            ]

        def rewrite_fn(e: ColumnExpression):
            if isinstance(e, ReducerExpression):
                idx = len(reducers)
                reducers.append(e)
                return gref(f"r{idx}", e._dtype)
            if id(e) in grouping_ids:
                j = grouping.index(e)
                return gref(f"g{j}", e._dtype)
            if not isinstance(e, ColumnReference):
                for j, g in enumerate(grouping):
                    if _same_structure(e, g):
                        return gref(f"g{j}", e._dtype)
            if isinstance(e, ColumnReference):
                j = grouping_refs.get((id(e.table), e.name))
                if j is not None:
                    return gref(f"g{j}", e._dtype)
                if e.name == "id" and e.table is base:
                    raise ValueError(
                        "cannot use id of the source table in reduce(); "
                        "group by it explicitly"
                    )
            return None

        rewritten = [thisclass.rewrite(e, rewrite_fn) for e in out_exprs]

        # validate: no remaining refs outside gtable
        for e in rewritten:
            for ref in e._deps:
                if ref.table is not gtable:
                    raise ValueError(
                        f"column {ref.name!r} must be grouped or wrapped in a reducer"
                    )
        for i, r in enumerate(reducers):
            gtable_cols[f"r{i}"] = r._dtype

        stateful = [r for r in reducers if isinstance(r._reducer, StatefulReducer)]

        out_schema = schema_from_types(
            **{n: e._dtype for n, e in zip(names, rewritten)}
        )
        out = Table(out_schema, Universe())
        n_group = len(grouping)

        sort_by = self._sort_by
        id_from_first = self._id_from_first_group_col
        key_fn = (lambda gvals: gvals[0]) if id_from_first else None

        def lower(ctx):
            from pathway_tpu.engine.expression import compile_expression

            all_input_exprs = list(grouping) + [
                a for r in reducers for a in r._args
            ] + ([sort_by] if sort_by is not None else [])
            et, resolver = ctx._combined_view(base, all_input_exprs)

            deterministic = all(e._is_deterministic for e in all_input_exprs)
            if deterministic:
                gfns = [
                    compile_expression(g, resolver, ctx.runtime) for g in grouping
                ]
                arg_fns = [
                    [compile_expression(a, resolver, ctx.runtime) for a in r._args]
                    for r in reducers
                ]
                sort_fn = (
                    compile_expression(sort_by, resolver, ctx.runtime)
                    if sort_by is not None
                    else None
                )
            else:
                # non-deterministic UDFs feeding a groupby must be computed
                # ONCE per row and replayed on retraction, else the retraction
                # keys a different multiset slot (consistent-deletions
                # semantics, reference dataflow.rs:1480) — pre-materialize all
                # inputs through a memoized rowwise stage and index by slot
                base_fns = [
                    compile_expression(e, resolver, ctx.runtime)
                    for e in all_input_exprs
                ]

                def precompute(keys, rows):
                    cols = [f(keys, rows) for f in base_fns]
                    return list(zip(*cols)) if cols else [()] * len(keys)

                et = ctx.scope.rowwise_memoized(
                    et, precompute, len(all_input_exprs),
                    src_exprs=all_input_exprs,
                )

                def slot_fn(j):
                    def f(keys, rows):
                        return [r[j] for r in rows]

                    return f

                gfns = [slot_fn(j) for j in range(n_group)]
                arg_fns = []
                pos = n_group
                for r in reducers:
                    arg_fns.append(
                        [slot_fn(pos + i) for i in range(len(r._args))]
                    )
                    pos += len(r._args)
                sort_fn = slot_fn(pos) if sort_by is not None else None

            def grouping_fn(k, row):
                return tuple(f([k], [row])[0] for f in gfns)

            def args_fn(k, row):
                # contract: (*args, order_token, row_key) per reducer slot
                order = sort_fn([k], [row])[0] if sort_fn is not None else k
                return tuple(
                    tuple(f([k], [row])[0] for f in fns) + (order, k)
                    for fns in arg_fns
                )

            # column-oriented batch variants: one evaluator call per column
            # per batch instead of two closure calls per row
            def grouping_batch(keys, rows):
                if not gfns:
                    return [()] * len(keys)
                cols = [f(keys, rows) for f in gfns]
                return list(zip(*cols))

            # all-plain-column grouping builds the gvals tuples in one C
            # pass over the rows (the wordcount-class hot path). In the
            # non-deterministic branch the grouping values occupy slots
            # 0..n_group-1 of the pre-materialized rows by construction.
            from pathway_tpu.engine.stream import get_fp

            fp = get_fp()
            if fp is not None and grouping:
                g_idx: list[int] | None = []
                if deterministic:
                    for g in grouping:
                        loc = (
                            resolver(g)
                            if isinstance(g, ColumnReference)
                            else None
                        )
                        if isinstance(loc, int):
                            g_idx.append(loc)
                        else:
                            g_idx = None
                            break
                else:
                    g_idx = list(range(n_group))
                if g_idx is not None and len(g_idx) > 32:
                    g_idx = None  # native projection caps at 32 columns
                if g_idx is not None:
                    idxs = tuple(g_idx)
                    pt = fp.project_tuples

                    def grouping_batch(keys, rows):  # noqa: F811
                        return pt(rows, idxs)

            def args_batch(keys, rows):
                n = len(keys)
                order_col = (
                    sort_fn(keys, rows) if sort_fn is not None else keys
                )
                per_reducer = []
                for fns in arg_fns:
                    if fns:
                        acols = [f(keys, rows) for f in fns]
                        per_reducer.append(
                            [
                                tuple(vals) + (order_col[i], keys[i])
                                for i, vals in enumerate(zip(*acols))
                            ]
                        )
                    else:
                        per_reducer.append(
                            [(order_col[i], keys[i]) for i in range(n)]
                        )
                if not per_reducer:  # reduce() with no reducer columns
                    return [()] * n
                return list(zip(*per_reducer))

            # single-column arg evaluators for the native executor: one
            # entry per reducer — None for arg-less reducers (count);
            # multi-arg reducers make the node ineligible. sort_by rides
            # along as a separate order column (native_order) that the
            # C++ store keys multiset entries and tuple/any orderings on.
            native_args = []
            for fns in arg_fns:
                if len(fns) == 0:
                    native_args.append(None)
                elif len(fns) == 1:
                    native_args.append(fns[0])
                else:
                    native_args = None
                    break

            if len(stateful) == len(reducers) == 1:
                red = reducers[0]
                post = getattr(red, "_post_process", None)
                combine = red._reducer.combine_many

                def combine_rows(state, rows):
                    # rows: list of (args_combo, diff); combo = ((a1..ak, order, key),)
                    flat = [(combo[0][:-2], d) for combo, d in rows]
                    return combine(state, flat)

                get = ctx.scope.stateful_reduce(
                    et, grouping_fn, args_fn, combine_rows, n_group, key_fn=key_fn
                )
                if post is not None:
                    get = ctx.scope.rowwise(
                        get,
                        lambda keys, rows: [
                            r[:-1] + (post(r[-1]),) for r in rows
                        ],
                        get.width,
                    )
                grouped = get
            else:
                reducer_specs = []
                for r in reducers:
                    post = getattr(r, "_post_process", None)
                    if isinstance(r._reducer, StatefulReducer):
                        # stateful rides the general node as a per-row
                        # accumulator slot — freely composable with plain
                        # reducers (reference: reduce.rs:22, Stateful is
                        # just another Reducer variant). Diffs flow into
                        # combine_many exactly like the dedicated node's.
                        combine = r._reducer.combine_many

                        def upd(s, combo, d, _c=combine):
                            return _c(s, [(combo[:-2], d)])

                        fin = (
                            (lambda s, _p=post: _p(s))
                            if post is not None
                            else (lambda s: s)
                        )
                        reducer_specs.append(("abelian", upd, fin, None))
                        continue
                    spec = r._reducer.engine_spec(**r._kwargs)
                    if post is not None:
                        if spec[0] == "abelian":
                            # drops any native code: post-processing needs
                            # the Python finish path
                            upd, fin, init = spec[1], spec[2], spec[3]
                            spec = (
                                "abelian", upd,
                                lambda s, _f=fin, _p=post: _p(_f(s)), init,
                            )
                        else:
                            fn = spec[1]
                            spec = (
                                "full",
                                lambda ms, slot, _f=fn, _p=post: _p(_f(ms, slot)),
                            )
                    reducer_specs.append(spec)
                # NativeBatch fused-chain eligibility: deterministic
                # plain-column grouping and argless/single-plain-column
                # reducer args, no sort_by — the shapes the columnar C
                # parse→groupby path (exec.cpp process_batch_nb) executes
                # with zero per-row Python objects. The predicate (and
                # the blame naming the offending expression/reducer)
                # lives in analysis/eligibility.py, shared with
                # pw.analyze so analyzer and executor cannot drift.
                from pathway_tpu.analysis import eligibility as _elig

                nb_gidx, nb_argidx, nb_blame = _elig.groupby_nb_indices(
                    grouping, reducers, sort_by, deterministic, resolver
                )

                grouped = ctx.scope.group_by(
                    et, grouping_fn, args_fn, reducer_specs, n_group,
                    key_fn=key_fn, grouping_batch=grouping_batch,
                    args_batch=args_batch, native_args=native_args,
                    native_order=sort_fn,
                    nb_gidx=nb_gidx, nb_argidx=nb_argidx,
                    nb_blame=nb_blame,
                    src_exprs=all_input_exprs,
                )

            # stage 2: evaluate output expressions over gvals + reducer values
            def out_resolver(ref):
                if ref.table is gtable:
                    name = ref.name
                    if name.startswith("g"):
                        return int(name[1:])
                    return n_group + int(name[1:])
                if ref.name == "id":
                    return "id"
                raise KeyError(ref.name)

            # identity projection (reduce(word=this.g, c=reducer) in slot
            # order) needs no rowwise stage at all; an all-plain-column
            # projection runs as one C pass. Both are the common shapes on
            # the relational hot path.
            out_idx: list[int] | None = []
            for e in rewritten:
                loc = (
                    out_resolver(e)
                    if isinstance(e, ColumnReference)
                    else None
                )
                if isinstance(loc, int):
                    out_idx.append(loc)
                else:
                    out_idx = None
                    break
            grouped_width = n_group + len(reducers)
            if out_idx is not None and out_idx == list(range(grouped_width)):
                ctx.set_engine_table(out, grouped)
                return
            if out_idx is not None and len(out_idx) > 32:
                out_idx = None  # native projection caps at 32 columns

            if out_idx is not None and fp is not None:
                idxs_out = tuple(out_idx)

                def batch_fn(keys, rows):
                    return fp.project_tuples(rows, idxs_out)

            else:
                out_fns = [
                    compile_expression(e, out_resolver, ctx.runtime)
                    for e in rewritten
                ]

                def batch_fn(keys, rows):  # noqa: F811
                    cols = [f(keys, rows) for f in out_fns]
                    return list(zip(*cols)) if cols else [()] * len(keys)

            ctx.set_engine_table(
                out,
                ctx.scope.rowwise_auto(
                    grouped, batch_fn, len(rewritten),
                    all(e._is_deterministic for e in rewritten),
                    src_exprs=rewritten,
                ),
            )

        dep_exprs = list(grouping) + [a for r in reducers for a in r._args]
        G.add_operator(base._dep_tables(dep_exprs), [out], lower, "groupby_reduce")
        return out
