"""OpenTelemetry tracing surface (reference:
python/pathway/internals/graph_runner/telemetry.py, 140 LoC — spans
`graph_runner.build` / `graph_runner.run` around lowering and execution;
engine side src/engine/telemetry.rs exports OTLP).

Only the OTel API is required: with no SDK configured the spans are
no-ops; installing `opentelemetry-sdk` + an exporter activates them
without code changes (`pw.set_monitoring_config(server_endpoint=...)`
records the OTLP endpoint for the SDK bootstrap)."""

from __future__ import annotations

import contextlib
from typing import Any


class Telemetry:
    def __init__(self, tracer):
        self.tracer = tracer

    @classmethod
    def create(cls, endpoint: str | None = None) -> "Telemetry":
        try:
            from opentelemetry import trace

            if endpoint is not None:
                cls._try_bootstrap_sdk(endpoint)
            tracer = trace.get_tracer("pathway_tpu")
        except ImportError:
            tracer = None
        return cls(tracer)

    _sdk_bootstrapped = False

    @classmethod
    def _try_bootstrap_sdk(cls, endpoint: str) -> None:
        # once per process: OTel ignores later set_tracer_provider calls,
        # so repeats would only leak batch-export threads + gRPC channels
        if cls._sdk_bootstrapped:
            return
        cls._sdk_bootstrapped = True
        try:
            from opentelemetry import trace
            from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
                OTLPSpanExporter,
            )
            from opentelemetry.sdk.resources import Resource
            from opentelemetry.sdk.trace import TracerProvider
            from opentelemetry.sdk.trace.export import BatchSpanProcessor

            provider = TracerProvider(
                resource=Resource.create({"service.name": "pathway_tpu"})
            )
            provider.add_span_processor(
                BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint))
            )
            trace.set_tracer_provider(provider)
        except ImportError:
            pass  # API-only install: spans stay no-ops

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        if self.tracer is None:
            yield None
            return
        with self.tracer.start_as_current_span(name) as s:
            for k, v in attributes.items():
                try:
                    s.set_attribute(k, v)
                except Exception:
                    pass
            yield s
