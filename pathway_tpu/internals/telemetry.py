"""OpenTelemetry tracing surface (reference:
python/pathway/internals/graph_runner/telemetry.py, 140 LoC — spans
`graph_runner.build` / `graph_runner.run` around lowering and execution;
engine side src/engine/telemetry.rs exports OTLP).

Only the OTel API is required: with no SDK configured the spans are
no-ops; installing `opentelemetry-sdk` + an exporter activates them
without code changes (`pw.set_monitoring_config(server_endpoint=...)`
records the OTLP endpoint for the SDK bootstrap)."""

from __future__ import annotations

import contextlib
from typing import Any


class Telemetry:
    def __init__(self, tracer):
        self.tracer = tracer

    _otlp_cache: dict = {}

    @classmethod
    def create(cls, endpoint: str | None = None, *, stats=None):
        if endpoint is not None:
            # hand-rolled OTLP/HTTP JSON exporter (internals/otlp.py):
            # spans + 60 s process/latency gauges with no OTel SDK needed
            # (reference: src/engine/telemetry.rs:38-45). One instance per
            # endpoint per process — repeated pw.run() calls must not each
            # leak a metrics thread.
            from pathway_tpu.internals.otlp import OtlpTelemetry

            tel = cls._otlp_cache.get(endpoint)
            if tel is None:
                tel = OtlpTelemetry(endpoint, stats=stats)
                cls._otlp_cache[endpoint] = tel
            else:
                tel.stats = stats  # gauge source follows the live runtime
            return tel
        try:
            from opentelemetry import trace

            tracer = trace.get_tracer("pathway_tpu")
        except ImportError:
            tracer = None
        return cls(tracer)

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        if self.tracer is None:
            yield None
            return
        with self.tracer.start_as_current_span(name) as s:
            for k, v in attributes.items():
                try:
                    s.set_attribute(k, v)
                except Exception:
                    pass
            yield s
