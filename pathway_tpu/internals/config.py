"""Environment-first configuration (reference:
python/pathway/internals/config.py:57-97 PathwayConfig — PATHWAY_* env
vars; engine mirror src/engine/dataflow/config.rs:88 Config::from_env).

On TPU the worker topology maps to the device mesh (SURVEY §2.9):
PATHWAY_THREADS ~ data-parallel shards within a host, PATHWAY_PROCESSES ~
hosts in the jax.distributed cluster.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def _env_field(name: str, default: str | None = None):
    return field(default_factory=lambda: os.environ.get(name, default))


def _env_bool_field(name: str, default: str = "false"):
    def factory() -> bool:
        # strip() mirrors the knob registry's Knob.check — the two must
        # accept the same value set or _load_config's except-ValueError
        # routing re-raises the raw factory error without a KnobError
        value = os.environ.get(name, default).strip().lower()
        if value in ("1", "true", "yes"):
            return True
        if value in ("0", "false", "no"):
            return False
        raise ValueError(f"unexpected value for {name!r}: {value!r}")

    return field(default_factory=factory)


def _env_int_field(name: str, default: int):
    return field(
        default_factory=lambda: int(os.environ.get(name, str(default)) or default)
    )


@dataclass
class PathwayConfig:
    threads: int = _env_int_field("PATHWAY_THREADS", 1)
    processes: int = _env_int_field("PATHWAY_PROCESSES", 1)
    process_id: int = _env_int_field("PATHWAY_PROCESS_ID", 0)
    first_port: int = _env_int_field("PATHWAY_FIRST_PORT", 10000)
    run_id: str | None = _env_field("PATHWAY_RUN_ID")
    license_key: str | None = _env_field("PATHWAY_LICENSE_KEY")
    monitoring_server: str | None = _env_field("PATHWAY_MONITORING_SERVER")
    replay_storage: str | None = _env_field("PATHWAY_REPLAY_STORAGE")
    snapshot_access: str | None = _env_field("PATHWAY_SNAPSHOT_ACCESS")
    persistence_mode: str | None = _env_field("PATHWAY_PERSISTENCE_MODE")
    continue_after_replay: bool = _env_bool_field("PATHWAY_CONTINUE_AFTER_REPLAY")
    ignore_asserts: bool = _env_bool_field("PATHWAY_IGNORE_ASSERTS")
    runtime_typechecking: bool = _env_bool_field("PATHWAY_RUNTIME_TYPECHECKING")
    terminate_on_error: bool = _env_bool_field(
        "PATHWAY_TERMINATE_ON_ERROR", "true"
    )

    @property
    def replay_config(self):
        if self.replay_storage is None:
            return None
        from pathway_tpu import persistence

        return persistence.Config(
            backend=persistence.Backend.filesystem(self.replay_storage)
        )


# Constructed LAZILY (first get_pathway_config()/attribute access), not
# at import: `python -m pathway_tpu.analysis` must be able to import the
# package and DIAGNOSE a broken environment rather than crash before its
# own error handling runs (runpy imports the package before __main__).
import threading as _threading

_pathway_config: PathwayConfig | None = None
_config_lock = _threading.Lock()


def _load_config() -> PathwayConfig:
    global _pathway_config
    if _pathway_config is None:
        # double-checked under a lock: connector / emulated-rank threads
        # racing the first load must not each build an instance (the
        # loser's would silently discard set_license_key-style mutations
        # made to the winner's)
        with _config_lock:
            if _pathway_config is None:
                try:
                    _pathway_config = PathwayConfig()
                except ValueError:
                    # a config-backed PATHWAY_* var failed to parse —
                    # route the failure through the knob registry so the
                    # user gets the full did-you-mean/range report
                    # (KnobError) instead of a raw ValueError out of a
                    # field factory. knobs.py is stdlib-only, so this
                    # import cannot cycle back here.
                    from pathway_tpu.analysis.knobs import (
                        enforce_environment,
                    )

                    enforce_environment()
                    raise  # registry considered the env valid: as-is
    return _pathway_config


def __getattr__(name: str):
    # module attribute access (tests monkeypatch C.pathway_config.*)
    if name == "pathway_config":
        return _load_config()
    raise AttributeError(name)

# Per-thread overlay used by the emulated-rank CI lane (scripts/
# ci_lanes.sh): companion ranks run as THREADS of one test process, each
# seeing its own process_id/processes/first_port while the global config
# stays untouched. Real multi-process runs never set this.
import contextvars as _contextvars

_thread_overlay: "_contextvars.ContextVar[dict | None]" = (
    _contextvars.ContextVar("pathway_config_overlay", default=None)
)


class _OverlaidConfig:
    __slots__ = ("_base", "_overlay")

    def __init__(self, base: PathwayConfig, overlay: dict):
        self._base = base
        self._overlay = overlay

    def __getattr__(self, name):
        if name in self._overlay:
            return self._overlay[name]
        return getattr(self._base, name)


def push_config_overlay(**kwargs):
    """Set per-thread config fields; returns a token for reset."""
    return _thread_overlay.set(kwargs)


def pop_config_overlay(token) -> None:
    _thread_overlay.reset(token)


def get_pathway_config() -> PathwayConfig:
    overlay = _thread_overlay.get()
    if overlay:
        return _OverlaidConfig(_load_config(), overlay)  # type: ignore
    return _load_config()


def set_license_key(key: str | None) -> None:
    """reference: pw.set_license_key — entitlements are not enforced in
    this build (no keygen.sh round trips); the key is recorded for config
    surface parity."""
    _load_config().license_key = key


def set_monitoring_config(*, server_endpoint: str | None = None, **kwargs) -> None:
    _load_config().monitoring_server = server_endpoint


def _check_entitlements(*entitlements: str) -> bool:
    """reference: internals/config.py:105 — always granted here."""
    return True
