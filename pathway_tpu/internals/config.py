"""Environment-first configuration (reference:
python/pathway/internals/config.py:57-97 PathwayConfig — PATHWAY_* env
vars; engine mirror src/engine/dataflow/config.rs:88 Config::from_env).

On TPU the worker topology maps to the device mesh (SURVEY §2.9):
PATHWAY_THREADS ~ data-parallel shards within a host, PATHWAY_PROCESSES ~
hosts in the jax.distributed cluster.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def _env_field(name: str, default: str | None = None):
    return field(default_factory=lambda: os.environ.get(name, default))


def _env_bool_field(name: str, default: str = "false"):
    def factory() -> bool:
        value = os.environ.get(name, default).lower()
        if value in ("1", "true", "yes"):
            return True
        if value in ("0", "false", "no"):
            return False
        raise ValueError(f"unexpected value for {name!r}: {value!r}")

    return field(default_factory=factory)


def _env_int_field(name: str, default: int):
    return field(
        default_factory=lambda: int(os.environ.get(name, str(default)) or default)
    )


@dataclass
class PathwayConfig:
    threads: int = _env_int_field("PATHWAY_THREADS", 1)
    processes: int = _env_int_field("PATHWAY_PROCESSES", 1)
    process_id: int = _env_int_field("PATHWAY_PROCESS_ID", 0)
    first_port: int = _env_int_field("PATHWAY_FIRST_PORT", 10000)
    run_id: str | None = _env_field("PATHWAY_RUN_ID")
    license_key: str | None = _env_field("PATHWAY_LICENSE_KEY")
    monitoring_server: str | None = _env_field("PATHWAY_MONITORING_SERVER")
    replay_storage: str | None = _env_field("PATHWAY_REPLAY_STORAGE")
    snapshot_access: str | None = _env_field("PATHWAY_SNAPSHOT_ACCESS")
    persistence_mode: str | None = _env_field("PATHWAY_PERSISTENCE_MODE")
    continue_after_replay: bool = _env_bool_field("PATHWAY_CONTINUE_AFTER_REPLAY")
    ignore_asserts: bool = _env_bool_field("PATHWAY_IGNORE_ASSERTS")
    runtime_typechecking: bool = _env_bool_field("PATHWAY_RUNTIME_TYPECHECKING")
    terminate_on_error: bool = _env_bool_field(
        "PATHWAY_TERMINATE_ON_ERROR", "true"
    )

    @property
    def replay_config(self):
        if self.replay_storage is None:
            return None
        from pathway_tpu import persistence

        return persistence.Config(
            backend=persistence.Backend.filesystem(self.replay_storage)
        )


pathway_config = PathwayConfig()

# Per-thread overlay used by the emulated-rank CI lane (scripts/
# ci_lanes.sh): companion ranks run as THREADS of one test process, each
# seeing its own process_id/processes/first_port while the global config
# stays untouched. Real multi-process runs never set this.
import contextvars as _contextvars

_thread_overlay: "_contextvars.ContextVar[dict | None]" = (
    _contextvars.ContextVar("pathway_config_overlay", default=None)
)


class _OverlaidConfig:
    __slots__ = ("_base", "_overlay")

    def __init__(self, base: PathwayConfig, overlay: dict):
        self._base = base
        self._overlay = overlay

    def __getattr__(self, name):
        if name in self._overlay:
            return self._overlay[name]
        return getattr(self._base, name)


def push_config_overlay(**kwargs):
    """Set per-thread config fields; returns a token for reset."""
    return _thread_overlay.set(kwargs)


def pop_config_overlay(token) -> None:
    _thread_overlay.reset(token)


def get_pathway_config() -> PathwayConfig:
    overlay = _thread_overlay.get()
    if overlay:
        return _OverlaidConfig(pathway_config, overlay)  # type: ignore
    return pathway_config


def set_license_key(key: str | None) -> None:
    """reference: pw.set_license_key — entitlements are not enforced in
    this build (no keygen.sh round trips); the key is recorded for config
    surface parity."""
    pathway_config.license_key = key


def set_monitoring_config(*, server_endpoint: str | None = None, **kwargs) -> None:
    pathway_config.monitoring_server = server_endpoint


def _check_entitlements(*entitlements: str) -> bool:
    """reference: internals/config.py:105 — always granted here."""
    return True
