"""Hand-rolled OTLP/HTTP JSON exporter (reference: src/engine/telemetry.rs
— OTLP exporters with process memory/CPU gauges and input/output latency,
60 s periodic reader at telemetry.rs:38-45; Python side
graph_runner/telemetry.py).

No OpenTelemetry SDK required: spans and gauges are encoded directly as
OTLP/HTTP JSON (`/v1/traces`, `/v1/metrics` per the OTLP spec) and POSTed
with urllib on a background thread. Activated by
``pw.set_monitoring_config(server_endpoint=...)`` / PATHWAY_MONITORING_SERVER.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import urllib.request
from typing import Any

_SERVICE = "pathway_tpu"


def _resource() -> dict:
    return {
        "attributes": [
            {"key": "service.name", "value": {"stringValue": _SERVICE}},
            {"key": "process.pid", "value": {"intValue": str(os.getpid())}},
        ]
    }


def _attr_value(v: Any) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _attrs(d: dict) -> list[dict]:
    return [{"key": k, "value": _attr_value(v)} for k, v in d.items()]


class OtlpHttpExporter:
    """POSTs OTLP JSON payloads; failures are swallowed (telemetry must
    never take the pipeline down) but counted for tests/diagnostics."""

    def __init__(self, endpoint: str, timeout: float = 5.0):
        endpoint = endpoint.rstrip("/")
        if not endpoint.startswith(("http://", "https://")):
            endpoint = "http://" + endpoint
        self.endpoint = endpoint
        self.timeout = timeout
        self.sent = 0
        self.errors = 0

    def _post(self, path: str, payload: dict) -> bool:
        req = urllib.request.Request(
            self.endpoint + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
            self.sent += 1
            return True
        except Exception:
            self.errors += 1
            return False

    def export_spans(self, spans: list[dict]) -> bool:
        if not spans:
            return True
        payload = {
            "resourceSpans": [
                {
                    "resource": _resource(),
                    "scopeSpans": [
                        {"scope": {"name": _SERVICE}, "spans": spans}
                    ],
                }
            ]
        }
        return self._post("/v1/traces", payload)

    def export_gauges(self, gauges: dict[str, float], unit: str = "") -> bool:
        now = str(time.time_ns())
        metrics = [
            {
                "name": name,
                "unit": unit,
                "gauge": {
                    "dataPoints": [
                        {"timeUnixNano": now, "asDouble": float(value)}
                    ]
                },
            }
            for name, value in gauges.items()
        ]
        payload = {
            "resourceMetrics": [
                {
                    "resource": _resource(),
                    "scopeMetrics": [
                        {"scope": {"name": _SERVICE}, "metrics": metrics}
                    ],
                }
            ]
        }
        return self._post("/v1/metrics", payload)


def process_gauges() -> dict[str, float]:
    """Process memory/CPU gauges (reference: telemetry.rs:41-45)."""
    import resource as _res

    ru = _res.getrusage(_res.RUSAGE_SELF)
    gauges = {
        "process.memory.usage": float(ru.ru_maxrss * 1024),
        "process.cpu.utime": float(ru.ru_utime),
        "process.cpu.stime": float(ru.ru_stime),
    }
    try:
        with open("/proc/self/statm") as f:
            gauges["process.memory.rss"] = (
                float(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
            )
    except OSError:
        pass
    return gauges


class OtlpTelemetry:
    """Span recorder + periodic metrics pusher over OtlpHttpExporter.

    Matches internals.telemetry.Telemetry's span() contract so the graph
    runner can use either interchangeably.
    """

    def __init__(
        self,
        endpoint: str,
        *,
        stats=None,
        interval_s: float = 60.0,
        autostart_metrics: bool = True,
    ):
        import queue as _queue

        self.exporter = OtlpHttpExporter(endpoint)
        self.stats = stats
        self.interval_s = interval_s
        self._trace_id = os.urandom(16).hex()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # spans export on a background worker so an unreachable collector
        # never stalls the pipeline (the POST timeout would otherwise be
        # paid inline in the span context manager)
        self._span_queue: "_queue.Queue" = _queue.Queue()
        self._span_worker = threading.Thread(
            target=self._span_loop, name="pw-otlp-spans", daemon=True
        )
        self._span_worker.start()
        if autostart_metrics:
            self.start_metrics_thread()

    def _span_loop(self) -> None:
        while True:
            span = self._span_queue.get()
            try:
                if span is not None:
                    self.exporter.export_spans([span])
            finally:
                self._span_queue.task_done()

    def flush(self, timeout: float = 5.0) -> None:
        """Best-effort wait for queued spans to be exported. Waits on
        task COMPLETION (unfinished_tasks, decremented by the worker's
        task_done after the POST), not queue emptiness — the worker
        dequeues a span before exporting it, so an empty queue can
        still have the last span's POST in flight (a caller tearing
        down its collector right after flush() would lose it)."""
        deadline = time.monotonic() + timeout
        q = self._span_queue
        while q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.01)

    def drain(self, node_spans=None, timeout: float = 5.0) -> None:
        """Flush-on-shutdown (graph_runner calls this after every run):
        the metrics thread pushes on a 60 s cadence, so a short run
        would exit with its gauges never exported and its spans still
        queued — push the gauges once, enqueue the flight recorder's
        per-node aggregate spans (same OTLP channel as the build/run
        spans), and wait out the span queue. The periodic thread keeps
        running — the telemetry object is cached per endpoint and
        reused by later runs in the same process."""
        for s in node_spans or ():
            self._span_queue.put(
                {
                    "traceId": self._trace_id,
                    "spanId": os.urandom(8).hex(),
                    "name": s["name"],
                    "kind": 1,
                    "startTimeUnixNano": str(int(s["start_ns"])),
                    "endTimeUnixNano": str(int(s["end_ns"])),
                    "attributes": _attrs(s.get("attrs", {})),
                    "status": {"code": 1},
                }
            )
        try:
            self.push_metrics_once()
        except Exception:
            pass
        self.flush(timeout)

    # -- spans ------------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        start = time.time_ns()
        span_id = os.urandom(8).hex()
        try:
            yield None
            status = {"code": 1}  # OK
        except BaseException:
            status = {"code": 2}  # ERROR
            raise
        finally:
            self._span_queue.put(
                {
                    "traceId": self._trace_id,
                    "spanId": span_id,
                    "name": name,
                    "kind": 1,
                    "startTimeUnixNano": str(start),
                    "endTimeUnixNano": str(time.time_ns()),
                    "attributes": _attrs(attributes),
                    "status": status,
                }
            )

    # -- metrics ----------------------------------------------------------
    def collect_gauges(self) -> dict[str, float]:
        gauges = process_gauges()
        stats = self.stats
        if stats is not None:
            try:
                gauges["input_latency_ms"] = float(stats.input_latency_ms())
                gauges["output_latency_ms"] = float(stats.output_latency_ms())
            except Exception:
                pass
        return gauges

    def push_metrics_once(self) -> bool:
        return self.exporter.export_gauges(self.collect_gauges())

    def start_metrics_thread(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.interval_s):
                self.push_metrics_once()

        self._thread = threading.Thread(
            target=loop, name="pw-otlp-metrics", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
