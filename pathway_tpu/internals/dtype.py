"""Type lattice for column dtypes.

TPU-native rebuild of the reference's dtype system (reference:
python/pathway/internals/dtype.py, 979 LoC).  We keep the same user-facing
lattice — ANY at the top, concrete scalar types below, composites
(List/Tuple/Array), Optional as a union with NONE — but the implementation is
a fresh, small singleton-based design.  Machine representation decisions
(numpy/JAX dtypes for the dense path) live in :mod:`pathway_tpu.engine`.
"""

from __future__ import annotations

import datetime
import typing
from typing import Any, Iterable

import numpy as np


class DType:
    """Base of all dtypes. Concrete singletons are created below."""

    _name: str

    def __repr__(self) -> str:
        return self._name

    def is_optional(self) -> bool:
        return False

    def wrapped(self) -> DType:
        return self

    # -- lattice ---------------------------------------------------------
    def is_subtype_of(self, other: DType) -> bool:
        if other is ANY or self == other:
            return True
        if isinstance(other, _OptionalDType):
            if self is NONE:
                return True
            inner = self.wrapped() if isinstance(self, _OptionalDType) else self
            return inner.is_subtype_of(other.wrapped())
        if self is INT and other is FLOAT:
            return True
        if isinstance(self, _OptionalDType):
            return False
        return False

    def __eq__(self, other: object) -> bool:
        return self is other

    def __hash__(self) -> int:
        return id(self)


class _SimpleDType(DType):
    def __init__(self, name: str):
        self._name = name


class _OptionalDType(DType):
    _cache: dict[DType, _OptionalDType] = {}

    def __new__(cls, wrapped: DType) -> _OptionalDType:
        if wrapped in cls._cache:
            return cls._cache[wrapped]
        self = super().__new__(cls)
        self._wrapped = wrapped
        self._name = f"Optional({wrapped!r})"
        cls._cache[wrapped] = self
        return self

    def is_optional(self) -> bool:
        return True

    def wrapped(self) -> DType:
        return self._wrapped

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _OptionalDType) and other._wrapped == self._wrapped

    def __hash__(self) -> int:
        return hash(("Optional", self._wrapped))


class _TupleDType(DType):
    def __init__(self, args: tuple[DType, ...]):
        self.args = args
        self._name = f"Tuple({', '.join(map(repr, args))})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _TupleDType) and other.args == self.args

    def __hash__(self) -> int:
        return hash(("Tuple", self.args))


class _ListDType(DType):
    def __init__(self, arg: DType):
        self.arg = arg
        self._name = f"List({arg!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _ListDType) and other.arg == self.arg

    def __hash__(self) -> int:
        return hash(("List", self.arg))


class _ArrayDType(DType):
    """N-dimensional numeric array column (reference dtype.Array)."""

    def __init__(self, n_dim: int | None = None, wrapped: DType | None = None):
        self.n_dim = n_dim
        self.element_type = wrapped
        self._name = f"Array({n_dim}, {wrapped!r})"

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, _ArrayDType)
            and other.n_dim == self.n_dim
            and other.element_type == self.element_type
        )

    def __hash__(self) -> int:
        return hash(("Array", self.n_dim, self.element_type))

    def is_subtype_of(self, other: DType) -> bool:
        if isinstance(other, _ArrayDType):
            dim_ok = other.n_dim is None or other.n_dim == self.n_dim
            el_ok = other.element_type is None or other.element_type == self.element_type
            return dim_ok and el_ok
        return super().is_subtype_of(other)


class _CallableDType(DType):
    def __init__(self, arg_types, return_type):
        self.arg_types = arg_types
        self.return_type = return_type
        self._name = f"Callable(..., {return_type!r})"

    def __eq__(self, other):
        return (
            isinstance(other, _CallableDType)
            and other.arg_types == self.arg_types
            and other.return_type == self.return_type
        )

    def __hash__(self):
        return hash(("Callable", tuple(self.arg_types or ()), self.return_type))


class _PointerDType(DType):
    def __init__(self, *args):
        self.args = args
        self._name = "Pointer" if not args else f"Pointer({args})"

    def __eq__(self, other):
        return isinstance(other, _PointerDType)

    def __hash__(self):
        return hash("Pointer")


ANY = _SimpleDType("ANY")
NONE = _SimpleDType("NONE")
BOOL = _SimpleDType("BOOL")
INT = _SimpleDType("INT")
FLOAT = _SimpleDType("FLOAT")
STR = _SimpleDType("STR")
BYTES = _SimpleDType("BYTES")
JSON = _SimpleDType("JSON")
DATE_TIME_NAIVE = _SimpleDType("DATE_TIME_NAIVE")
DATE_TIME_UTC = _SimpleDType("DATE_TIME_UTC")
DURATION = _SimpleDType("DURATION")
PY_OBJECT_WRAPPER = _SimpleDType("PY_OBJECT_WRAPPER")
POINTER = _PointerDType()
ANY_TUPLE = _SimpleDType("ANY_TUPLE")
ANY_ARRAY = _ArrayDType(None, None)
INT_ARRAY = _ArrayDType(None, INT)
FLOAT_ARRAY = _ArrayDType(None, FLOAT)


def Optional(wrapped: DType) -> DType:
    if wrapped is ANY or isinstance(wrapped, _OptionalDType) or wrapped is NONE:
        return wrapped
    return _OptionalDType(wrapped)


def Tuple(*args: DType) -> DType:
    return _TupleDType(tuple(args))


def List(arg: DType) -> DType:
    return _ListDType(arg)


def Array(n_dim: int | None = None, wrapped: DType | None = None) -> DType:
    return _ArrayDType(n_dim, wrapped)


def Callable(arg_types=..., return_type=ANY) -> DType:
    return _CallableDType(arg_types, return_type)


def Pointer(*args) -> DType:
    return _PointerDType(*args)


_PY_TYPE_MAP: dict[Any, DType] = {
    int: INT,
    float: FLOAT,
    bool: BOOL,
    str: STR,
    bytes: BYTES,
    type(None): NONE,
    datetime.datetime: DATE_TIME_NAIVE,
    datetime.timedelta: DURATION,
    # pw.DateTimeNaive/DateTimeUtc/Duration (pandas-extending classes,
    # internals/datetime_types.py) resolve via wrap()'s subclass checks
    np.ndarray: ANY_ARRAY,
    dict: JSON,
    Any: ANY,
    typing.Any: ANY,
}


def wrap(input_type: Any) -> DType:
    """Convert a python type annotation / dtype-ish object to a DType."""
    if isinstance(input_type, DType):
        return input_type
    if input_type is None:
        return NONE
    if input_type in _PY_TYPE_MAP:
        return _PY_TYPE_MAP[input_type]
    origin = typing.get_origin(input_type)
    if origin is typing.Union:
        args = typing.get_args(input_type)
        non_none = [a for a in args if a is not type(None)]
        inner = wrap(non_none[0]) if len(non_none) == 1 else ANY
        if type(None) in args:
            return Optional(inner)
        return inner
    if origin in (list, typing.List):
        args = typing.get_args(input_type)
        return List(wrap(args[0])) if args else List(ANY)
    if origin in (tuple, typing.Tuple):
        args = typing.get_args(input_type)
        if not args:
            return ANY_TUPLE
        if len(args) == 2 and args[1] is Ellipsis:
            return List(wrap(args[0]))
        return Tuple(*(wrap(a) for a in args))
    from pathway_tpu.internals.api import Json as JsonCls, Pointer as PointerCls

    if isinstance(input_type, type) and issubclass(input_type, PointerCls):
        return POINTER
    if isinstance(input_type, type) and issubclass(input_type, JsonCls):
        return JSON
    if isinstance(input_type, type):
        # user-facing datetime classes (internals/datetime_types.py):
        # pw.DateTimeNaive / pw.DateTimeUtc / pw.Duration annotations
        from pathway_tpu.internals import datetime_types as _dtt

        if issubclass(input_type, _dtt.Duration):
            return DURATION
        if issubclass(input_type, _dtt.DateTimeUtc):
            return DATE_TIME_UTC
        if issubclass(input_type, _dtt.DateTimeNaive):
            return DATE_TIME_NAIVE
        import pandas as _pd

        if issubclass(input_type, _pd.Timedelta):
            return DURATION
        if issubclass(input_type, _pd.Timestamp):
            return DATE_TIME_NAIVE
    return ANY


def dtype_of_value(value: Any) -> DType:
    from pathway_tpu.internals.api import Json, Pointer as PointerCls, PyObjectWrapper

    if value is None:
        return NONE
    if isinstance(value, bool) or isinstance(value, np.bool_):
        return BOOL
    if isinstance(value, (int, np.integer)):
        return INT
    if isinstance(value, (float, np.floating)):
        return FLOAT
    if isinstance(value, str):
        return STR
    if isinstance(value, bytes):
        return BYTES
    if isinstance(value, PointerCls):
        return POINTER
    if isinstance(value, datetime.datetime):
        return DATE_TIME_UTC if value.tzinfo is not None else DATE_TIME_NAIVE
    if isinstance(value, datetime.timedelta):
        return DURATION
    if isinstance(value, np.ndarray):
        if np.issubdtype(value.dtype, np.integer):
            return Array(value.ndim, INT)
        if np.issubdtype(value.dtype, np.floating):
            return Array(value.ndim, FLOAT)
        return Array(value.ndim, ANY)
    if isinstance(value, Json):
        return JSON
    if isinstance(value, (dict, list)):
        return JSON
    if isinstance(value, tuple):
        return Tuple(*(dtype_of_value(v) for v in value))
    if isinstance(value, PyObjectWrapper):
        return PY_OBJECT_WRAPPER
    return ANY


def lub(*types: DType) -> DType:
    """Least upper bound of dtypes in the lattice."""
    result: DType | None = None
    for t in types:
        if result is None:
            result = t
        elif t.is_subtype_of(result):
            pass
        elif result.is_subtype_of(t):
            result = t
        elif result is NONE:
            result = Optional(t)
        elif t is NONE:
            result = Optional(result)
        elif {result.wrapped(), t.wrapped()} <= {INT, FLOAT} and (
            result.is_optional() or t.is_optional()
        ):
            result = Optional(FLOAT)
        else:
            return ANY
    return result if result is not None else ANY


def types_lca(a: DType, b: DType, raising: bool = False) -> DType:
    out = lub(a, b)
    if raising and out is ANY and a is not ANY and b is not ANY:
        raise TypeError(f"no common supertype of {a} and {b}")
    return out


def normalize_default(dtypes: Iterable[DType]) -> DType:
    return lub(*dtypes)


def unoptionalize(t: DType) -> DType:
    return t.wrapped() if isinstance(t, _OptionalDType) else t
