"""Deterministic fault-injection harness (reference pattern: the
wordcount integration battery's kill-at-phase loop,
integration_tests/wordcount/ — generalized into named in-process
injection points so crash/recovery scenarios replay bit-identically).

Injection points threaded through the hot paths:

    connector.read                  per message a connector subject emits
    connector.flush                 per connector flush (timer or commit)
    persistence.journal_write       before a journal batch is appended
    persistence.journal_write.post  after the append is durable, before
                                    control returns to the engine loop
                                    (crash here = journaled, never accepted)
    persistence.checkpoint          before an operator snapshot / subject
                                    state write
    runtime.step                    per engine timestamp step
    mesh.send                       per mesh frame sent (procgroup.py
                                    send/send_exchange)
    mesh.recv                       per mesh recv (collectives included)
    mesh.rank_kill                  phase-tagged kill slots on the
                                    distributed recovery path: the runtime
                                    hits it with ``phase=`` context at
                                    ``wave_send`` (before an exchange
                                    wave's frames ship), ``post_snapshot``
                                    (rank-local snapshot written, commit
                                    marker not yet moved) and ``restore``
                                    (distributed snapshot restore after
                                    the marker tag is agreed)
    serve.dispatch                  per serving batch window, phase-tagged:
                                    ``window`` (window formed, upserts not
                                    yet committed) and ``committed`` (the
                                    window's commit applied, responses not
                                    yet delivered) — the serve chaos lane
                                    kills mid-dispatch here
    serve.park                      per request parked by the serving
                                    frontend at backend loss
    serve.replay                    per parked request replayed into the
                                    first window of epoch+1
    sink.stage                      per staged egress segment (a
                                    transactional sink sealing one
                                    commit's rows into its staging area,
                                    io/txn.py — crash here = staged
                                    output the next recovery discards)
    sink.finalize                   per staged unit becoming externally
                                    visible (marker landed; crash here =
                                    marker moved but the unit still
                                    pending — recovery must FINALIZE it)
    sink.recover                    per sink recovery scan at restore
                                    (crash here = recovery repeats —
                                    double recovery must be idempotent)
    device.dispatch                 per supervised device dispatch
                                    (internals/device.py
                                    supervised_dispatch — the KNN
                                    search/write sites and the fused
                                    ingest chain), with ``site=``
                                    context; a retryable raise here
                                    exercises the bounded-backoff retry
                                    classifier, a delay longer than
                                    PATHWAY_DEVICE_DISPATCH_TIMEOUT_S
                                    trips the watchdog
    device.h2d                      per host->device staging copy
                                    (ops/ingest.py tokenize-ahead
                                    producer)
    device.oom                      HBM growth attempts
                                    (KnnShard._grow_to /
                                    ShardedKnnIndex._grow_to_local): a
                                    raise here emulates allocator
                                    RESOURCE_EXHAUSTED — growth refuses
                                    and the serving breaker browns out
    device.snapshot                 per index snapshot cut, phase-tagged
                                    ``cut`` (before any segment write)
                                    and ``post_segment`` (segment
                                    durable, manifest not yet part of a
                                    committed cut) — the --device grid
                                    kills both sides of the boundary
    device.restore                  per index restore-from-segments
                                    (phase ``restore``)
    mesh.slow                       straggler injection slots on the wave
                                    path (never crashes — pair with the
                                    ``delay`` action): the runtime hits it
                                    with ``phase="wave_send"`` (slices
                                    prepared, frames about to ship — a
                                    delay here stalls this rank's sends so
                                    every peer's recv-wait points at it)
                                    and ``phase="step"`` (once per engine
                                    timestamp step — a compute-side drag)
    mem.pressure                    per memory-accountant sample
                                    (internals/memory.py sample(), phase
                                    ``sample``): a ``raise`` here is
                                    CAUGHT by the accountant and read as
                                    a synthetic over-high-watermark
                                    sample — the ladder steps up at
                                    exactly the listed hits, which is
                                    how the pacing checker's traces and
                                    the ``fault_matrix --pressure`` grid
                                    replay pressure episodes
                                    deterministically; ``crash`` kills
                                    the rank mid-pressure as usual

A *plan* is a schedule of rules. Each rule names a point, when it fires —
explicit 1-based ``hits``, a modular ``every``, or a seeded probability
``prob`` (drawn from ``random.Random(seed ^ rule_index)`` so the draw
sequence replays exactly) — and an action: ``raise`` throws
:class:`InjectedFault` (retryable unless ``retryable: false``, so the
connector supervisor's default classifier fails fast on it), ``crash``
hard-kills the process via ``os._exit`` (default exit code
``CRASH_EXIT_CODE``), ``delay`` sleeps ``delay_ms`` milliseconds and
returns normally — the straggler injection the N-rank scaling lanes use
(a ``rank``-scoped ``mesh.slow`` delay rule makes exactly one rank
deterministically slow, with no crash and no semantic change, so the
critical-path analyzer's straggler attribution is replayable like every
other fault). Hit counters are global per point and deterministic
given the program's emit/commit order — with the one caveat that
``connector.flush`` also counts wall-clock autocommit flushes, so exact-
hit plans against it are only fully deterministic when autocommit is
disabled (``autocommit_duration_ms=None``); the other points count only
program-ordered events.

Multi-rank schedules: a rule may carry ``"phase"`` (matches only hits
whose call-site context has that phase, counted on a per-(point, phase)
counter so kill-phase schedules stay deterministic regardless of how
phases interleave) and ``"rank"`` (fires only in the process whose
``pathway_config.process_id`` matches — one shared ``PATHWAY_FAULT_PLAN``
can then name its victim rank, which is how the mesh supervisor smoke
kills exactly one rank of a supervised run).

Plans come from the ``PATHWAY_FAULT_PLAN`` env var (inline JSON, or a
path to a JSON file) or programmatically via
``install_plan()``/``clear_plan()``::

    PATHWAY_FAULT_PLAN='{"seed": 7, "rules": [
        {"point": "persistence.journal_write", "hits": [2], "action": "crash"}
    ]}'

The disabled fast path is two attribute loads — safe on per-row paths.
"""

from __future__ import annotations

import json
import os
import random
import threading
from typing import Any

CRASH_EXIT_CODE = 27

POINTS = (
    "connector.read",
    "connector.flush",
    "persistence.journal_write",
    "persistence.journal_write.post",
    "persistence.checkpoint",
    "runtime.step",
    "mesh.send",
    "mesh.recv",
    "mesh.rank_kill",
    "serve.dispatch",
    "serve.park",
    "serve.replay",
    "mesh.slow",
    "sink.stage",
    "sink.finalize",
    "sink.recover",
    "device.dispatch",
    "device.h2d",
    "device.oom",
    "device.snapshot",
    "device.restore",
    "mem.pressure",
)

_ACTIONS = ("raise", "crash", "delay")


class InjectedFault(RuntimeError):
    """Raised by a firing ``raise`` rule. ``retryable`` feeds the
    connector supervisor's default classifier."""

    def __init__(self, point: str, hit: int, retryable: bool = True):
        super().__init__(f"injected fault at {point} (hit {hit})")
        self.point = point
        self.hit = hit
        self.retryable = retryable


class FaultRule:
    __slots__ = (
        "point", "hits", "every", "prob", "action", "retryable",
        "max_fires", "fired", "exit_code", "phase", "rank", "delay_ms",
        "_rng",
    )

    def __init__(
        self,
        point: str,
        hits=None,
        every: int | None = None,
        prob: float | None = None,
        action: str = "raise",
        retryable: bool = True,
        max_fires: int | None = None,
        exit_code: int = CRASH_EXIT_CODE,
        phase: str | None = None,
        rank: int | None = None,
        delay_ms: float = 0.0,
    ):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; use {_ACTIONS}")
        if point not in POINTS:
            # a typo'd point would silently never fire, making a crash-
            # recovery test pass vacuously
            raise ValueError(
                f"unknown injection point {point!r}; known points: {POINTS}"
            )
        self.point = point
        self.hits = set(hits) if hits is not None else None
        self.every = every
        self.prob = prob
        self.action = action
        self.retryable = retryable
        # crash rules fire at most once by nature; raise rules default to
        # one fire per listed hit unless max_fires widens/narrows it
        self.max_fires = max_fires
        self.fired = 0
        self.exit_code = exit_code
        # phase-scoped rules count hits on the (point, phase) counter so a
        # "second wave_send" schedule replays identically no matter how
        # other phases of the same point interleave with it
        self.phase = phase
        self.rank = rank
        # "delay" action: how long a firing rule stalls the caller (the
        # straggler knob; a non-positive delay makes the rule a no-op)
        self.delay_ms = float(delay_ms)
        self._rng: random.Random | None = None  # bound by the plan

    def applies(self, context: dict | None) -> bool:
        """Context filters that gate whether a hit is even considered:
        call-site phase and the firing process's mesh rank."""
        if self.phase is not None:
            if context is None or context.get("phase") != self.phase:
                return False
        if self.rank is not None:
            from pathway_tpu.internals.config import get_pathway_config

            if get_pathway_config().process_id != self.rank:
                return False
        return True

    def matches(self, hit: int) -> bool:
        if self.max_fires is not None and self.fired >= self.max_fires:
            return False
        if self.hits is not None:
            return hit in self.hits
        if self.every is not None:
            return hit % self.every == 0
        if self.prob is not None:
            # one deterministic draw per hit at this point, in hit order
            return self._rng.random() < self.prob
        return True  # unconditional: fires on every hit (cap via max_fires)


class FaultPlan:
    """Seeded, thread-safe schedule of fault rules with per-point hit
    counters. Deterministic: the same program order replays the same
    fires bit-identically."""

    def __init__(self, rules, seed: int = 0):
        self.rules = [
            r if isinstance(r, FaultRule) else FaultRule(**r) for r in rules
        ]
        self.seed = seed
        for i, rule in enumerate(self.rules):
            rule._rng = random.Random((seed << 8) ^ i)
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec: "FaultPlan | str | dict") -> "FaultPlan":
        if isinstance(spec, FaultPlan):
            return spec
        if isinstance(spec, str):
            spec = json.loads(spec)
        return cls(spec.get("rules", []), seed=int(spec.get("seed", 0)))

    def on_hit(self, point: str, context: dict | None = None):
        """Count a hit at `point`; return (rule, hit) if a rule fires.
        Hits with a ``phase`` in their context are additionally counted on
        a per-(point, phase) counter — phase-scoped rules match against
        THAT counter, so their schedules are deterministic per phase."""
        with self._lock:
            hit = self._counts.get(point, 0) + 1
            self._counts[point] = hit
            phase_hit = None
            phase = context.get("phase") if context else None
            if phase is not None:
                pkey = f"{point}#{phase}"
                phase_hit = self._counts.get(pkey, 0) + 1
                self._counts[pkey] = phase_hit
            for rule in self.rules:
                if rule.point != point or not rule.applies(context):
                    continue
                h = phase_hit if rule.phase is not None else hit
                if h is not None and rule.matches(h):
                    rule.fired += 1
                    return rule, h
        return None

    def hit_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)


_active: FaultPlan | None = None
_env_checked = False


def install_plan(spec) -> FaultPlan | None:
    """Install a plan programmatically (FaultPlan, dict spec, or JSON
    string); None uninstalls. Returns the active plan."""
    global _active, _env_checked
    _active = FaultPlan.from_spec(spec) if spec is not None else None
    _env_checked = True  # programmatic choice wins over the env var
    return _active


def clear_plan() -> None:
    install_plan(None)


def reset() -> None:
    """Forget any installed plan AND re-read PATHWAY_FAULT_PLAN on the
    next hit (test isolation helper)."""
    global _active, _env_checked
    _active = None
    _env_checked = False


def active_plan() -> FaultPlan | None:
    global _active, _env_checked
    if _active is not None or _env_checked:
        return _active
    _env_checked = True
    spec = os.environ.get("PATHWAY_FAULT_PLAN")
    if spec:
        if not spec.lstrip().startswith("{"):
            with open(spec) as f:
                spec = f.read()
        _active = FaultPlan.from_spec(spec)
    return _active


def fault_point(point: str, **context: Any) -> None:
    """Hot-path hook. No-op without an active plan; otherwise counts the
    hit and executes the first matching rule's action. Context keys the
    rules understand: ``phase`` (kill-phase schedules)."""
    if _active is None and _env_checked:
        return
    plan = active_plan()
    if plan is None:
        return
    fired = plan.on_hit(point, context or None)
    if fired is None:
        return
    rule, hit = fired
    if rule.action == "crash":
        os._exit(rule.exit_code)
    if rule.action == "delay":
        # straggler injection: stall, never raise — the run's semantics
        # (and its exactly-once audit) must be bit-identical to fault-free
        if rule.delay_ms > 0:
            import time as _time

            _time.sleep(rule.delay_ms / 1000.0)
        return
    raise InjectedFault(point, hit, retryable=rule.retryable)
