"""Reducers (reference: python/pathway/internals/reducers.py +
src/engine/reduce.rs ``enum Reducer``).

Each DSL reducer lowers to an engine function evaluated over a group's
multiset of argument combos.  The engine contract (GroupByNode): entries is a
list of ``(combo_tuple, count[, stamp])`` where ``combo_tuple[slot]`` is this
reducer's argument tuple ``(*args, order_token, row_key)`` — the order token
(the groupby ``sort_by`` value when given, else the row key) drives the tuple
reducer's ordering, the row key backs argmin/argmax, and ``stamp`` (the
engine ``(time, batch position)`` at multiset-entry creation) drives
earliest/latest, which rank by PROCESSING TIME like the reference
(EarliestReducer, reduce.rs:594) and ignore ``sort_by``.
Semigroup reducers (sum/count) could use running state; the rediff strategy
recomputes per touched group, which is exact and fast enough until the C++
core lands.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ERROR
from pathway_tpu.internals.expression import ColumnExpression, ReducerExpression


def _entries(ms, slot: int):
    items = ms.items() if hasattr(ms, "items") else ms
    for entry in items:  # (combo, count[, stamp])
        yield entry[0][slot], entry[1]


# full (non-abelian) reducers the C++ executor runs natively; the _sn
# variants are the skip_nones tuple forms
_NATIVE_FULL_CODES = frozenset(
    {
        "min", "max", "argmin", "argmax", "unique", "any", "earliest",
        "latest", "tuple", "tuple_sn", "sorted_tuple", "sorted_tuple_sn",
    }
)


class Reducer:
    def __init__(
        self,
        name: str,
        engine_fn_factory: Callable,
        return_type_fn=None,
        abelian_factory: Callable | None = None,
    ):
        self.name = name
        self._factory = engine_fn_factory
        self._return_type_fn = return_type_fn
        # abelian reducers (count/sum/avg) maintain O(1) running state per
        # group instead of rescanning the multiset (reference: semigroup
        # fast path, src/engine/reduce.rs:40 SemigroupReducerImpl)
        self._abelian_factory = abelian_factory

    def return_type(self, arg_types: list[dt.DType]) -> dt.DType:
        if self._return_type_fn is not None:
            return self._return_type_fn(arg_types)
        return arg_types[0] if arg_types else dt.ANY

    def engine_fn(self, **kwargs) -> Callable:
        return self._factory(**kwargs)

    def engine_spec(self, **kwargs):
        """("abelian", update(state, combo, diff), finish(state), init[,
        native_code]) when incremental maintenance applies, else ("full",
        fn[, native_code]). native_code marks specs the sharded C++
        executor (native/exec.cpp) runs natively: count/sum/avg keep O(1)
        abelian state; min/max keep an ordered value multiset per group;
        tuple/sorted_tuple/unique/any/argmin/argmax/earliest/latest are
        recomputed from the joint row multiset with GIL-free change
        fingerprints (reference: the full Reducer enum, reduce.rs:22-594).
        ndarray and stateful reducers stay on the Python path."""
        if self._abelian_factory is not None:
            spec = ("abelian",) + self._abelian_factory(**kwargs)
            if self.name in ("count", "sum", "avg"):
                spec = spec + (self.name,)
            return spec
        spec = ("full", self._factory(**kwargs))
        code = getattr(self, "_native_code", self.name)
        if code in _NATIVE_FULL_CODES:
            spec = spec + (code,)
        return spec

    def __call__(self, *args, **kwargs) -> ReducerExpression:
        return ReducerExpression(self, *args, **kwargs)

    def __repr__(self):
        return f"pathway.reducers.{self.name}"


# -- engine implementations ----------------------------------------------


def _count_factory(**kw):
    def fn(ms, slot):
        return builtins.sum(count for _, count in _entries(ms, slot))

    return fn


def _count_abelian(**kw):
    def update(state, combo, diff):
        return state + diff

    return (update, lambda s: s, 0)


def _sum_abelian(**kw):
    # state: [n_numeric, total, err_count] — n_numeric tracks live numeric
    # rows so full retraction returns None (matching the full reducer),
    # not a stale 0
    def update(state, combo, diff):
        v = combo[0]
        if state is None:
            state = [0, None, 0]
        if v is ERROR:
            state[2] += diff
        elif v is not None:
            contrib = v * diff
            state[1] = contrib if state[1] is None else state[1] + contrib
            state[0] += diff
        return state

    def finish(state):
        if state is None:
            return None
        if state[2] > 0:
            return ERROR
        return state[1] if state[0] > 0 else None

    return (update, finish, None)


def _avg_abelian(**kw):
    # state: [total, n, err_count]
    def update(state, combo, diff):
        v = combo[0]
        if state is None:
            state = [0.0, 0, 0]
        if v is ERROR:
            state[2] += diff
        elif v is not None:
            state[0] += v * diff
            state[1] += diff
        return state

    def finish(state):
        if state is None:
            return None
        if state[2] > 0:
            return ERROR  # error poison outranks emptiness (full-reducer parity)
        return state[0] / state[1] if state[1] else None

    return (update, finish, None)


def _sum_factory(**kw):
    def fn(ms, slot):
        # None entries are skipped (outer temporal windows pad unmatched
        # rows with None); an all-None group sums to None
        total = None
        for args, count in _entries(ms, slot):
            v = args[0]
            if v is ERROR:
                return ERROR
            if v is None:
                continue
            contrib = v * count
            total = contrib if total is None else total + contrib
        return total

    return fn


def _min_factory(**kw):
    def fn(ms, slot):
        vals = [args[0] for args, _ in _entries(ms, slot)]
        if builtins.any(v is ERROR for v in vals):
            return ERROR
        vals = [v for v in vals if v is not None]
        return builtins.min(vals) if vals else None

    return fn


def _max_factory(**kw):
    def fn(ms, slot):
        vals = [args[0] for args, _ in _entries(ms, slot)]
        if builtins.any(v is ERROR for v in vals):
            return ERROR
        vals = [v for v in vals if v is not None]
        return builtins.max(vals) if vals else None

    return fn


def _argmin_factory(**kw):
    def fn(ms, slot):
        best = builtins.min(_entries(ms, slot), key=lambda e: (e[0][0], e[0][-1]))
        return best[0][-1]

    return fn


def _argmax_factory(**kw):
    def fn(ms, slot):
        best = builtins.max(_entries(ms, slot), key=lambda e: (e[0][0], -e[0][-1]))
        return best[0][-1]

    return fn


def _unique_factory(**kw):
    def fn(ms, slot):
        distinct = {args[0] for args, _ in _entries(ms, slot)}
        if len(distinct) != 1:
            return ERROR
        return next(iter(distinct))

    return fn


def _any_factory(**kw):
    def fn(ms, slot):
        return builtins.min(_entries(ms, slot), key=lambda e: (e[0][-2], e[0][-1]))[0][0]

    return fn


def _avg_factory(**kw):
    def fn(ms, slot):
        total = 0.0
        n = 0
        for args, count in _entries(ms, slot):
            if args[0] is ERROR:
                return ERROR
            if args[0] is None:
                continue
            total += args[0] * count
            n += count
        return total / n if n else None

    return fn


def _sorted_tuple_factory(skip_nones: bool = False, **kw):
    def fn(ms, slot):
        vals = []
        for args, count in _entries(ms, slot):
            v = args[0]
            if skip_nones and v is None:
                continue
            vals.extend([v] * count)
        # None sorts FIRST (reference: Value::None is the smallest Value,
        # value.rs:208; pinned by test_common.py test_tuple_reducer)
        return builtins.tuple(
            builtins.sorted(
                vals, key=lambda v: (0, 0) if v is None else (1, v)
            )
        )

    return fn


def _tuple_factory(skip_nones: bool = False, **kw):
    def fn(ms, slot):
        entries = builtins.sorted(_entries(ms, slot), key=lambda e: (e[0][-2], e[0][-1]))
        vals = []
        for args, count in entries:
            v = args[0]
            if skip_nones and v is None:
                continue
            vals.extend([v] * count)
        return builtins.tuple(vals)

    return fn


def _ndarray_factory(skip_nones: bool = False, **kw):
    tup = _tuple_factory(skip_nones=skip_nones)

    def fn(ms, slot):
        return np.array(tup(ms, slot))

    return fn


def _stamped_entries(ms, slot: int):
    """(spec_combo, count, stamp) triples — stamp is the engine (time,
    batch position) recorded when the multiset entry was created."""
    items = ms.items() if hasattr(ms, "items") else ms
    for entry in items:
        combo, count = entry[0], entry[1]
        stamp = entry[2] if len(entry) > 2 else (0, 0)
        yield combo[slot], count, stamp


def _earliest_factory(**kw):
    # reference: EarliestReducer (reduce.rs:594) — the value with the
    # LOWEST processing time; row key breaks same-batch ties
    def fn(ms, slot):
        return builtins.min(
            _stamped_entries(ms, slot), key=lambda e: (e[2], e[0][-1])
        )[0][0]

    return fn


def _latest_factory(**kw):
    def fn(ms, slot):
        return builtins.max(
            _stamped_entries(ms, slot), key=lambda e: (e[2], e[0][-1])
        )[0][0]

    return fn


def _sum_return_type(arg_types: list[dt.DType]) -> dt.DType:
    if not arg_types:
        return dt.ANY
    t = arg_types[0]
    if t in (dt.INT, dt.FLOAT) or isinstance(t, dt._ArrayDType):
        return t
    return dt.ANY


count = Reducer("count", _count_factory, lambda ts: dt.INT, abelian_factory=_count_abelian)
sum = Reducer("sum", _sum_factory, _sum_return_type, abelian_factory=_sum_abelian)
min = Reducer("min", _min_factory)
max = Reducer("max", _max_factory)
argmin = Reducer("argmin", _argmin_factory, lambda ts: dt.POINTER)
argmax = Reducer("argmax", _argmax_factory, lambda ts: dt.POINTER)
unique = Reducer("unique", _unique_factory)
any = Reducer("any", _any_factory)
avg = Reducer("avg", _avg_factory, lambda ts: dt.FLOAT, abelian_factory=_avg_abelian)
earliest = Reducer("earliest", _earliest_factory)
latest = Reducer("latest", _latest_factory)
ndarray_reducer = Reducer(
    "ndarray", _ndarray_factory, lambda ts: dt.ANY_ARRAY
)


def sorted_tuple(arg, skip_nones: bool = False) -> ReducerExpression:
    r = Reducer(
        "sorted_tuple",
        lambda **kw: _sorted_tuple_factory(skip_nones=skip_nones),
        lambda ts: dt.List(ts[0]) if ts else dt.ANY_TUPLE,
    )
    r._native_code = "sorted_tuple_sn" if skip_nones else "sorted_tuple"
    return ReducerExpression(r, arg)


def tuple(arg, skip_nones: bool = False) -> ReducerExpression:  # noqa: A001
    r = Reducer(
        "tuple",
        lambda **kw: _tuple_factory(skip_nones=skip_nones),
        lambda ts: dt.List(ts[0]) if ts else dt.ANY_TUPLE,
    )
    r._native_code = "tuple_sn" if skip_nones else "tuple"
    return ReducerExpression(r, arg)


def ndarray(arg, skip_nones: bool = False) -> ReducerExpression:
    r = Reducer(
        "ndarray",
        lambda **kw: _ndarray_factory(skip_nones=skip_nones),
        lambda ts: dt.ANY_ARRAY,
    )
    return ReducerExpression(r, arg)


class StatefulReducer(Reducer):
    """pw.reducers.stateful_many / stateful_single (reference:
    custom_reducers.py; engine Reducer::Stateful)."""

    def __init__(self, combine_many: Callable, name="stateful_many"):
        self.combine_many = combine_many
        super().__init__(name, lambda **kw: None, lambda ts: dt.ANY)
        self.is_stateful = True


def stateful_many(combine_many: Callable) -> Callable:
    def wrapper(*args) -> ReducerExpression:
        return ReducerExpression(StatefulReducer(combine_many), *args)

    return wrapper


def stateful_single(combine_single: Callable) -> Callable:
    def combine_many(state, rows):
        for row, count in rows:
            if count > 0:
                for _ in range(count):
                    state = combine_single(state, *row)
        return state

    return stateful_many(combine_many)


def udf_reducer(reducer_cls):
    """@pw.reducers.udf_reducer over a BaseCustomAccumulator subclass."""

    def combine_many(state, rows):
        for row, count in rows:
            if count <= 0:
                continue
            for _ in range(count):
                neu = reducer_cls.from_row(list(row))
                state = neu if state is None else state.update(neu)
        return state

    def wrapper(*args) -> ReducerExpression:
        expr = ReducerExpression(
            StatefulReducer(combine_many, name="udf_reducer"), *args
        )
        expr._post_process = lambda acc: acc.compute_result() if acc is not None else None
        return expr

    return wrapper


# deprecated reference spellings (reference: reducers.py int_sum/npsum)
int_sum = sum
npsum = sum
