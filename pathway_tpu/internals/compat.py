"""Top-level API compatibility surface (reference:
python/pathway/__init__.py __all__ — aliases and small helpers that
round out the `import pathway as pw` drop-in surface)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import Schema, schema_from_types


# -- pw.Type / pw.PersistenceMode ------------------------------------------

Type = dt.DType


class PersistenceMode:
    """reference: api.PersistenceMode (engine.pyi:776)."""

    PERSISTING = "PERSISTING"
    OPERATOR_PERSISTING = "OPERATOR_PERSISTING"
    BATCH = "BATCH"
    REALTIME_REPLAY = "REALTIME_REPLAY"
    SPEEDRUN_REPLAY = "SPEEDRUN_REPLAY"
    UDF_CACHING = "UDF_CACHING"


# -- custom accumulators ----------------------------------------------------


class BaseCustomAccumulator(ABC):
    """reference: internals/custom_reducers.py:174 — subclass with
    from_row/update/compute_result (+ optional neutral/retract) and use via
    pw.reducers.udf_reducer(MyAccumulator)."""

    @classmethod
    @abstractmethod
    def from_row(cls, row: list) -> "BaseCustomAccumulator": ...

    @abstractmethod
    def update(self, other: "BaseCustomAccumulator") -> "BaseCustomAccumulator": ...

    @abstractmethod
    def compute_result(self) -> Any: ...


# -- schema helpers ---------------------------------------------------------


@dataclass(frozen=True)
class SchemaProperties:
    append_only: bool = False


def schema_from_csv(path: str, *, name: str = "schema_from_csv",
                    num_parsed_rows: int | None = 20, **kwargs) -> type[Schema]:
    """Infer a schema from a CSV file's header + sampled rows (reference:
    schema.py schema_from_csv)."""
    import csv as _csv

    with open(path, newline="") as f:
        reader = _csv.DictReader(f)
        names = reader.fieldnames or []
        samples: list[dict] = []
        for i, rec in enumerate(reader):
            if num_parsed_rows is not None and i >= num_parsed_rows:
                break
            samples.append(rec)
    cols = {}
    for cname in names:
        vals = [_coerce(r.get(cname)) for r in samples]
        cols[cname] = (
            dt.lub(*(dt.dtype_of_value(v) for v in vals)) if vals else dt.ANY
        )
    return schema_from_types(**cols)


def _coerce(v):
    if v is None:
        return None
    for cast in (int, float):
        try:
            return cast(v)
        except (TypeError, ValueError):
            pass
    return v


def assert_table_has_schema(
    table, schema: type[Schema], *, allow_superset: bool = False, **kwargs
) -> None:
    """reference: assert_table_has_schema — column-name (and presence)
    validation at declaration time."""
    expected = set(schema.column_names())
    actual = set(table.column_names())
    if allow_superset:
        missing = expected - actual
        if missing:
            raise AssertionError(
                f"table is missing columns {sorted(missing)}"
            )
    elif expected != actual:
        raise AssertionError(
            f"table columns {sorted(actual)} != schema columns "
            f"{sorted(expected)}"
        )


# -- decorators / free functions -------------------------------------------


def table_transformer(func: Callable) -> Callable:
    """reference: internals/common.py:520 — marks a Table -> Table
    function; a passthrough here (argument checking is dynamic)."""
    return func


def join(left, right, *on, **kwargs):
    return left.join(right, *on, **kwargs)


def join_inner(left, right, *on, **kwargs):
    return left.join_inner(right, *on, **kwargs)


def join_left(left, right, *on, **kwargs):
    return left.join_left(right, *on, **kwargs)


def join_right(left, right, *on, **kwargs):
    return left.join_right(right, *on, **kwargs)


def join_outer(left, right, *on, **kwargs):
    return left.join_outer(right, *on, **kwargs)


def groupby(table, *args, **kwargs):
    return table.groupby(*args, **kwargs)


def iterate_universe(body, **kwargs):
    """reference: iterate_universe — universe-changing fixed point; our
    iterate already permits key-set changes across iterations."""
    from pathway_tpu.internals.iterate import iterate

    return iterate(body, **kwargs)


def local_error_log():
    """reference: local_error_log — per-scope error log; scopes are not
    nested here, so this is the global log."""
    from pathway_tpu.internals.error_log import global_error_log

    return global_error_log()
