"""Cluster metrics plane (ISSUE 10): one merged view over every rank.

Per-rank OpenMetrics endpoints (``internals/monitoring.py``, port
``20000 + process_id``) are islands: nothing aggregates them, so
multi-rank runs have no single place that answers "where is the mesh's
wall-clock going" — the visibility ROADMAP item 3 needs before the mesh
scales past 2 ranks. This module is the aggregation layer:
:class:`ClusterMetricsAggregator` periodically scrapes every rank's
``/metrics``, relabels each sample with ``rank="r"``, and serves ONE
merged ``/metrics/cluster`` view plus derived cluster gauges:

* ``cluster_ranks`` / ``cluster_ranks_expected`` — live-scraped vs
  configured world size (a rank that misses a scrape drops out of the
  view but its last-seen samples are retained and marked stale);
* ``mesh_skew_seconds`` — max−min across ranks of cumulative exchange
  recv-wait. Semantics: every wave ends in a rendezvous, so the rank
  that finishes its own work LAST waits least and everyone else's wait
  absorbs the spread — the cumulative (max−min) of per-rank recv-wait
  is the total per-wave finish spread the fastest rank lost to the
  slowest. (Exact per-wave skew lives in the trace-based analyzer,
  ``python -m pathway_tpu.analysis --critical-path``.)
* ``cluster_rows_per_s`` — ingest throughput over the aggregator's own
  observation window (Δ connector rows / Δ time between scrapes);
* ``scaling_efficiency`` — ``cluster_rows_per_s / (baseline × world)``
  when a 1-rank baseline is configured
  (``PATHWAY_CLUSTER_BASELINE_ROWS_PER_S``); 1.0 = perfect linear
  scaling, the number every scaling PR is judged on;
* the exchange **byte matrix**: per-rank ``exchange_peer_bytes_total``
  samples pass through with the scraping rank's label added, so
  ``{rank="0",peer="1"}`` reads "bytes rank 0 shipped to rank 1".

Ownership: the :class:`~pathway_tpu.parallel.supervisor.MeshSupervisor`
hosts the aggregator ACROSS epochs when it owns the rank set
(``--cluster-metrics PORT``) — rank endpoints are re-resolved on every
respawn, so a rollback is a scrape blip, not a dead dashboard. An
unsupervised multi-rank run hosts it on rank 0 instead
(``PATHWAY_CLUSTER_METRICS_PORT``, engine/runtime.py
``_start_monitoring``), which also feeds the TUI dashboard's per-rank
section via :meth:`ClusterMetricsAggregator.summary`.

This module is deliberately stdlib-only and file-path-loadable (like
``parallel/protocol.py`` and ``io/http/_frontend.py``): the supervisor
loads it without executing the package ``__init__``s, keeping
import-light drivers (scripts/fault_matrix.py) jax-free.
"""

from __future__ import annotations

import http.server
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Iterable

# metric families whose per-rank samples the cluster view re-exports
# with a rank label. Everything else a rank serves is reachable on the
# rank's own endpoint; the cluster view curates the cross-rank story
# (where did the wall-clock go, who talks to whom, who is behind).
PASSTHROUGH_FAMILIES = (
    "connector_rows_total",
    "output_rows_total",
    "exchange_frames_total",
    "exchange_bytes_total",
    # fast wire (ISSUE 13): frame bytes before/after the wire codec —
    # the cross-rank compression-effectiveness story
    "exchange_uncompressed_bytes_total",
    "exchange_compressed_bytes_total",
    "exchange_peer_frames_total",
    "exchange_peer_bytes_total",
    "exchange_peer_uncompressed_bytes_total",
    "exchange_peer_compressed_bytes_total",
    "mesh_tree_depth",
    "exchange_comms_seconds_total",
    "exchange_compute_seconds_total",
    "exchange_recv_wait_seconds_total",
    "exchange_peer_recv_wait_seconds_total",
    "exchange_waves_total",
    "exchange_wave_seconds_total",
    "exchange_fallbacks_total",
    "nb_fallbacks_total",
    # columnar egress (ISSUE 14): which ranks deliver Arrow batches vs
    # row-expand at their sinks (partitioned sinks write on every rank)
    "capture_arrow_batches_total",
    "capture_arrow_rows_total",
    "capture_rows_expanded_total",
    "sink_egress_seconds_total",
    # device plane (ISSUE 15): which ranks' accelerators are busy, at
    # what MFU, and whether any rank's trace ring is dropping events
    "device_dispatches_total",
    "device_dispatch_seconds_total",
    "device_wall_seconds_total",
    "device_flops_total",
    "device_flops_effective_total",
    "device_transfer_bytes_total",
    "device_recompiles_total",
    "device_mfu",
    "device_mfu_padded",
    "device_hbm_live_bytes",
    "device_hbm_peak_bytes",
    "device_queue_depth",
    "device_hbm_stats_available",
    "device_peak_flops",
    "device_site_dispatches_total",
    "device_site_dispatch_seconds_total",
    "device_site_wall_seconds_total",
    "device_site_flops_total",
    "device_site_flops_effective_total",
    "device_site_recompiles_total",
    # device fault domain (ISSUE 17): which rank is retrying, tripping
    # its watchdog, refusing growth, or paying restore time — per rank
    "device_dispatch_retries_total",
    "device_dispatch_failures_total",
    "device_watchdog_trips_total",
    "device_oom_events_total",
    "device_index_restore_seconds_total",
    "device_index_snapshot_bytes_total",
    "index_filter_errors_total",
    "device_site_dispatch_retries_total",
    "device_site_dispatch_failures_total",
    "device_site_watchdog_trips_total",
    "device_site_oom_events_total",
    "trace_dropped_events_total",
    "runtime_idle_seconds_total",
    "mesh_heartbeats_missed_total",
    "mesh_rank_restarts_total",
    "mesh_rollbacks_total",
    "mesh_last_committed_epoch",
    # backpressure plane (ISSUE 19): which rank is under memory
    # pressure, how deep into its budget, and which connectors are
    # paced — the engage/release story the backpressure lane watches
    "mem_pressure_state",
    "mem_total_bytes",
    "mem_peak_bytes",
    "mem_budget_bytes",
    "mem_pressure_injections_total",
    "mem_component_bytes",
    "connector_paused",
    "connector_paused_seconds_total",
)


def valid_port(port) -> bool:
    return isinstance(port, int) and 1 <= port <= 65535


def metrics_port_from_env() -> int | None:
    """The one parse of PATHWAY_CLUSTER_METRICS_PORT (the runtime and
    the supervisor both route through this module — no drift): unset,
    unparsable or out-of-range reads as off. The knob registry
    (analysis/knobs.py) rejects bad values at engine startup with a
    rich error; this guard covers the paths that do not validate the
    environment (file-path-loaded supervisors)."""
    raw = os.environ.get("PATHWAY_CLUSTER_METRICS_PORT", "")
    try:
        port = int(raw) if raw.strip() else None
    except ValueError:
        return None
    return port if port is not None and valid_port(port) else None


def parse_openmetrics(text: str) -> list[tuple[str, dict, float]]:
    """Minimal OpenMetrics text parser: ``(name, labels, value)`` per
    sample line. Skips comments/TYPE lines and anything unparsable
    (histograms' bucket lines parse fine — ``le`` is just a label)."""
    out: list[tuple[str, dict, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            head, _, raw_val = line.rpartition(" ")
            value = float(raw_val)
            labels: dict = {}
            if "{" in head:
                name, _, rest = head.partition("{")
                body = rest.rsplit("}", 1)[0]
                for part in _split_labels(body):
                    k, _, v = part.partition("=")
                    labels[k.strip()] = v.strip().strip('"')
            else:
                name = head
            name = name.strip()
            if name:
                out.append((name, labels, value))
        except ValueError:
            continue
    return out


def _split_labels(body: str) -> Iterable[str]:
    """Split ``a="x",b="y"`` on commas outside quotes."""
    part, quoted = [], False
    for ch in body:
        if ch == '"':
            quoted = not quoted
        if ch == "," and not quoted:
            yield "".join(part)
            part = []
        else:
            part.append(ch)
    if part:
        yield "".join(part)


def render_sample(name: str, labels: dict, value: float) -> str:
    lab = ",".join(f'{k}="{v}"' for k, v in labels.items())
    val = f"{value:g}" if value != int(value) else str(int(value))
    return f"{name}{{{lab}}} {val}" if lab else f"{name} {val}"


class _RankState:
    """Last successful scrape of one rank. ``departed`` marks a rank a
    shrink rescale removed from the world (ISSUE 11): its endpoint is
    gone, its final-scrape samples are retained and served with
    ``stale="1"`` so the dashboards keep the departed rank's last
    totals instead of watching them vanish."""

    __slots__ = ("samples", "scraped_at", "stale", "errors", "departed")

    def __init__(self):
        self.samples: list[tuple[str, dict, float]] = []
        self.scraped_at: float = 0.0
        self.stale = True
        self.errors = 0
        self.departed = False


class ClusterMetricsAggregator:
    """Scrape every rank's ``/metrics``; serve ``/metrics/cluster``.

    ``endpoints`` maps rank -> URL; :meth:`set_endpoints` re-resolves
    them (the supervisor calls it on every epoch respawn — rank metric
    ports are stable at ``20000 + process_id``, but re-resolving resets
    scrape health and stamps the new epoch so a rolled-back rank's
    stale sample set is marked rather than trusted)."""

    def __init__(
        self,
        port: int,
        endpoints: dict[int, str],
        *,
        interval_s: float = 2.0,
        baseline_rows_per_s: float | None = None,
        timeout_s: float = 2.0,
        host: str = "0.0.0.0",
    ):
        self.port = port
        self.host = host
        self.interval_s = max(0.05, float(interval_s))
        self.baseline_rows_per_s = baseline_rows_per_s
        self.timeout_s = timeout_s
        self._endpoints = dict(endpoints)
        self._ranks: dict[int, _RankState] = {
            r: _RankState() for r in self._endpoints
        }
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._server: http.server.ThreadingHTTPServer | None = None
        self.epoch = 0
        # observation window for cluster_rows_per_s: (monotonic, rows)
        # at the first and latest scrape that saw any connector rows
        self._rate_first: tuple[float, float] | None = None
        self._rate_last: tuple[float, float] | None = None

    # -- construction helpers ---------------------------------------------
    @staticmethod
    def default_endpoints(
        world: int, host: str = "127.0.0.1", base_port: int = 20000
    ) -> dict[int, str]:
        """The engine's per-rank metric endpoints: 20000 + process_id
        (internals/monitoring.py start_http_server call sites)."""
        return {
            r: f"http://{host}:{base_port + r}/metrics"
            for r in range(world)
        }

    @classmethod
    def from_env(cls, port: int, world: int) -> "ClusterMetricsAggregator":
        """Knob-configured construction (PATHWAY_CLUSTER_SCRAPE_S,
        PATHWAY_CLUSTER_BASELINE_ROWS_PER_S); stdlib env reads so
        file-path loads need no package config."""
        try:
            interval = float(
                os.environ.get("PATHWAY_CLUSTER_SCRAPE_S", "") or 2.0
            )
        except ValueError:
            interval = 2.0
        baseline = None
        raw = os.environ.get("PATHWAY_CLUSTER_BASELINE_ROWS_PER_S", "")
        if raw.strip():
            try:
                baseline = float(raw)
            except ValueError:
                baseline = None
        return cls(
            port,
            cls.default_endpoints(world),
            interval_s=interval,
            baseline_rows_per_s=baseline,
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ClusterMetricsAggregator":
        agg = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    body = b"ok\n"
                    ctype = "text/plain"
                elif path in ("/metrics/cluster", "/metrics", "/"):
                    body = agg.render_cluster().encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass  # scrape cadence must not bury the pipeline's logs

        self._server = http.server.ThreadingHTTPServer(
            (self.host, self.port), Handler
        )
        threading.Thread(
            target=self._server.serve_forever, daemon=True
        ).start()
        self._thread = threading.Thread(target=self._scrape_loop, daemon=True)
        self._thread.start()
        return self

    def stop(self, final_scrape: bool = False) -> None:
        if final_scrape:
            try:
                self.scrape_once()
            except Exception:
                pass
        self._stop.set()
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except OSError:
                pass
            self._server = None

    # -- epoch survival -----------------------------------------------------
    def set_endpoints(
        self, endpoints: dict[int, str], epoch: int | None = None
    ) -> None:
        """Re-resolve rank endpoints (supervisor respawn path): fresh
        scrape-health state per rank; last-seen samples are kept but
        marked stale until the new epoch's endpoint answers."""
        with self._lock:
            self._endpoints = dict(endpoints)
            for r in self._endpoints:
                st = self._ranks.get(r)
                if st is None:
                    self._ranks[r] = _RankState()
                else:
                    st.stale = True
                    st.departed = False
            for r in list(self._ranks):
                if r not in self._endpoints:
                    # a shrink rescale removed this rank from the world
                    # (ISSUE 11): keep its final-scrape samples, marked
                    # stale + departed, instead of erasing its history
                    # (the supervisor takes one last scrape before the
                    # reap so the totals cover the rank's whole life)
                    st = self._ranks[r]
                    if st.samples:
                        st.stale = True
                        st.departed = True
                    else:
                        del self._ranks[r]
            if epoch is not None:
                self.epoch = epoch
            # a rollback restarts ingest counters from the committed
            # cut: restart the throughput observation window too
            self._rate_first = None
            self._rate_last = None

    # -- scraping -----------------------------------------------------------
    def _scrape_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:
                pass  # individual rank failures are per-rank state

    def scrape_once(self) -> int:
        """Scrape every rank once; returns how many answered."""
        with self._lock:
            endpoints = dict(self._endpoints)
        ok = 0
        results: dict[int, list | None] = {}
        for rank, url in endpoints.items():
            try:
                with urllib.request.urlopen(
                    url, timeout=self.timeout_s
                ) as resp:
                    results[rank] = parse_openmetrics(
                        resp.read().decode("utf-8", "replace")
                    )
                    ok += 1
            except (OSError, urllib.error.URLError, ValueError):
                results[rank] = None
        now = time.monotonic()
        with self._lock:
            total_rows = 0.0
            any_rows = False
            for rank, samples in results.items():
                st = self._ranks.setdefault(rank, _RankState())
                if samples is None:
                    st.errors += 1
                    st.stale = True
                    continue
                st.samples = samples
                st.scraped_at = now
                st.stale = False
            for st in self._ranks.values():
                for name, _labels, value in st.samples:
                    if name == "connector_rows_total":
                        total_rows += value
                        any_rows = True
            if any_rows:
                if self._rate_first is None:
                    self._rate_first = (now, total_rows)
                self._rate_last = (now, total_rows)
        return ok

    # -- derived + rendering ------------------------------------------------
    def _per_rank(self, family: str) -> dict[int, float]:
        """Sum of a family's samples per rank (labels collapsed).
        Departed ranks (shrink rescale) are excluded: their frozen
        totals would distort cross-rank derivations (skew) computed
        over the CURRENT world."""
        out: dict[int, float] = {}
        for rank, st in self._ranks.items():
            if st.departed:
                continue
            total = None
            for name, _labels, value in st.samples:
                if name == family:
                    total = (total or 0.0) + value
            if total is not None:
                out[rank] = total
        return out

    def _rows_per_s(self) -> float | None:
        if self._rate_first is None or self._rate_last is None:
            return None
        (t0, r0), (t1, r1) = self._rate_first, self._rate_last
        if t1 - t0 < 1e-3 or r1 <= r0:
            return None
        return (r1 - r0) / (t1 - t0)

    def derived(self) -> dict:
        """The cluster gauges, as numbers (render_cluster serializes
        them; summary() hands them to the TUI dashboard)."""
        waits = self._per_rank("exchange_recv_wait_seconds_total")
        skew = (max(waits.values()) - min(waits.values())) if len(
            waits
        ) >= 2 else 0.0
        rate = self._rows_per_s()
        eff = None
        if (
            rate is not None
            and self.baseline_rows_per_s
            and self._endpoints
        ):
            eff = rate / (self.baseline_rows_per_s * len(self._endpoints))
        return {
            "ranks_live": sum(
                1 for st in self._ranks.values() if not st.stale
            ),
            "ranks_expected": len(self._endpoints),
            "mesh_skew_seconds": skew,
            "rows_per_s": rate,
            "scaling_efficiency": eff,
        }

    def render_cluster(self) -> str:
        with self._lock:
            d = self.derived()
            lines = [
                "# TYPE cluster_ranks gauge",
                f"cluster_ranks {d['ranks_live']}",
                "# TYPE cluster_ranks_expected gauge",
                f"cluster_ranks_expected {d['ranks_expected']}",
                "# TYPE cluster_epoch gauge",
                f"cluster_epoch {self.epoch}",
                # the CURRENT world size, stamped next to the epoch so a
                # rescale is visible the scrape after it happens
                # (departed ranks' retained samples carry stale="1")
                "# TYPE cluster_world_size gauge",
                f"cluster_world_size {len(self._endpoints)}",
                "# TYPE mesh_skew_seconds gauge",
                f"mesh_skew_seconds {d['mesh_skew_seconds']:.6f}",
            ]
            if d["rows_per_s"] is not None:
                lines.append("# TYPE cluster_rows_per_s gauge")
                lines.append(f"cluster_rows_per_s {d['rows_per_s']:.1f}")
            if d["scaling_efficiency"] is not None:
                lines.append("# TYPE scaling_efficiency gauge")
                lines.append(
                    f"scaling_efficiency {d['scaling_efficiency']:.4f}"
                )
            # pass-through: every curated family, grouped under one TYPE
            # line across ranks (the OpenMetrics grouping contract),
            # each sample re-labeled with its rank (+ stale marker when
            # the rank's endpoint missed the last scrape)
            by_family: dict[str, list[str]] = {}
            for rank in sorted(self._ranks):
                st = self._ranks[rank]
                extra = {"rank": str(rank)}
                if st.stale and st.samples:
                    extra["stale"] = "1"
                for name, labels, value in st.samples:
                    if name not in PASSTHROUGH_FAMILIES:
                        continue
                    by_family.setdefault(name, []).append(
                        render_sample(name, {**extra, **labels}, value)
                    )
            for name in PASSTHROUGH_FAMILIES:
                samples = by_family.get(name)
                if samples:
                    kind = (
                        "gauge"
                        if name in (
                            "mesh_last_committed_epoch", "mesh_tree_depth",
                            "device_mfu", "device_mfu_padded",
                            "device_hbm_live_bytes",
                            "device_hbm_peak_bytes", "device_queue_depth",
                            "device_hbm_stats_available",
                            "device_peak_flops",
                            "trace_dropped_events_total",
                        )
                        else "counter"
                    )
                    lines.append(f"# TYPE {name} {kind}")
                    lines.extend(samples)
            return "\n".join(lines) + "\n"

    def summary(self) -> dict | None:
        """Per-rank wall-clock split + derived gauges for the TUI
        dashboard's cluster section; None before the first scrape."""
        with self._lock:
            if not any(st.samples for st in self._ranks.values()):
                return None
            rows = self._per_rank("connector_rows_total")
            comms = self._per_rank("exchange_comms_seconds_total")
            compute = self._per_rank("exchange_compute_seconds_total")
            idle = self._per_rank("runtime_idle_seconds_total")
            waits = self._per_rank("exchange_recv_wait_seconds_total")
            d = self.derived()
            return {
                "ranks": {
                    r: {
                        "rows": rows.get(r, 0.0),
                        "comms_s": comms.get(r, 0.0),
                        "compute_s": compute.get(r, 0.0),
                        "idle_s": idle.get(r, 0.0),
                        "recv_wait_s": waits.get(r, 0.0),
                        "stale": self._ranks[r].stale,
                    }
                    for r in self._ranks
                    if self._ranks[r].samples
                },
                "skew_s": d["mesh_skew_seconds"],
                "rows_per_s": d["rows_per_s"],
                "efficiency": d["scaling_efficiency"],
                "epoch": self.epoch,
            }


def load_by_path() -> "type[ClusterMetricsAggregator]":
    """Helper mirror of the supervisor's file-path load pattern (used by
    tests to pin that this module stays stdlib-only/importable without
    the package __init__s)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_pw_cluster", os.path.abspath(__file__)
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.ClusterMetricsAggregator
