"""Monitoring: ProberStats counters + live text dashboard + OpenMetrics
HTTP endpoint (reference: python/pathway/internals/monitoring.py rich TUI;
src/engine/http_server.rs:21 Prometheus endpoint at port
20000+process_id exposing input_latency_ms / output_latency_ms and
per-connector counters)."""

from __future__ import annotations

import enum
import http.server
import sys
import threading
import time
from dataclasses import dataclass, field


class MonitoringLevel(enum.Enum):
    AUTO = 0
    AUTO_ALL = 1
    NONE = 2
    IN_OUT = 3
    ALL = 4


@dataclass
class ConnectorStats:
    name: str = ""
    rows: int = 0
    batches: int = 0
    last_commit_ts: float = 0.0


@dataclass
class ProberStats:
    """reference: graph.rs:554 ProberStats — input/output frontier lag."""

    connectors: dict[str, ConnectorStats] = field(default_factory=dict)
    outputs_emitted: int = 0
    last_output_ts: float = 0.0
    started_at: float = field(default_factory=time.time)

    def on_ingest(self, name: str, n_rows: int) -> None:
        st = self.connectors.setdefault(name, ConnectorStats(name=name))
        st.rows += n_rows
        st.batches += 1
        st.last_commit_ts = time.time()

    def on_output(self, n_rows: int) -> None:
        self.outputs_emitted += n_rows
        self.last_output_ts = time.time()

    def input_latency_ms(self) -> float:
        if not self.connectors:
            return 0.0
        newest = max(s.last_commit_ts for s in self.connectors.values())
        return max(0.0, (time.time() - newest) * 1000.0) if newest else 0.0

    def output_latency_ms(self) -> float:
        if not self.last_output_ts:
            return 0.0
        return max(0.0, (time.time() - self.last_output_ts) * 1000.0)

    def render_openmetrics(self) -> str:
        lines = [
            "# TYPE input_latency_ms gauge",
            f"input_latency_ms {self.input_latency_ms():.1f}",
            "# TYPE output_latency_ms gauge",
            f"output_latency_ms {self.output_latency_ms():.1f}",
            "# TYPE connector_rows_total counter",
        ]
        for st in self.connectors.values():
            lines.append(
                f'connector_rows_total{{connector="{st.name}"}} {st.rows}'
            )
        lines.append("# TYPE output_rows_total counter")
        lines.append(f"output_rows_total {self.outputs_emitted}")
        return "\n".join(lines) + "\n"

    def render_text(self) -> str:
        up = time.time() - self.started_at
        rows = [f"uptime {up:6.1f}s  outputs {self.outputs_emitted}"]
        for st in self.connectors.values():
            rows.append(
                f"  {st.name:<30} rows={st.rows:<8} batches={st.batches}"
            )
        return "\n".join(rows)


def start_http_server(stats: ProberStats, port: int) -> threading.Thread:
    """OpenMetrics endpoint (reference: http_server.rs — port
    20000 + process_id)."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = stats.render_openmetrics().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return thread


def start_monitor_printer(
    stats: ProberStats, interval: float = 2.0
) -> threading.Thread:
    def loop():
        while True:
            time.sleep(interval)
            print(stats.render_text(), file=sys.stderr)

    thread = threading.Thread(target=loop, daemon=True)
    thread.start()
    return thread
