"""Monitoring: ProberStats counters + live text dashboard + OpenMetrics
HTTP endpoint (reference: python/pathway/internals/monitoring.py rich TUI;
src/engine/http_server.rs:21 Prometheus endpoint at port
20000+process_id exposing input_latency_ms / output_latency_ms and
per-connector counters)."""

from __future__ import annotations

import enum
import http.server
import logging
import sys
import threading
import time
from dataclasses import dataclass, field


class MonitoringLevel(enum.Enum):
    AUTO = 0
    AUTO_ALL = 1
    NONE = 2
    IN_OUT = 3
    ALL = 4


@dataclass
class ConnectorStats:
    name: str = ""
    rows: int = 0
    batches: int = 0
    last_commit_ts: float = 0.0
    last_minibatch: int = 0
    finished: bool = False
    # supervision health (engine/runtime.py _service_connector_health):
    # in-place restarts, permanent failures, watchdog stalls, and
    # at-least-once degradations (_BACKLOG_CAP overflow, deferred flushes)
    restarts: int = 0
    errors: int = 0
    stalls: int = 0
    degraded: int = 0
    # source pacing (ISSUE 19): currently gated by the memory ladder, and
    # cumulative seconds this connector's reader has spent paced
    paused: bool = False
    paused_seconds: float = 0.0
    # rolling (timestamp, n_rows) window for the last-minute column
    recent: list = field(default_factory=list)

    def rows_last_minute(self, now: float | None = None) -> int:
        now = now or time.time()
        self.recent = [(t, n) for t, n in self.recent if now - t <= 60.0]
        return sum(n for _, n in self.recent)


# serving histograms (io/http/_server.py gateway): fixed OpenMetrics
# bucket edges. Latency buckets span sub-ms colocated responses up to
# the shed/timeout regime; occupancy buckets prove request coalescing is
# engaging (occupancy > 1 under load is the direct evidence the gateway
# batches instead of paying one commit per request).
SERVE_LATENCY_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
    2500.0, 5000.0, 15000.0,
)
SERVE_OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

# event-time lag watermarks (flight recorder, ISSUE 8): commit→emit
# freshness per output — sub-ms fused chains up to multi-minute backlogs
LAG_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 5000.0, 30000.0, 300000.0,
)


class _Histogram:
    """Minimal cumulative-bucket histogram (OpenMetrics shape)."""

    __slots__ = ("edges", "counts", "total", "sum")

    def __init__(self, edges):
        self.edges = tuple(edges)
        self.counts = [0] * (len(self.edges) + 1)  # last = +Inf
        self.total = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.total += 1
        self.sum += value
        for i, edge in enumerate(self.edges):
            if value <= edge:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def render(self, name: str, labels: str) -> list[str]:
        sep = "," if labels else ""
        lines = []
        cum = 0
        for edge, n in zip(self.edges, self.counts):
            cum += n
            le = f"{edge:g}"
            lines.append(f'{name}_bucket{{{labels}{sep}le="{le}"}} {cum}')
        cum += self.counts[-1]
        lines.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {cum}')
        lines.append(f"{name}_sum{{{labels}}} {self.sum:.6g}")
        lines.append(f"{name}_count{{{labels}}} {self.total}")
        return lines

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile (upper edge of the bucket holding
        the q-th observation) — dashboard summaries, not SLO math."""
        if self.total == 0:
            return 0.0
        target = q * self.total
        cum = 0
        for edge, n in zip(self.edges, self.counts):
            cum += n
            if cum >= target:
                return float(edge)
        return float(self.edges[-1])


@dataclass
class ServeMetrics:
    """Per-route serving gateway instrumentation (io/http/_server.py):
    request/shed/timeout counters, the request-latency histogram, and
    the batch-occupancy histogram — the direct evidence that request
    coalescing is engaging under load. The subject owns this object from
    construction; the runtime mounts it on ProberStats at add_connector
    time so the OpenMetrics endpoint serves it."""

    route: str = ""
    requests: int = 0
    shed: int = 0
    timeouts: int = 0
    commits: int = 0          # batch windows committed into the dataflow
    # serving-through-rollback instrumentation (ISSUE 9): degraded
    # answers served while the dispatch breaker is open, windows aborted
    # (uncommitted) on an epoch rollback, and the breaker's state as a
    # gauge (0 = closed, 1 = half_open, 2 = open)
    browned_out: int = 0
    windows_aborted: int = 0
    breaker_state: str = "closed"
    latency: _Histogram = field(
        default_factory=lambda: _Histogram(SERVE_LATENCY_BUCKETS_MS)
    )
    occupancy: _Histogram = field(
        default_factory=lambda: _Histogram(SERVE_OCCUPANCY_BUCKETS)
    )

    def on_request(self) -> None:
        self.requests += 1

    def on_shed(self) -> None:
        self.shed += 1

    def on_timeout(self) -> None:
        self.timeouts += 1

    def on_brownout(self) -> None:
        """One request answered degraded (last committed snapshot, no
        update-fold) instead of shed while the breaker was open."""
        self.browned_out += 1

    def on_windows_aborted(self, n: int = 1) -> None:
        """Windows whose dispatch was aborted (committing nothing) when
        the epoch rolled back — the backend half of request parking."""
        self.windows_aborted += n

    def set_breaker(self, state: str) -> None:
        self.breaker_state = state

    def on_latency_ms(self, ms: float) -> None:
        self.latency.observe(ms)

    def on_window(self, occupancy: int) -> None:
        """One batch window committed (= one dataflow timestamp, one
        fused device dispatch downstream)."""
        self.commits += 1
        self.occupancy.observe(occupancy)


@dataclass
class ProberStats:
    """reference: graph.rs:554 ProberStats — input/output frontier lag."""

    connectors: dict[str, ConnectorStats] = field(default_factory=dict)
    outputs_emitted: int = 0
    last_output_ts: float = 0.0
    started_at: float = field(default_factory=time.time)
    # readiness state exposed on /healthz (ISSUE 9): "serving" (200 ok),
    # "draining" (shutdown requested) or "recovering" (epoch restore /
    # mesh rollback in flight) — both non-serving states answer 503 so a
    # load balancer rotates traffic away during the blip
    health_state: str = "serving"
    # multi-process exchange plane (engine/runtime.py wave engine +
    # parallel/procgroup.py v2 frames): coalesced frames/bytes shipped,
    # per-node empty slices elided from the wire, non-empty batches that
    # de-optimized to the tuple path, and per-timestamp communication vs
    # computation wall time
    exchange_frames: int = 0
    exchange_bytes: int = 0
    exchange_empty_elided: int = 0
    exchange_fallbacks: int = 0
    exchange_comms_s: float = 0.0
    exchange_compute_s: float = 0.0
    # per-peer exchange breakdown (ISSUE 10): the cluster aggregator
    # relabels these with this rank's id, turning them into the
    # (rank, peer) byte matrix of the mesh. Bounded cardinality: at most
    # world-1 peers. The unlabeled totals above stay for dashboard
    # back-compat.
    exchange_peer: dict = field(default_factory=dict)  # peer -> [frames, bytes]
    # recv-wait seconds this rank spent parked on each peer inside
    # exchange waves — the straggler signal: the SLOW rank waits least,
    # everyone else's wait points at it (max-min across ranks is the
    # cluster's mesh_skew_seconds)
    exchange_recv_wait_s: float = 0.0
    exchange_peer_wait: dict = field(default_factory=dict)  # peer -> seconds
    # wave accounting: completed exchange waves and their wall seconds
    exchange_waves: int = 0
    exchange_wave_s: float = 0.0
    # fast wire (ISSUE 13): frame bytes before/after the per-blob codec
    # (procgroup._frame_send feeds BOTH paths — wave engine and the
    # generic topo-loop fallback — so a fallback run can never report a
    # phantom compression state; when the link negotiates no codec the
    # two totals advance in lockstep and the ratio reads an honest 1.0)
    exchange_raw_bytes: int = 0
    exchange_wire_bytes: int = 0
    # peer -> [raw, wire]: per-link codec effectiveness for the cluster
    # byte matrix (bounded: world-1 peers)
    exchange_comp_peer: dict = field(default_factory=dict)
    # frame accounting lock (ISSUE 13): several per-peer sender threads
    # feed the frame/byte counters concurrently; unguarded `+=` could
    # drop increments and make raw/wire diverge on an uncompressed
    # link, breaking the honest-off raw==wire contract lane 12 asserts
    _frame_lock: object = field(
        default_factory=threading.Lock, repr=False
    )
    # gather-tree depth of the exchange topology (protocol.tree_depth;
    # 0 = flat) — a gauge, set once per mesh join
    mesh_tree_depth: int = 0
    # event-loop idle: seconds the main loop spent blocked on an empty
    # connector queue (per-rank comms/compute/idle on the cluster view)
    idle_s: float = 0.0
    # cluster aggregator handle (internals/cluster.py), attached by the
    # unsupervised rank-0 runtime so the TUI dashboard can render the
    # per-rank section; None everywhere else
    cluster: object = None
    # fused-chain de-optimizations at join/groupby/select nodes: batches
    # that were statically expected columnar (analysis/eligibility.py
    # expects_native_batch) but executed on the tuple path. A permanent
    # demotion (poison / unsupported-value migration) counts exactly once
    # for the node, not once per subsequent batch. pw.analyze "fused"
    # verdicts must correspond to this staying 0.
    nb_fallbacks: int = 0
    # mesh fault tolerance (procgroup detection layer + runtime recovery
    # path): heartbeat windows a peer missed, post-recovery incarnations
    # of this rank (epoch > 0 at mesh join), epoch aborts this rank
    # initiated after detecting a peer failure, and the recovery epoch at
    # which the newest distributed snapshot cut was committed/restored
    # (gauge; -1 = never)
    mesh_heartbeats_missed: int = 0
    mesh_rank_restarts: int = 0
    mesh_rollbacks: int = 0
    mesh_last_committed_epoch: int = -1
    # serving gateway routes (io/http/_server.py): each RestServerSubject
    # owns a ServeMetrics; the runtime mounts them here at add_connector
    # time so /metrics serves every route's counters and histograms
    serve: list = field(default_factory=list)
    # flight-recorder aggregates (engine/runtime.py _step_node when
    # anything is watching): node label -> [self_s, rows, batches,
    # nb_batches] — per-node self-time/rows gauges on /metrics and the
    # dashboard's hot-nodes panel
    nodes: dict = field(default_factory=dict)
    # event-time lag watermarks: output label -> freshness histogram
    # (commit→emit ms against the connector's flush-time ingest stamp)
    lag: dict = field(default_factory=dict)
    # transactional egress (ISSUE 12): per-sink 2PC counters — segments
    # staged (sealed, invisible), finalized (externally visible after
    # the snapshot_commit marker landed), aborted (discarded at
    # recovery / epoch abort: no committed cut claimed them) and
    # recovered (finalized by a restore-time recovery scan: the crash
    # landed between the marker and the owner's local finalize) — plus
    # the per-sink epoch lag gauge: how many committed cuts the
    # external output trails the staged set by (0 = egress is current)
    sink_staged: dict = field(default_factory=dict)    # name -> units
    sink_finalized: dict = field(default_factory=dict)
    sink_aborted: dict = field(default_factory=dict)
    sink_recovered: dict = field(default_factory=dict)
    sink_lag: dict = field(default_factory=dict)       # name -> gauge
    # columnar egress (ISSUE 14): rows delivered to sinks/subscribers as
    # Arrow record batches straight off the C-owned column buffers vs
    # rows a NativeBatch expanded back into Python objects at an egress
    # node (OutputNode consolidate / CaptureNode flush). A fused egress
    # verdict (analysis/eligibility.py sink_egress_decision) must
    # correspond to rows_expanded staying flat in the steady state.
    capture_arrow_batches: int = 0
    capture_arrow_rows: int = 0
    capture_rows_expanded: int = 0
    # per-sink seconds spent encoding/staging egress output (the sink
    # side of the egress leg --profile/--critical-path report)
    sink_egress_s: dict = field(default_factory=dict)  # name -> seconds
    # device plane (ISSUE 15; internals/device.py): per-dispatch-site
    # accounting — [dispatches, wall_s, device_s, flops, bytes_accessed,
    # transfer_bytes, flops_effective]. device_s is the
    # block_until_ready-bounded device share of each dispatch's wall
    # span; wall - device = host assembly. flops_effective (ISSUE 16) is
    # the real-row share of flops — padding waste is the gap between the
    # two. Bounded cardinality: a handful of static site names
    # (knn.search, encoder.forward, ingest.fused, serve.window, ...).
    device_sites: dict = field(default_factory=dict)
    # fresh XLA compilations observed at dispatch sites (ISSUE 16): a
    # new shape bucket entering a site's compiled-fn cache. A recompile
    # storm (shape-bucket leak) shows here before it shows as wall time.
    device_recompiles: dict = field(default_factory=dict)
    # dispatch-queue depth observed at the most recent launch (gauge)
    device_queue_depth: int = 0
    # MFU denominator this process resolved at arm time (device-kind
    # table / PATHWAY_DEVICE_PEAK_FLOPS) — rendered so a scraped MFU is
    # auditable against the peak it was computed from
    device_peak_flops: float = 0.0
    # HBM gauges from jax.local_devices()[0].memory_stats(), absent-safe:
    # a backend without allocator stats (CPU) keeps available=False and
    # the byte gauges at 0 — "no HBM story", not an error
    device_hbm_live: int = 0
    device_hbm_peak: int = 0
    device_hbm_available: bool = False
    # flight-recorder ring pressure (ISSUE 15 satellite): events the
    # bounded in-memory log evicted (previously visible only in the
    # dump's dropped_events field — now a live gauge, so a capped trace
    # is observable before shutdown)
    trace_dropped_events: int = 0
    # device fault domain (ISSUE 17): dispatch-supervision and index
    # snapshot/restore accounting. Retries / failures / watchdog trips /
    # OOM refusals are keyed by dispatch site (the bounded static set);
    # restore seconds and snapshot bytes are running totals — snapshot
    # bytes scaling with corpus size instead of the epoch delta is the
    # regression the quiet-epoch test pins.
    device_dispatch_retries: dict = field(default_factory=dict)
    device_dispatch_failures: dict = field(default_factory=dict)
    device_watchdog_trips: dict = field(default_factory=dict)
    device_oom_events: dict = field(default_factory=dict)
    device_index_restore_s: float = 0.0
    device_index_snapshot_bytes: int = 0
    # filter predicates that raised during index search (ISSUE 17
    # satellite: previously swallowed, silently dropping matching rows)
    index_filter_errors: int = 0
    # memory governance / backpressure (ISSUE 19; internals/memory.py):
    # degradation-ladder state (ok/pacing/brownout/abort), accounted
    # totals against the budget, and the per-component byte breakdown
    # (bounded cardinality: memory.COMPONENTS). budget == 0 renders the
    # gauges anyway so "governance off" is scrapeable, not invisible.
    mem_state: str = "ok"
    mem_total_bytes: int = 0
    mem_peak_bytes: int = 0
    mem_budget_bytes: int = 0
    mem_components: dict = field(default_factory=dict)
    # mem.pressure fault injections observed by the accountant (counter)
    mem_pressure_injections: int = 0

    def on_node_step(
        self, label: str, self_s: float, rows: int, nb: bool
    ) -> None:
        agg = self.nodes.get(label)
        if agg is None:
            agg = self.nodes[label] = [0.0, 0, 0, 0]
        agg[0] += self_s
        agg[1] += rows
        agg[2] += 1
        if nb:
            agg[3] += 1

    def on_output_lag(self, label: str, lag_ms: float) -> None:
        h = self.lag.get(label)
        if h is None:
            h = self.lag[label] = _Histogram(LAG_BUCKETS_MS)
        h.observe(lag_ms)

    def mount_serve_metrics(self, metrics: "ServeMetrics") -> None:
        if metrics not in self.serve:
            self.serve.append(metrics)

    def set_health_state(self, state: str) -> None:
        """serving / draining / recovering — the runtime drives this
        through protocol-visible transitions (run start, _finish,
        rollback abort, distributed restore)."""
        self.health_state = state

    def on_mesh_heartbeat_missed(self, n: int = 1) -> None:
        self.mesh_heartbeats_missed += n

    def on_mesh_rank_restart(self) -> None:
        self.mesh_rank_restarts += 1

    def on_mesh_rollback(self) -> None:
        self.mesh_rollbacks += 1

    def on_mesh_epoch_committed(self, epoch: int) -> None:
        self.mesh_last_committed_epoch = epoch

    def on_exchange_frame(self, nbytes: int, peer: int | None = None) -> None:
        with self._frame_lock:
            self._on_exchange_frame_locked(nbytes, peer)

    def _on_exchange_frame_locked(
        self, nbytes: int, peer: int | None
    ) -> None:
        self.exchange_frames += 1
        self.exchange_bytes += nbytes
        if peer is not None:
            slot = self.exchange_peer.get(peer)
            if slot is None:
                slot = self.exchange_peer[peer] = [0, 0]
            slot[0] += 1
            slot[1] += nbytes

    def on_exchange_compression(
        self, peer: int, raw_bytes: int, wire_bytes: int
    ) -> None:
        """One exchange frame's byte accounting before/after the wire
        codec (raw == wire when the link ships raw). Called from
        several sender threads concurrently — lock-guarded so no
        increment is lost and raw/wire can never diverge on an
        uncompressed link."""
        with self._frame_lock:
            self.exchange_raw_bytes += raw_bytes
            self.exchange_wire_bytes += wire_bytes
            if peer is not None:
                slot = self.exchange_comp_peer.get(peer)
                if slot is None:
                    slot = self.exchange_comp_peer[peer] = [0, 0]
                slot[0] += raw_bytes
                slot[1] += wire_bytes

    def set_tree_depth(self, depth: int) -> None:
        """Gauge: gather-tree depth of this mesh's exchange topology
        (0 = flat)."""
        self.mesh_tree_depth = depth

    def on_exchange_recv_wait(self, peer: int, seconds: float) -> None:
        """Seconds this rank blocked in a wave recv on `peer` — per-peer
        for upstream attribution, totaled for the skew derivation."""
        if seconds > 0:
            self.exchange_recv_wait_s += seconds
            self.exchange_peer_wait[peer] = (
                self.exchange_peer_wait.get(peer, 0.0) + seconds
            )

    def on_exchange_wave(self, seconds: float) -> None:
        self.exchange_waves += 1
        self.exchange_wave_s += max(0.0, seconds)

    def on_idle(self, seconds: float) -> None:
        """Main-loop wall time spent waiting on an EMPTY connector queue
        (a drain that returned work is not idle and is not counted)."""
        if seconds > 0:
            self.idle_s += seconds

    def on_exchange_elided(self, n: int) -> None:
        if n > 0:
            self.exchange_empty_elided += n

    def on_exchange_fallback(self) -> None:
        self.exchange_fallbacks += 1

    def on_nb_fallback(self) -> None:
        self.nb_fallbacks += 1

    def on_exchange_step(self, comms_s: float, compute_s: float) -> None:
        self.exchange_comms_s += comms_s
        self.exchange_compute_s += max(0.0, compute_s)

    def on_ingest(self, name: str, n_rows: int) -> None:
        st = self.connectors.setdefault(name, ConnectorStats(name=name))
        st.rows += n_rows
        st.batches += 1
        st.last_minibatch = n_rows
        st.last_commit_ts = time.time()
        st.recent.append((st.last_commit_ts, n_rows))
        # prune the rolling window HERE, not only in the dashboard
        # renderer — without a dashboard the list would grow per commit
        # forever on the ingest hot path
        cutoff = st.last_commit_ts - 60.0
        while st.recent and st.recent[0][0] < cutoff:
            st.recent.pop(0)

    def on_connector_finished(self, name: str) -> None:
        st = self.connectors.setdefault(name, ConnectorStats(name=name))
        st.finished = True

    def on_connector_restart(self, name: str) -> None:
        st = self.connectors.setdefault(name, ConnectorStats(name=name))
        st.restarts += 1

    def on_connector_error(self, name: str) -> None:
        st = self.connectors.setdefault(name, ConnectorStats(name=name))
        st.errors += 1

    def on_connector_stall(self, name: str) -> None:
        st = self.connectors.setdefault(name, ConnectorStats(name=name))
        st.stalls += 1

    def on_connector_degraded(self, name: str) -> None:
        st = self.connectors.setdefault(name, ConnectorStats(name=name))
        st.degraded += 1

    # -- memory governance / backpressure (ISSUE 19) -----------------------

    def set_mem_pressure(
        self,
        state: str,
        total: int,
        peak: int,
        budget: int,
        components: dict,
        injections: int = 0,
    ) -> None:
        """Gauge snapshot from the memory accountant's latest sample
        (engine/runtime.py _service_memory)."""
        self.mem_state = state
        self.mem_total_bytes = int(total)
        self.mem_peak_bytes = int(peak)
        self.mem_budget_bytes = int(budget)
        self.mem_components = dict(components)
        self.mem_pressure_injections = int(injections)

    def on_connector_paused(self, name: str) -> None:
        st = self.connectors.setdefault(name, ConnectorStats(name=name))
        st.paused = True

    def on_connector_paced(self, name: str, seconds: float) -> None:
        """Accrue paced wall seconds for a STILL-paused connector — the
        governor charges each health pass's slice as it elapses, so the
        counter is live while the pause is in progress (the smoke lane
        watches it move on /metrics/cluster mid-episode)."""
        st = self.connectors.setdefault(name, ConnectorStats(name=name))
        st.paused_seconds += max(0.0, seconds)

    def on_connector_resumed(self, name: str, seconds: float) -> None:
        st = self.connectors.setdefault(name, ConnectorStats(name=name))
        st.paused = False
        st.paused_seconds += max(0.0, seconds)

    def on_output(self, n_rows: int) -> None:
        self.outputs_emitted += n_rows
        self.last_output_ts = time.time()

    # -- transactional egress (io/txn.py; ISSUE 12) ------------------------

    def on_sink_staged(self, name: str, n: int = 1) -> None:
        self.sink_staged[name] = self.sink_staged.get(name, 0) + n

    def on_sink_finalized(self, name: str, n: int = 1) -> None:
        self.sink_finalized[name] = self.sink_finalized.get(name, 0) + n

    def on_sink_aborted(self, name: str, n: int = 1) -> None:
        self.sink_aborted[name] = self.sink_aborted.get(name, 0) + n

    def on_sink_recovered(self, name: str, n: int = 1) -> None:
        self.sink_recovered[name] = self.sink_recovered.get(name, 0) + n

    def on_sink_epoch_lag(self, name: str, lag: int) -> None:
        self.sink_lag[name] = lag

    # -- columnar egress (io/_arrow.py; ISSUE 14) --------------------------

    def on_capture_arrow_batch(self, n_rows: int) -> None:
        self.capture_arrow_batches += 1
        self.capture_arrow_rows += n_rows

    def on_capture_rows_expanded(self, n_rows: int) -> None:
        self.capture_rows_expanded += n_rows

    def on_sink_egress_seconds(self, name: str, seconds: float) -> None:
        if seconds > 0:
            self.sink_egress_s[name] = (
                self.sink_egress_s.get(name, 0.0) + seconds
            )

    # -- device plane (internals/device.py; ISSUE 15) ----------------------

    def on_device_dispatch(
        self, site: str, wall_s: float, device_s: float, flops: float,
        bytes_accessed: float, transfer_bytes: int, depth: int,
        flops_effective: float | None = None,
    ) -> None:
        """One closed dispatch record from the device plane. Records
        arrive from several threads (gateway dispatch workers close
        serve.window records while the engine thread closes knn/encoder
        ones) — lock-guarded like the exchange-frame counters so no
        increment is lost and the MFU gauge never reads torn totals.
        ``flops_effective`` (ISSUE 16) defaults to ``flops`` — an
        unpadded site is 100% effective."""
        if flops_effective is None:
            flops_effective = flops
        with self._frame_lock:
            agg = self.device_sites.get(site)
            if agg is None:
                agg = self.device_sites[site] = [
                    0, 0.0, 0.0, 0.0, 0.0, 0, 0.0,
                ]
            agg[0] += 1
            agg[1] += max(0.0, wall_s)
            agg[2] += max(0.0, device_s)
            agg[3] += max(0.0, flops)
            agg[4] += max(0.0, bytes_accessed)
            agg[5] += max(0, transfer_bytes)
            agg[6] += max(0.0, min(flops_effective, flops))
            self.device_queue_depth = depth

    def on_device_recompile(self, site: str) -> None:
        """A dispatch site compiled a fresh executable (new shape
        bucket). Bounded cardinality: the static site-name set."""
        with self._frame_lock:
            self.device_recompiles[site] = (
                self.device_recompiles.get(site, 0) + 1
            )

    def set_device_peak_flops(self, v: float) -> None:
        self.device_peak_flops = v

    def set_device_memory(
        self, live: int, peak: int, available: bool = True
    ) -> None:
        self.device_hbm_live = live
        self.device_hbm_peak = max(self.device_hbm_peak, peak)
        self.device_hbm_available = available

    def set_trace_dropped(self, n: int) -> None:
        self.trace_dropped_events = n

    # -- device fault domain (ISSUE 17) ------------------------------------

    def on_device_dispatch_retry(self, site: str) -> None:
        """A supervised dispatch classified transient and is retrying
        with backoff (internals/device.supervised_dispatch)."""
        with self._frame_lock:
            self.device_dispatch_retries[site] = (
                self.device_dispatch_retries.get(site, 0) + 1
            )

    def on_device_dispatch_failure(self, site: str) -> None:
        """A supervised dispatch exhausted its verdict — permanent
        failure, retry budget spent, or OOM brownout."""
        with self._frame_lock:
            self.device_dispatch_failures[site] = (
                self.device_dispatch_failures.get(site, 0) + 1
            )

    def on_device_watchdog_trip(self, site: str) -> None:
        """A dispatch exceeded PATHWAY_DEVICE_DISPATCH_TIMEOUT_S and was
        abandoned by the watchdog."""
        with self._frame_lock:
            self.device_watchdog_trips[site] = (
                self.device_watchdog_trips.get(site, 0) + 1
            )

    def on_device_oom(self, site: str) -> None:
        """HBM growth refused (real RESOURCE_EXHAUSTED or injected
        device.oom) — the index keeps serving at committed capacity and
        the serving breaker browns out."""
        with self._frame_lock:
            self.device_oom_events[site] = (
                self.device_oom_events.get(site, 0) + 1
            )

    def on_index_restore_seconds(self, seconds: float) -> None:
        """One index restore-from-segments completed (the ≥10x-vs-
        rebuild path the chaos smoke pins)."""
        with self._frame_lock:
            self.device_index_restore_s += max(0.0, seconds)

    def on_index_snapshot_bytes(self, nbytes: int) -> None:
        """One delta segment written at a snapshot cut — bytes scale
        with the epoch's dirty set, not corpus size."""
        with self._frame_lock:
            self.device_index_snapshot_bytes += max(0, nbytes)

    def on_index_filter_error(self, n: int = 1) -> None:
        """Filter predicates that raised during index search; the first
        message also lands in the global error log."""
        with self._frame_lock:
            self.index_filter_errors += n

    def device_totals(self) -> tuple:
        """(dispatches, wall_s, device_s, flops, bytes_accessed,
        transfer_bytes, flops_effective) summed over sites, plus the
        resulting effective MFU (real rows only — the honest number)
        and padded MFU (what the hardware executed, bucket padding
        included) — shared by the OpenMetrics render and the TUI
        dashboard."""
        tot = [0, 0.0, 0.0, 0.0, 0.0, 0, 0.0]
        with self._frame_lock:
            aggs = [list(a) for a in self.device_sites.values()]
        for agg in aggs:
            for i in range(7):
                # pre-ISSUE-16 6-element rows (a restored snapshot)
                # read as zero effective FLOPs, never as a crash
                tot[i] += agg[i] if i < len(agg) else 0.0
        mfu_eff = mfu_padded = 0.0
        if tot[2] > 0 and self.device_peak_flops > 0:
            denom = tot[2] * self.device_peak_flops
            mfu_eff = tot[6] / denom
            mfu_padded = tot[3] / denom
        return (*tot, mfu_eff, mfu_padded)

    def input_latency_ms(self) -> float:
        if not self.connectors:
            return 0.0
        newest = max(s.last_commit_ts for s in self.connectors.values())
        return max(0.0, (time.time() - newest) * 1000.0) if newest else 0.0

    def output_latency_ms(self) -> float:
        if not self.last_output_ts:
            return 0.0
        return max(0.0, (time.time() - self.last_output_ts) * 1000.0)

    def render_openmetrics(self) -> str:
        lines = [
            "# TYPE input_latency_ms gauge",
            f"input_latency_ms {self.input_latency_ms():.1f}",
            "# TYPE output_latency_ms gauge",
            f"output_latency_ms {self.output_latency_ms():.1f}",
            "# TYPE connector_rows_total counter",
        ]
        for st in self.connectors.values():
            lines.append(
                f'connector_rows_total{{connector="{st.name}"}} {st.rows}'
            )
        for metric, attr in (
            ("connector_restarts_total", "restarts"),
            ("connector_errors_total", "errors"),
            ("connector_stalls_total", "stalls"),
            ("connector_degraded_total", "degraded"),
        ):
            lines.append(f"# TYPE {metric} counter")
            for st in self.connectors.values():
                lines.append(
                    f'{metric}{{connector="{st.name}"}} {getattr(st, attr)}'
                )
        # source pacing (ISSUE 19): seconds each connector's reader spent
        # paced by the memory governor (a CURRENTLY paused connector's
        # open episode is included so the smoke can observe engagement
        # live), plus the live gate state as a 0/1 gauge
        lines.append("# TYPE connector_paused_seconds_total counter")
        for st in self.connectors.values():
            lines.append(
                f'connector_paused_seconds_total{{connector="{st.name}"}} '
                f"{st.paused_seconds:.6f}"
            )
        lines.append("# TYPE connector_paused gauge")
        for st in self.connectors.values():
            lines.append(
                f'connector_paused{{connector="{st.name}"}} '
                f"{int(st.paused)}"
            )
        lines.append("# TYPE output_rows_total counter")
        lines.append(f"output_rows_total {self.outputs_emitted}")
        for metric, val in (
            ("exchange_frames_total", self.exchange_frames),
            ("exchange_bytes_total", self.exchange_bytes),
            ("exchange_uncompressed_bytes_total", self.exchange_raw_bytes),
            ("exchange_compressed_bytes_total", self.exchange_wire_bytes),
            ("exchange_empty_elided_total", self.exchange_empty_elided),
            ("exchange_fallbacks_total", self.exchange_fallbacks),
            ("nb_fallbacks_total", self.nb_fallbacks),
        ):
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {val}")
        for metric, val in (
            ("exchange_comms_seconds_total", self.exchange_comms_s),
            ("exchange_compute_seconds_total", self.exchange_compute_s),
            ("exchange_recv_wait_seconds_total", self.exchange_recv_wait_s),
            ("exchange_wave_seconds_total", self.exchange_wave_s),
            ("runtime_idle_seconds_total", self.idle_s),
        ):
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {val:.6f}")
        lines.append("# TYPE exchange_waves_total counter")
        lines.append(f"exchange_waves_total {self.exchange_waves}")
        if self.exchange_peer:
            # per-peer byte matrix rows (bounded: world-1 label values);
            # the cluster aggregator adds the rank label on its side
            for metric, idx in (
                ("exchange_peer_frames_total", 0),
                ("exchange_peer_bytes_total", 1),
            ):
                lines.append(f"# TYPE {metric} counter")
                for peer in sorted(self.exchange_peer):
                    lines.append(
                        f'{metric}{{peer="{peer}"}} '
                        f"{self.exchange_peer[peer][idx]}"
                    )
        if self.exchange_comp_peer:
            # per-peer codec effectiveness (ISSUE 13), labeled like the
            # byte matrix so the cluster aggregator relabels per rank
            for metric, idx in (
                ("exchange_peer_uncompressed_bytes_total", 0),
                ("exchange_peer_compressed_bytes_total", 1),
            ):
                lines.append(f"# TYPE {metric} counter")
                for peer in sorted(self.exchange_comp_peer):
                    lines.append(
                        f'{metric}{{peer="{peer}"}} '
                        f"{self.exchange_comp_peer[peer][idx]}"
                    )
        lines.append("# TYPE mesh_tree_depth gauge")
        lines.append(f"mesh_tree_depth {self.mesh_tree_depth}")
        if self.exchange_peer_wait:
            lines.append(
                "# TYPE exchange_peer_recv_wait_seconds_total counter"
            )
            for peer in sorted(self.exchange_peer_wait):
                lines.append(
                    f'exchange_peer_recv_wait_seconds_total{{peer="{peer}"}}'
                    f" {self.exchange_peer_wait[peer]:.6f}"
                )
        for metric, val in (
            ("mesh_heartbeats_missed_total", self.mesh_heartbeats_missed),
            ("mesh_rank_restarts_total", self.mesh_rank_restarts),
            ("mesh_rollbacks_total", self.mesh_rollbacks),
        ):
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {val}")
        lines.append("# TYPE mesh_last_committed_epoch gauge")
        lines.append(
            f"mesh_last_committed_epoch {self.mesh_last_committed_epoch}"
        )
        # transactional egress families (bounded cardinality: one label
        # value per sink in the program). The cluster aggregator relabels
        # these per rank, so /metrics/cluster shows the whole mesh's
        # staged/finalized balance in one view.
        for metric, store in (
            ("sink_staged_total", self.sink_staged),
            ("sink_finalized_total", self.sink_finalized),
            ("sink_aborted_total", self.sink_aborted),
            ("sink_recovered_total", self.sink_recovered),
        ):
            if store:
                lines.append(f"# TYPE {metric} counter")
                for name in sorted(store):
                    lines.append(
                        f'{metric}{{sink="{name}"}} {store[name]}'
                    )
        if self.sink_lag:
            lines.append("# TYPE sink_epoch_lag gauge")
            for name in sorted(self.sink_lag):
                lines.append(
                    f'sink_epoch_lag{{sink="{name}"}} {self.sink_lag[name]}'
                )
        # columnar egress (ISSUE 14): always rendered so the lakehouse
        # smoke can assert `capture_arrow_batches_total > 0` AND the
        # forced-row run can assert it stays 0
        for metric, val in (
            ("capture_arrow_batches_total", self.capture_arrow_batches),
            ("capture_arrow_rows_total", self.capture_arrow_rows),
            ("capture_rows_expanded_total", self.capture_rows_expanded),
        ):
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {val}")
        if self.sink_egress_s:
            lines.append("# TYPE sink_egress_seconds_total counter")
            for name in sorted(self.sink_egress_s):
                lines.append(
                    f'sink_egress_seconds_total{{sink="{name}"}} '
                    f"{self.sink_egress_s[name]:.6f}"
                )
        # device plane (ISSUE 15): globals rendered ALWAYS — the smoke
        # lane asserts device_dispatch_seconds_total > 0 on a traced
        # embed+KNN run AND that a relational run honestly reads 0
        (n_disp, wall_s, dev_s, flops, bytes_acc, xfer, flops_eff,
         mfu, mfu_padded) = self.device_totals()
        for metric, val, fmt in (
            ("device_dispatches_total", n_disp, "{}"),
            ("device_dispatch_seconds_total", dev_s, "{:.6f}"),
            ("device_wall_seconds_total", wall_s, "{:.6f}"),
            ("device_flops_total", flops, "{:.6g}"),
            ("device_flops_effective_total", flops_eff, "{:.6g}"),
            ("device_transfer_bytes_total", xfer, "{}"),
            ("device_recompiles_total",
             sum(self.device_recompiles.values()), "{}"),
        ):
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} " + fmt.format(val))
        for metric, val, fmt in (
            # device_mfu is EFFECTIVE (real rows); the padded variant —
            # what the hardware executed, bucket padding included — is
            # kept alongside so padding waste is auditable (ISSUE 16)
            ("device_mfu", mfu, "{:.6f}"),
            ("device_mfu_padded", mfu_padded, "{:.6f}"),
            ("device_queue_depth", self.device_queue_depth, "{}"),
            ("device_hbm_live_bytes", self.device_hbm_live, "{}"),
            ("device_hbm_peak_bytes", self.device_hbm_peak, "{}"),
            ("device_hbm_stats_available",
             int(self.device_hbm_available), "{}"),
            ("device_peak_flops", self.device_peak_flops, "{:.6g}"),
            ("trace_dropped_events_total", self.trace_dropped_events,
             "{}"),
        ):
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} " + fmt.format(val))
        if self.device_sites:
            # per-site breakdown (bounded: static site-name set)
            for metric, idx, fmt in (
                ("device_site_dispatches_total", 0, "{}"),
                ("device_site_dispatch_seconds_total", 2, "{:.6f}"),
                ("device_site_wall_seconds_total", 1, "{:.6f}"),
                ("device_site_flops_total", 3, "{:.6g}"),
                ("device_site_flops_effective_total", 6, "{:.6g}"),
            ):
                lines.append(f"# TYPE {metric} counter")
                for site in sorted(self.device_sites):
                    agg = self.device_sites[site]
                    val = agg[idx] if idx < len(agg) else 0.0
                    lines.append(
                        f'{metric}{{site="{site}"}} ' + fmt.format(val)
                    )
        if self.device_recompiles:
            lines.append("# TYPE device_site_recompiles_total counter")
            for site in sorted(self.device_recompiles):
                lines.append(
                    f'device_site_recompiles_total{{site="{site}"}} '
                    f"{self.device_recompiles[site]}"
                )
        # device fault domain (ISSUE 17): supervision + index snapshot
        # counters, rendered ALWAYS like the other device globals — a
        # healthy run honestly reads 0 everywhere
        for metric, val, fmt in (
            ("device_dispatch_retries_total",
             sum(self.device_dispatch_retries.values()), "{}"),
            ("device_dispatch_failures_total",
             sum(self.device_dispatch_failures.values()), "{}"),
            ("device_watchdog_trips_total",
             sum(self.device_watchdog_trips.values()), "{}"),
            ("device_oom_events_total",
             sum(self.device_oom_events.values()), "{}"),
            ("device_index_restore_seconds_total",
             self.device_index_restore_s, "{:.6f}"),
            ("device_index_snapshot_bytes_total",
             self.device_index_snapshot_bytes, "{}"),
            ("index_filter_errors_total", self.index_filter_errors, "{}"),
        ):
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} " + fmt.format(val))
        for metric, per_site in (
            ("device_site_dispatch_retries_total",
             self.device_dispatch_retries),
            ("device_site_dispatch_failures_total",
             self.device_dispatch_failures),
            ("device_site_watchdog_trips_total", self.device_watchdog_trips),
            ("device_site_oom_events_total", self.device_oom_events),
        ):
            if per_site:
                lines.append(f"# TYPE {metric} counter")
                for site in sorted(per_site):
                    lines.append(
                        f'{metric}{{site="{site}"}} {per_site[site]}'
                    )
        # memory governance (ISSUE 19): rendered ALWAYS — budget 0 reads
        # as "governance off", not as a missing family. State is encoded
        # by its rung index on the protocol ladder (0 ok, 1 pacing,
        # 2 brownout, 3 abort) so dashboards can alert on >= 1.
        from pathway_tpu.parallel.protocol import MEM_LADDER

        try:
            mem_state_n = MEM_LADDER.index(self.mem_state)
        except ValueError:
            mem_state_n = 0
        for metric, val in (
            ("mem_pressure_state", mem_state_n),
            ("mem_total_bytes", self.mem_total_bytes),
            ("mem_peak_bytes", self.mem_peak_bytes),
            ("mem_budget_bytes", self.mem_budget_bytes),
        ):
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {val}")
        lines.append("# TYPE mem_pressure_injections_total counter")
        lines.append(
            f"mem_pressure_injections_total {self.mem_pressure_injections}"
        )
        if self.mem_components:
            lines.append("# TYPE mem_component_bytes gauge")
            for comp in sorted(self.mem_components):
                lines.append(
                    f'mem_component_bytes{{component="{comp}"}} '
                    f"{self.mem_components[comp]}"
                )
        if self.nodes:
            for metric, idx, fmt in (
                ("node_self_seconds_total", 0, "{:.6f}"),
                ("node_rows_total", 1, "{}"),
                ("node_batches_total", 2, "{}"),
                ("node_nb_batches_total", 3, "{}"),
            ):
                lines.append(f"# TYPE {metric} counter")
                for label, agg in self.nodes.items():
                    lines.append(
                        f'{metric}{{node="{label}"}} '
                        + fmt.format(agg[idx])
                    )
        if self.lag:
            lines.append("# TYPE output_lag_ms histogram")
            for label, h in self.lag.items():
                lines.extend(h.render("output_lag_ms", f'output="{label}"'))
        if self.serve:
            # samples grouped under their TYPE line, per metric across
            # all routes (the OpenMetrics grouping contract)
            for metric, attr in (
                ("serve_requests_total", "requests"),
                ("serve_shed_total", "shed"),
                ("serve_timeouts_total", "timeouts"),
                ("serve_window_commits_total", "commits"),
                ("serve_browned_out_total", "browned_out"),
                ("serve_windows_aborted_total", "windows_aborted"),
            ):
                lines.append(f"# TYPE {metric} counter")
                for sm in self.serve:
                    lines.append(
                        f'{metric}{{route="{sm.route}"}} {getattr(sm, attr)}'
                    )
            lines.append("# TYPE serve_breaker_state gauge")
            for sm in self.serve:
                level = {"closed": 0, "half_open": 1, "open": 2}.get(
                    sm.breaker_state, 0
                )
                lines.append(
                    f'serve_breaker_state{{route="{sm.route}"}} {level}'
                )
            for metric, attr in (
                ("serve_request_latency_ms", "latency"),
                ("serve_batch_occupancy", "occupancy"),
            ):
                lines.append(f"# TYPE {metric} histogram")
                for sm in self.serve:
                    lines.extend(
                        getattr(sm, attr).render(
                            metric, f'route="{sm.route}"'
                        )
                    )
        return "\n".join(lines) + "\n"

    def render_text(self) -> str:
        up = time.time() - self.started_at
        rows = [f"uptime {up:6.1f}s  outputs {self.outputs_emitted}"]
        for st in self.connectors.values():
            line = f"  {st.name:<30} rows={st.rows:<8} batches={st.batches}"
            health = []
            if st.restarts:
                health.append(f"restarts={st.restarts}")
            if st.errors:
                health.append(f"errors={st.errors}")
            if st.stalls:
                health.append(f"stalls={st.stalls}")
            if st.degraded:
                health.append(f"degraded={st.degraded}")
            if health:
                line += "  " + " ".join(health)
            rows.append(line)
        return "\n".join(rows)


def start_http_server(stats: ProberStats, port: int) -> threading.Thread:
    """OpenMetrics endpoint (reference: http_server.rs — port
    20000 + process_id)."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                # liveness probe: flat 200, no metric rendering — k8s
                # probes must stay cheap and never 500 on a metrics bug,
                # and a 503 here during a rollback would make kubelet
                # KILL the pod mid-recovery (readiness lives on /readyz)
                body = b"ok\n"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path == "/readyz":
                # readiness probe: state-aware — draining/recovering
                # answer 503 with the state name so a load balancer
                # rotates traffic away for exactly the rollback blip
                state = getattr(stats, "health_state", "serving")
                body = (
                    b"ok\n" if state == "serving"
                    else f"{state}\n".encode()
                )
                self.send_response(200 if state == "serving" else 503)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            body = stats.render_openmetrics().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            # BaseHTTPRequestHandler's default writes one stderr line
            # per request — a 5s Prometheus scrape interval would bury
            # the pipeline's real logs
            pass

    server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return thread


class _LogGraveyard(logging.Handler):
    """Ring buffer of recent log records for the dashboard's LOGS panel
    (reference: monitoring.py ConsolePrintingToBuffer/LogsOutput)."""

    def __init__(self, capacity: int = 50):
        super().__init__()
        self.capacity = capacity
        self.records: list[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.records.append(self.format(record))
        except Exception:
            return
        if len(self.records) > self.capacity:
            self.records = self.records[-self.capacity :]


def render_dashboard(stats: ProberStats, graveyard=None):
    """One rich renderable frame of the live dashboard (reference:
    python/pathway/internals/monitoring.py:273-class TUI — per-connector
    rows with minibatch / last-minute / total columns, the input/output
    latency table, and the log graveyard)."""
    from rich import box
    from rich.console import Group
    from rich.panel import Panel
    from rich.table import Table

    now = time.time()
    conn = Table(box=box.SIMPLE, title="connectors")
    conn.add_column("connector", justify="left")
    conn.add_column("last minibatch", justify="right")
    conn.add_column("last minute", justify="right")
    conn.add_column("since start", justify="right")
    conn.add_column("health", justify="right")
    for st in stats.connectors.values():
        issues = st.restarts + st.errors + st.stalls + st.degraded
        health = "ok" if not issues else (
            f"r{st.restarts} e{st.errors} s{st.stalls} d{st.degraded}"
        )
        if st.paused:
            # memory governor has this source's reader gated (ISSUE 19)
            health = f"paced {st.paused_seconds:.0f}s | {health}"
        elif st.paused_seconds > 0:
            health = f"paced∑{st.paused_seconds:.0f}s | {health}"
        conn.add_row(
            st.name,
            "finished" if st.finished else str(st.last_minibatch),
            str(st.rows_last_minute(now)),
            str(st.rows),
            health,
        )

    lat = Table(box=box.SIMPLE, title="latency [ms]")
    lat.add_column("operator")
    lat.add_column("latency", justify="right")
    lat.add_row("input", f"{stats.input_latency_ms():.0f}")
    lat.add_row("output", f"{stats.output_latency_ms():.0f}")
    lat.add_row("rows emitted", str(stats.outputs_emitted))
    # event-time lag line (flight recorder watermarks): worst-output
    # freshness, so one glance says how stale downstream consumers are
    if stats.lag:
        worst = max(stats.lag.items(), key=lambda kv: kv[1].quantile(0.5))
        label, h = worst
        lat.add_row(
            f"event-time lag ({label})",
            f"p50≤{h.quantile(0.5):g} p95≤{h.quantile(0.95):g}",
        )

    # whole-pipeline panel: exchange, mesh, fused-chain and serving
    # families — one screen covers ingest → exchange → compute → serve
    pipe = Table(box=box.SIMPLE, title="pipeline")
    pipe.add_column("counter", justify="left")
    pipe.add_column("value", justify="right")
    if stats.exchange_frames or stats.exchange_bytes:
        pipe.add_row(
            "exchange frames/bytes",
            f"{stats.exchange_frames}/{stats.exchange_bytes}",
        )
        pipe.add_row(
            "exchange elided/fallbacks",
            f"{stats.exchange_empty_elided}/{stats.exchange_fallbacks}",
        )
        pipe.add_row(
            "comms/compute [s]",
            f"{stats.exchange_comms_s:.2f}/{stats.exchange_compute_s:.2f}",
        )
        # wire codec line (ISSUE 13): raw vs shipped bytes and the
        # resulting ratio — "compression helped/hurt" at a glance
        if stats.exchange_wire_bytes:
            ratio = stats.exchange_raw_bytes / stats.exchange_wire_bytes
            pipe.add_row(
                "exchange raw/wire bytes",
                f"{stats.exchange_raw_bytes}/{stats.exchange_wire_bytes}"
                f" ({ratio:.2f}x)",
            )
    if stats.mesh_tree_depth:
        pipe.add_row("gather tree depth", str(stats.mesh_tree_depth))
    pipe.add_row("nb_fallbacks", str(stats.nb_fallbacks))
    # columnar egress (ISSUE 14): arrow-delivered vs row-expanded at the
    # sinks — "did the fused chain reach the edge" at a glance
    if stats.capture_arrow_batches or stats.capture_rows_expanded:
        pipe.add_row(
            "egress arrow batches/rows | expanded",
            f"{stats.capture_arrow_batches}/{stats.capture_arrow_rows}"
            f" | {stats.capture_rows_expanded}",
        )
    # device plane (ISSUE 15): dispatches, device-vs-wall seconds, MFU
    # and the HBM gauges — "is the accelerator the limiter" at a glance
    if stats.device_sites:
        (n_disp, wall_s, dev_s, _f, _b, _x, _fe,
         mfu, mfu_padded) = stats.device_totals()
        pipe.add_row(
            "device dispatches (dev/wall s)",
            f"{n_disp} ({dev_s:.2f}/{wall_s:.2f})",
        )
        pipe.add_row(
            "device MFU (eff/padded)", f"{mfu:.3f}/{mfu_padded:.3f}"
        )
        if stats.device_recompiles:
            pipe.add_row(
                "device recompiles",
                str(sum(stats.device_recompiles.values())),
            )
        if stats.device_hbm_available:
            pipe.add_row(
                "device HBM live/peak [MB]",
                f"{stats.device_hbm_live // 2**20}"
                f"/{stats.device_hbm_peak // 2**20}",
            )
    # memory governance (ISSUE 19): ladder state + accounted bytes vs the
    # budget — "is backpressure engaged and how close to the ceiling" at
    # a glance. Shown only when a budget is set (governance on).
    if stats.mem_budget_bytes:
        pipe.add_row(
            "memory ladder",
            f"{stats.mem_state} "
            f"({stats.mem_total_bytes // 2**20}"
            f"/{stats.mem_budget_bytes // 2**20} MB, "
            f"peak {stats.mem_peak_bytes // 2**20})",
        )
    # device fault domain (ISSUE 17): retries/failures/watchdog/OOM at
    # a glance — shown whenever supervision recorded anything
    retries = sum(stats.device_dispatch_retries.values())
    failures = sum(stats.device_dispatch_failures.values())
    trips = sum(stats.device_watchdog_trips.values())
    ooms = sum(stats.device_oom_events.values())
    if retries or failures or trips or ooms:
        pipe.add_row(
            "device retries/failures/watchdog/oom",
            f"{retries}/{failures}/{trips}/{ooms}",
        )
    if stats.device_index_restore_s or stats.device_index_snapshot_bytes:
        pipe.add_row(
            "index snapshot bytes | restore s",
            f"{stats.device_index_snapshot_bytes}"
            f" | {stats.device_index_restore_s:.2f}",
        )
    if stats.index_filter_errors:
        pipe.add_row("index filter errors", str(stats.index_filter_errors))
    if (
        stats.mesh_heartbeats_missed
        or stats.mesh_rank_restarts
        or stats.mesh_rollbacks
        or stats.mesh_last_committed_epoch >= 0
    ):
        pipe.add_row(
            "mesh hb-missed/restarts/rollbacks",
            f"{stats.mesh_heartbeats_missed}/{stats.mesh_rank_restarts}"
            f"/{stats.mesh_rollbacks}",
        )
        pipe.add_row(
            "mesh committed epoch", str(stats.mesh_last_committed_epoch)
        )
    # transactional egress (ISSUE 12): one row per sink — the 2PC
    # balance (staged vs finalized) plus the epoch-lag gauge, so a
    # glance says whether committed output is keeping up with cuts
    for name in sorted(
        set(stats.sink_staged) | set(stats.sink_finalized)
        | set(stats.sink_lag)
    ):
        pipe.add_row(
            f"sink {name} staged/final/lag",
            f"{stats.sink_staged.get(name, 0)}"
            f"/{stats.sink_finalized.get(name, 0)}"
            f"/{stats.sink_lag.get(name, 0)}",
        )
    for sm in stats.serve:
        pipe.add_row(
            f"serve {sm.route} req/shed/timeout",
            f"{sm.requests}/{sm.shed}/{sm.timeouts}",
        )
        pipe.add_row(
            f"serve {sm.route} windows (occ p50)",
            f"{sm.commits} ({sm.occupancy.quantile(0.5):g})",
        )
    if stats.nodes:
        top = sorted(
            stats.nodes.items(), key=lambda kv: kv[1][0], reverse=True
        )[:3]
        for label, agg in top:
            pipe.add_row(
                f"hot {label}",
                f"{agg[0]:.2f}s / {agg[1]} rows",
            )

    parts = [conn, lat, pipe]
    # cluster section (ISSUE 10): when the cluster aggregator is
    # attached (unsupervised rank 0 with PATHWAY_CLUSTER_METRICS_PORT),
    # one row per scraped rank — where each rank's wall-clock went —
    # plus the derived skew/efficiency gauges
    summary = None
    if stats.cluster is not None:
        try:
            summary = stats.cluster.summary()
        except Exception:
            summary = None
    if summary and summary.get("ranks"):
        clus = Table(box=box.SIMPLE, title="cluster")
        clus.add_column("rank", justify="right")
        clus.add_column("rows", justify="right")
        clus.add_column("comms [s]", justify="right")
        clus.add_column("compute [s]", justify="right")
        clus.add_column("idle [s]", justify="right")
        clus.add_column("recv-wait [s]", justify="right")
        for rank in sorted(summary["ranks"]):
            r = summary["ranks"][rank]
            clus.add_row(
                str(rank),
                str(int(r.get("rows", 0))),
                f"{r.get('comms_s', 0.0):.2f}",
                f"{r.get('compute_s', 0.0):.2f}",
                f"{r.get('idle_s', 0.0):.2f}",
                f"{r.get('recv_wait_s', 0.0):.2f}",
            )
        derived = []
        if summary.get("skew_s") is not None:
            derived.append(f"skew {summary['skew_s']:.3f}s")
        if summary.get("rows_per_s") is not None:
            derived.append(f"{summary['rows_per_s']:.0f} rows/s")
        if summary.get("efficiency") is not None:
            derived.append(f"efficiency {summary['efficiency']:.2f}")
        if derived:
            clus.add_row("", "", "", "", "", "  ".join(derived))
        parts.append(clus)
    if graveyard is not None and graveyard.records:
        parts.append(
            Panel(
                "\n".join(graveyard.records[-12:]),
                title="LOGS",
                box=box.MINIMAL,
            )
        )
    return Group(*parts)


def start_dashboard(
    stats: ProberStats, interval: float = 1.0
):
    """Live-updating terminal dashboard; returns (thread, stop_fn).
    Falls back to the plain text printer when rich is unavailable."""
    try:
        from rich.console import Console
        from rich.live import Live
    except ImportError:
        return start_monitor_printer(stats, interval), lambda: None

    graveyard = _LogGraveyard()
    graveyard.setFormatter(logging.Formatter("%(levelname)s %(message)s"))
    logging.getLogger().addHandler(graveyard)
    stop = threading.Event()

    def loop():
        console = Console(stderr=True)
        with Live(
            render_dashboard(stats, graveyard),
            console=console,
            refresh_per_second=2,
            transient=True,
        ) as live:
            while not stop.is_set():
                stop.wait(interval)
                live.update(render_dashboard(stats, graveyard))
        logging.getLogger().removeHandler(graveyard)

    thread = threading.Thread(target=loop, daemon=True)
    thread.start()
    return thread, stop.set


def start_monitor_printer(
    stats: ProberStats, interval: float = 2.0
) -> threading.Thread:
    def loop():
        while True:
            time.sleep(interval)
            print(stats.render_text(), file=sys.stderr)

    thread = threading.Thread(target=loop, daemon=True)
    thread.start()
    return thread
