"""Graph lowering + execution (reference:
python/pathway/internals/graph_runner/__init__.py:36 GraphRunner,
storage_graph.py, expression_evaluator.py — collapsed: our engine scope is
in-process, so column-path planning reduces to schema-order row tuples).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Callable, Iterable

from pathway_tpu.engine.expression import compile_expression
from pathway_tpu.engine.runtime import Runtime
from pathway_tpu.engine.scope import EngineTable
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.parse_graph import G, Operator
from pathway_tpu.internals.universe import SOLVER

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class LoweringContext:
    def __init__(self, runtime: Runtime):
        self.runtime = runtime
        self.scope = runtime.scope
        self.engine_tables: dict[int, EngineTable] = {}

    # -- table registry ---------------------------------------------------
    def set_engine_table(self, table: "Table", et: EngineTable) -> None:
        self.engine_tables[id(table)] = et

    def engine_table(self, table: "Table") -> EngineTable:
        try:
            return self.engine_tables[id(table)]
        except KeyError:
            raise RuntimeError(
                f"table {table._name!r} was not lowered before use"
            ) from None

    # -- expression compilation -------------------------------------------
    def _combined_view(
        self, base: "Table", exprs: Iterable[expr_mod.ColumnExpression]
    ) -> tuple[EngineTable, Callable[[expr_mod.ColumnReference], Any]]:
        """Engine input holding base's row (+ id-joined rows of any other
        same-universe tables referenced by `exprs`) and a ref resolver."""
        dep_tables: list[Table] = []
        seen = {id(base)}
        for e in exprs:
            for ref in e._deps:
                t = ref.table
                if id(t) not in seen:
                    seen.add(id(t))
                    dep_tables.append(t)
        combined = self.engine_table(base)
        offsets: dict[int, int] = {id(base): 0}
        width = combined.width
        for t in dep_tables:
            if not (
                SOLVER.query_are_equal(base._universe, t._universe)
                or SOLVER.query_is_subset(base._universe, t._universe)
            ):
                raise ValueError(
                    f"expression references table {t._name!r} with an unrelated "
                    f"universe; use .restrict()/.ix() first"
                )
            other = self.engine_table(t)
            # join keys are 1-tuples (not bare Pointers) so the native
            # delta-join serializer accepts them — id-joins are the hot
            # path behind every cross-table expression
            combined = self.scope.join(
                combined,
                other,
                lambda k, row: (k,),
                lambda k, row: (k,),
                "inner",
                id_from_left=True,
                lkey_batch=lambda keys, rows: [(k,) for k in keys],
                rkey_batch=lambda keys, rows: [(k,) for k in keys],
            )
            offsets[id(t)] = width
            width += other.width

        def resolver(ref: expr_mod.ColumnReference):
            if ref.name == "id":
                return "id"
            t = ref.table
            try:
                idx = t._column_names.index(ref.name)
            except ValueError:
                raise KeyError(
                    f"no column {ref.name!r} in table {t._name!r} "
                    f"(columns: {t._column_names})"
                ) from None
            return offsets[id(t)] + idx

        return combined, resolver

    def rowwise_eval(
        self, base: "Table", exprs: list[expr_mod.ColumnExpression]
    ) -> tuple[EngineTable, Callable]:
        """Returns (engine_input, fn(keys, rows) -> list of output row tuples)."""
        combined, resolver = self._combined_view(base, exprs)
        fns = [compile_expression(e, resolver, self.runtime) for e in exprs]

        def batch_fn(keys, rows):
            cols = [f(keys, rows) for f in fns]
            return list(zip(*cols)) if cols else [()] * len(keys)

        return combined, batch_fn

    def mask_eval(
        self, base: "Table", e: expr_mod.ColumnExpression
    ) -> tuple[EngineTable, Callable]:
        combined, resolver = self._combined_view(base, [e])
        fn = compile_expression(e, resolver, self.runtime)
        return combined, fn

    def row_fn(
        self, base: "Table", exprs: list[expr_mod.ColumnExpression]
    ) -> tuple[EngineTable, Callable]:
        """Per-row variant: fn(key, row) -> tuple of values (for key fns).
        ``fn.batch(keys, rows) -> list of per-expr columns`` lets batch
        consumers (the time-gate operators) evaluate each expression once
        per batch instead of once per row."""
        combined, resolver = self._combined_view(base, exprs)
        fns = [compile_expression(e, resolver, self.runtime) for e in exprs]

        def one(key, row):
            return tuple(f([key], [row])[0] for f in fns)

        one.batch = lambda keys, rows: [f(keys, rows) for f in fns]
        return combined, one


_lower_lock = None  # serializes lowering across emulated-rank threads


class GraphRunner:
    """Lower + run the captured graph (reference:
    graph_runner/__init__.py:86 run_nodes / :96 run_tables / :113 run_outputs).

    Emulated-rank CI lane: with ``PATHWAY_LANE_PROCESSES=N`` set (and no
    real multi-process config), every run transparently spawns N-1
    companion ranks as THREADS of this process — each with a per-thread
    config overlay (process_id, first_port) and its own Runtime — joined
    over the real loopback TCP mesh. This re-runs the entire semantics
    battery through the lockstep exchange protocol (reference CI pattern:
    the suite re-runs under PATHWAY_THREADS=n / real process forks,
    python/pathway/tests/utils.py:31-48,599-677)."""

    def __init__(
        self,
        parse_graph=None,
        *,
        terminate_on_error: bool = True,
        persistence_config=None,
        with_http_server: bool = False,
        monitoring_level=None,
        **kwargs,
    ):
        self.graph = parse_graph or G
        self.terminate_on_error = terminate_on_error
        self.persistence_config = persistence_config
        self.with_http_server = with_http_server
        self.monitoring_level = monitoring_level

    def _make_runtime(self) -> Runtime:
        persistence = None
        if self.persistence_config is not None:
            from pathway_tpu.persistence import PersistenceManager

            persistence = PersistenceManager(self.persistence_config)
        return Runtime(
            terminate_on_error=self.terminate_on_error,
            persistence=persistence,
            with_http_server=self.with_http_server,
            monitoring_level=self.monitoring_level,
        )

    def _lower(self, ops: list[Operator], runtime: Runtime) -> LoweringContext:
        ctx = LoweringContext(runtime)
        try:
            for op in ops:
                # nodes created during this lower inherit the operator's
                # user frame for error attribution (reference:
                # EngineErrorWithTrace, graph_runner/__init__.py:217-229)
                runtime.current_trace = op.trace
                op.lower_fn(ctx)
        finally:
            runtime.current_trace = None
        return ctx

    @staticmethod
    def _lane_world() -> int:
        import os

        from pathway_tpu.internals.config import get_pathway_config

        try:
            n = int(os.environ.get("PATHWAY_LANE_PROCESSES", "1") or 1)
        except ValueError:
            return 1
        if n > 1 and get_pathway_config().processes == 1:
            return n
        return 1

    def _with_companions(self, ops, rank0_fn, companion_extra=None):
        """Run rank0_fn() with N-1 companion rank threads when the
        emulated lane is active; transparent no-op otherwise.
        companion_extra(runtime, ctx) mirrors any post-lowering graph
        construction rank 0 performs (captures) — the ranks' graphs must
        be shape-identical or the lockstep exchange sets diverge."""
        import threading

        n = self._lane_world()
        if n <= 1:
            return rank0_fn()
        global _lower_lock
        if _lower_lock is None:
            _lower_lock = threading.Lock()
        import socket

        from pathway_tpu.internals.config import (
            pop_config_overlay,
            push_config_overlay,
        )

        def free_port_base() -> int:
            # need n consecutive free ports (rank r listens on base + r)
            for _ in range(50):
                probe = socket.socket()
                probe.bind(("127.0.0.1", 0))
                base = probe.getsockname()[1]
                probe.close()
                held = []
                try:
                    for i in range(n):
                        s = socket.socket()
                        s.bind(("127.0.0.1", base + i))
                        held.append(s)
                    return base
                except OSError:
                    continue
                finally:
                    for s in held:
                        s.close()
            raise RuntimeError("no consecutive free port range found")

        port = free_port_base()
        errors: list = []
        companion_rts: list = []

        def companion(rank: int) -> None:
            token = push_config_overlay(
                processes=n, process_id=rank, first_port=port
            )
            try:
                rt = self._make_runtime()
                rt._lane_emulated = True
                companion_rts.append(rt)
                with _lower_lock:
                    ctx = self._lower(ops, rt)
                    if companion_extra is not None:
                        companion_extra(rt, ctx)
                rt.run()
            except Exception as exc:  # surfaced on the main thread
                errors.append((rank, exc))
            finally:
                pop_config_overlay(token)

        threads = [
            threading.Thread(target=companion, args=(r,), daemon=True)
            for r in range(1, n)
        ]
        for t in threads:
            t.start()
        token = push_config_overlay(
            processes=n, process_id=0, first_port=port
        )
        rank0_exc: BaseException | None = None
        result = None
        try:
            result = rank0_fn()
        except BaseException as exc:
            rank0_exc = exc
        finally:
            pop_config_overlay(token)
            if rank0_exc is not None:
                # unblock companions stuck in collectives or mesh setup:
                # closing their sockets surfaces ConnectionError there.
                # Failure-path close: no goodbye frame, so companions
                # classify the loss as a crash, not a clean shutdown
                for rt in companion_rts:
                    pg = getattr(rt, "_procgroup", None)
                    if pg is not None:
                        try:
                            pg.close(goodbye=False)
                        except Exception:
                            pass
            for t in threads:
                t.join(timeout=120)
        if rank0_exc is not None:
            # a companion's real failure beats rank 0's secondary
            # disconnect error (the raising rank closes the mesh, peers
            # observe ConnectionError)
            if errors and isinstance(rank0_exc, ConnectionError):
                raise errors[0][1]
            raise rank0_exc
        if errors:
            raise errors[0][1]
        return result

    def run_tables(self, *tables: "Table", include_outputs: bool = False):
        """Run to completion, capturing the given tables' final state +
        update streams.  Returns list of CaptureNodes."""
        targets = [t._source for t in tables if t._source is not None]
        if include_outputs:
            targets += self.graph.output_operators()
        ops = self.graph.reachable_operators(targets)

        # captured BEFORE _with_companions pushes the rank-0 overlay
        lane_active = self._lane_world() > 1

        def rank0():
            runtime = self._make_runtime()
            if lane_active:
                runtime._lane_emulated = True
                with _lower_lock:
                    ctx = self._lower(ops, runtime)
            else:
                ctx = self._lower(ops, runtime)
            captures = [
                runtime.scope.capture(ctx.engine_table(t)) for t in tables
            ]
            runtime.run()
            return captures

        def companion_extra(rt, ctx):
            # mirror rank 0's capture nodes (gather exchanges included) so
            # every rank's graph has identical shape; the gathers route all
            # rows to rank 0, so these captures stay empty
            for t in tables:
                rt.scope.capture(ctx.engine_table(t))

        return self._with_companions(ops, rank0, companion_extra)

    def run_outputs(self):
        from pathway_tpu.internals.config import get_pathway_config
        from pathway_tpu.internals.telemetry import Telemetry

        targets = self.graph.output_operators()
        ops = self.graph.reachable_operators(targets)

        # captured BEFORE _with_companions pushes the rank-0 overlay —
        # under the overlay the lane looks like real multi-process
        lane_active = self._lane_world() > 1

        def rank0():
            runtime = self._make_runtime()
            telemetry = Telemetry.create(
                get_pathway_config().monitoring_server,
                stats=getattr(runtime, "stats", None),
            )
            if lane_active:
                runtime._lane_emulated = True
                with telemetry.span(
                    "graph_runner.build", n_operators=len(ops)
                ), _lower_lock:
                    self._lower(ops, runtime)
            else:
                with telemetry.span(
                    "graph_runner.build", n_operators=len(ops)
                ):
                    self._lower(ops, runtime)
            with telemetry.span("graph_runner.run"):
                runtime.run()
            # flush-on-shutdown: short runs must not exit with buffered
            # spans/gauges unsent (the periodic pusher is on a 60 s
            # cadence); the flight recorder's per-node aggregate spans
            # ride the same OTLP channel
            drain = getattr(telemetry, "drain", None)
            if drain is not None:
                summary = getattr(runtime, "trace_summary", None) or {}
                drain(
                    node_spans=summary.get("node_spans"), timeout=2.0
                )
            else:
                flush = getattr(telemetry, "flush", None)
                if flush is not None:
                    flush(timeout=2.0)

        return self._with_companions(ops, rank0)
