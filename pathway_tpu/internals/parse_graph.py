"""Global graph capture (reference: python/pathway/internals/parse_graph.py:104,
global instance ``G`` at :244; operator hierarchy internals/operator.py).

Nothing executes at declaration time: every Table method appends an
``Operator`` to ``G``.  ``pw.run()`` / ``pw.debug.compute_and_print`` lower
the reachable subgraph onto an engine Runtime (graph_runner.py).
"""

from __future__ import annotations

import contextlib
import itertools
from typing import TYPE_CHECKING, Any, Callable

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class Operator:
    """A captured graph node.

    ``lower_fn(ctx)`` is responsible for computing engine tables for every
    output table and registering them via ``ctx.set_engine_table``.
    """

    _ids = itertools.count()

    def __init__(
        self,
        inputs: list["Table"],
        outputs: list["Table"],
        lower_fn: Callable[[Any], None],
        name: str,
        is_output: bool = False,
    ):
        self.id = next(Operator._ids)
        self.inputs = inputs
        self.outputs = outputs
        self.lower_fn = lower_fn
        self.name = name
        self.is_output = is_output
        self.trace = _user_frame()
        for t in outputs:
            t._source = self

    def __repr__(self):
        return f"Operator#{self.id}({self.name})"


class ParseGraph:
    def __init__(self):
        self.operators: list[Operator] = []
        self.cache: dict[Any, Any] = {}

    def add_operator(
        self,
        inputs: list["Table"],
        outputs: list["Table"],
        lower_fn: Callable[[Any], None],
        name: str,
        is_output: bool = False,
    ) -> Operator:
        op = Operator(inputs, outputs, lower_fn, name, is_output)
        self.operators.append(op)
        return op

    def output_operators(self) -> list[Operator]:
        return [op for op in self.operators if op.is_output]

    def reachable_operators(self, targets: list[Operator]) -> list[Operator]:
        """Tree-shake: ancestors of targets, in creation (topological) order."""
        needed: set[int] = set()
        stack = list(targets)
        while stack:
            op = stack.pop()
            if op.id in needed:
                continue
            needed.add(op.id)
            for t in op.inputs:
                if t._source is not None:
                    stack.append(t._source)
        return [op for op in self.operators if op.id in needed]

    def clear(self) -> None:
        self.operators.clear()
        self.cache.clear()

    @contextlib.contextmanager
    def scoped(self):
        """Capture operators declared inside the block into a private list
        instead of the global graph (reference: iterate subscopes,
        parse_graph.py Scope :27). Yields the list; on exit the global
        operator list is restored."""
        saved = self.operators
        self.operators = []
        try:
            yield self.operators
        finally:
            self.operators = saved


G = ParseGraph()


def _user_frame():
    """First stack frame outside this package — the user line that declared
    the operator (reference: internals/trace.py; re-raise at
    graph_runner/__init__.py:217-229)."""
    import traceback

    pkg = __name__.split(".")[0]
    for frame in reversed(traceback.extract_stack()[:-2]):
        fname = frame.filename.replace("\\", "/")
        if f"/{pkg}/" not in fname and "<frozen" not in fname:
            return frame
    return None


def clear_graph() -> None:
    G.clear()
