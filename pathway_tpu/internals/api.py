"""Engine-facing value types: Pointer keys, Json, PyObjectWrapper, errors.

Rebuild of the reference's value system (reference: src/engine/value.rs:207
``enum Value``; key type at value.rs:507).  Keys are 128-bit in the reference;
we use 128-bit ints derived from blake2b so that derived ids are stable across
runs and processes (required for persistence and multi-host determinism).
"""

from __future__ import annotations

import hashlib
import json as _json
import struct
from typing import Any, Iterable

import numpy as np

_KEY_MASK = (1 << 128) - 1


class Pointer(int):
    """Row id — 128-bit key (reference: value.rs Key).

    Subclasses int so it hashes/sorts natively and is cheap to shard
    (``key % n_shards``) while printing like a pathway pointer.
    """

    __slots__ = ()

    def __repr__(self) -> str:
        return f"^{int(self):032X}"[:12] + "..."

    def __str__(self) -> str:
        return self.__repr__()


def _hash_bytes(data: bytes) -> Pointer:
    digest = hashlib.blake2b(data, digest_size=16).digest()
    return Pointer(int.from_bytes(digest, "little") & _KEY_MASK)


def _value_to_bytes(value: Any) -> bytes:
    if value is None:
        return b"\x00"
    if isinstance(value, Pointer):
        return b"P" + int(value).to_bytes(16, "little")
    if isinstance(value, bool):
        return b"B" + (b"\x01" if value else b"\x00")
    if isinstance(value, int):
        return b"I" + value.to_bytes((value.bit_length() + 8) // 8 + 1, "little", signed=True)
    if isinstance(value, float):
        return b"F" + struct.pack("<d", value)
    if isinstance(value, str):
        return b"S" + value.encode("utf-8")
    if isinstance(value, bytes):
        return b"Y" + value
    if isinstance(value, tuple):
        return b"T" + _concat_lp([_value_to_bytes(v) for v in value])
    if isinstance(value, np.ndarray):
        # dtype + shape + data: keeps [1,2] distinct from [[1],[2]] etc.
        return b"A" + _concat_lp(
            [
                value.dtype.str.encode(),
                np.asarray(value.shape, dtype=np.int64).tobytes(),
                value.tobytes(),
            ]
        )
    if isinstance(value, Json):
        return b"J" + _json.dumps(value.value, sort_keys=True, default=str).encode()
    return b"O" + repr(value).encode()


def _concat_lp(parts: list[bytes]) -> bytes:
    """Length-prefixed concatenation — injective, unlike separator joins."""
    return struct.pack("<I", len(parts)) + b"".join(
        struct.pack("<I", len(p)) + p for p in parts
    )


_fp_mod: Any = False


def _fp():
    """Shared lazy accessor for the native fastpath module (resolution
    itself delegates to pathway_tpu.engine.stream.get_fp; the result is
    memoized here to keep the key-mint hot path import-free)."""
    global _fp_mod
    if _fp_mod is False:
        try:
            from pathway_tpu.engine.stream import get_fp

            _fp_mod = get_fp()
        except Exception:
            _fp_mod = None
    return _fp_mod


def _args_bytes(args: tuple) -> bytes:
    fp = _fp()
    if fp is not None:
        return fp.value_bytes(args)
    return _concat_lp([_value_to_bytes(a) for a in args])


def ref_scalar(*args: Any, optional: bool = False) -> Pointer:
    """Deterministic pointer from values (reference: python_api ref_scalar).
    The native fast path (fastpath.ref_scalar) mints byte-identical keys:
    same serialization, same blake2b-128 — verified by
    tests/test_native_keys.py."""
    if optional and any(a is None for a in args):
        return None  # type: ignore[return-value]
    fp = _fp()
    if fp is not None:
        return fp.ref_scalar(args)
    return _hash_bytes(_args_bytes(args))


_unsafe_counter = [0]


def unsafe_make_pointer(arg: int) -> Pointer:
    return Pointer(int(arg) & _KEY_MASK)


def sequential_pointer() -> Pointer:
    _unsafe_counter[0] += 1
    return Pointer(_unsafe_counter[0])


_NAV_MISSING = object()


def json_navigate(value: Any, index: Any):
    """TOTAL JSON navigation (reference: test_json.py pins — missing
    keys, out-of-range AND negative indices, and non-container values
    all yield null, never an error; no Python-style wraparound).
    Returns the raw inner value or _NAV_MISSING. The single source of
    truth for both expression-level ``j[i]``/``.get`` (engine
    eval_get) and ``Json`` object accessors."""
    if isinstance(index, bool):
        return _NAV_MISSING
    if isinstance(value, dict):
        if isinstance(index, (str, int)):
            return value.get(index, _NAV_MISSING)
        return _NAV_MISSING
    if isinstance(value, list):
        if isinstance(index, int) and 0 <= index < len(value):
            return value[index]
        return _NAV_MISSING
    return _NAV_MISSING


class Json:
    """JSON value wrapper (reference: Value::Json)."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        if isinstance(value, Json):
            value = value.value
        self.value = value

    # -- navigation ------------------------------------------------------
    def __getitem__(self, key):
        v = json_navigate(self.value, key)
        return Json(None if v is _NAV_MISSING else v)

    def get(self, key, default=None):
        out = json_navigate(self.value, key)
        if out is _NAV_MISSING:
            out = default
        return Json(out) if not isinstance(out, Json) else out

    def as_int(self) -> int:
        return int(self.value)

    def as_float(self) -> float:
        return float(self.value)

    def as_str(self) -> str:
        return str(self.value)

    def as_bool(self) -> bool:
        return bool(self.value)

    def as_list(self) -> list:
        return list(self.value)

    def as_dict(self) -> dict:
        return dict(self.value)

    def to_json_string(self) -> str:
        return _json.dumps(self.value, default=str)

    @staticmethod
    def parse(s: str | bytes) -> "Json":
        return Json(_json.loads(s))

    def __eq__(self, other):
        if isinstance(other, Json):
            return self.value == other.value
        return self.value == other

    def __hash__(self):
        return hash(_json.dumps(self.value, sort_keys=True, default=str))

    def __repr__(self):
        return _json.dumps(self.value, default=str)

    def __bool__(self):
        return bool(self.value)

    def __iter__(self):
        return (Json(v) for v in self.value)

    def __len__(self):
        return len(self.value)


class PyObjectWrapper:
    """Opaque python object carried through the dataflow (reference: Value::PyObjectWrapper)."""

    __slots__ = ("value", "_serializer")

    def __init__(self, value: Any, *, serializer: Any = None):
        self.value = value
        self._serializer = serializer

    def __eq__(self, other):
        return isinstance(other, PyObjectWrapper) and self.value == other.value

    def __hash__(self):
        try:
            return hash(self.value)
        except TypeError:
            return hash(id(self.value))

    def __repr__(self):
        return f"PyObjectWrapper({self.value!r})"


def wrap_py_object(value: Any, *, serializer: Any = None) -> PyObjectWrapper:
    return PyObjectWrapper(value, serializer=serializer)


class Error:
    """Poison value (reference: Value::Error, src/engine/error.rs)."""

    _instance: "Error | None" = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "Error"

    def __bool__(self):
        raise ValueError("cannot convert Error value to bool")


ERROR = Error()


def is_error(value: Any) -> bool:
    return value is ERROR


class Pending:
    """Placeholder for not-yet-computed Future values (reference: Value::Pending)."""

    _instance: "Pending | None" = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "Pending"


PENDING = Pending()


class EngineError(Exception):
    pass


class EngineErrorWithTrace(Exception):
    def __init__(self, error: Exception, trace: Any = None):
        msg = str(error)
        if trace is not None:
            msg = f"{msg}\noccurred in operator declared at {trace}"
        super().__init__(msg)
        self.error = error
        self.trace = trace


def hash_any(value: Any) -> int:
    """Stable 64-bit hash of any engine value (sharding, LSH buckets)."""
    return int.from_bytes(
        hashlib.blake2b(_value_to_bytes(value), digest_size=8).digest(), "little"
    )


def combine_pointers(*ptrs: Iterable[Pointer]) -> Pointer:
    return ref_scalar(*ptrs)
