"""Legacy class transformers (reference:
python/pathway/internals/row_transformer.py:294 +
graph_runner/row_transformer_operator_handler.py — `@pw.transformer`
classes with lazy pointer-chasing attribute access).

The modern surface (select/apply/AsyncTransformer) covers the same ground;
this provides the decorator API for programs written against it. Each
output attribute is computed per row with a `self` proxy that can follow
pointers into other transformer tables (the reference's Computer
machinery, python_api.rs:2092)."""

from __future__ import annotations

from types import SimpleNamespace
from typing import Any, Callable


class attribute:  # noqa: N801 — reference API names
    """Marks a computed output attribute."""

    def __init__(self, fn: Callable | None = None):
        self.fn = fn

    def __call__(self, fn):
        self.fn = fn
        return self


class input_attribute:  # noqa: N801
    """Marks a column taken from the input table."""

    def __init__(self, dtype=None):
        self.dtype = dtype


class input_method:  # noqa: N801
    def __init__(self, dtype=None):
        self.dtype = dtype


class output_attribute(attribute):  # noqa: N801
    pass


class method(attribute):  # noqa: N801
    pass


class _RowProxy:
    """`self` inside transformer methods: columns + pointer navigation."""

    def __init__(self, cls_ns, tables, table_name, key, row_lookup):
        self._cls_ns = cls_ns
        self._tables = tables
        self._table = table_name
        self._key = key
        self._row_lookup = row_lookup  # (table_name, key) -> dict

    @property
    def id(self):
        return self._key

    def transformer(self):
        return SimpleNamespace(
            **{
                name: _TableProxy(self._cls_ns, self._tables, name, self._row_lookup)
                for name in self._tables
            }
        )

    def __getattr__(self, name):
        ns = self._cls_ns[self._table]
        row = self._row_lookup(self._table, self._key)
        if name in row:
            return row[name]
        spec = ns.get(name)
        if isinstance(spec, method):
            # bound method: called with extra args by other attributes
            return lambda *a, **k: spec.fn(self, *a, **k)
        if isinstance(spec, attribute):
            return spec.fn(self)
        raise AttributeError(name)


class _TableProxy:
    def __init__(self, cls_ns, tables, table_name, row_lookup):
        self._cls_ns = cls_ns
        self._tables = tables
        self._table = table_name
        self._row_lookup = row_lookup

    def __getitem__(self, key):
        return _RowProxy(
            self._cls_ns, self._tables, self._table, key, self._row_lookup
        )


def transformer(cls):
    """@pw.transformer — per-row computed attributes over one or more
    input tables; returns a factory taking the input tables and yielding a
    namespace of output tables."""
    table_specs: dict[str, dict[str, Any]] = {}
    for tname, tcls in vars(cls).items():
        if tname.startswith("_") or not isinstance(tcls, type):
            continue
        table_specs[tname] = dict(vars(tcls))

    def build(**input_tables):
        import pathway_tpu as pw
        from pathway_tpu.internals import dtype as dt
        from pathway_tpu.internals.expression import apply_with_type
        from pathway_tpu.internals import reducers

        # materialize every input table's rows into one packed lookup;
        # internal column name must not collide with user columns
        packed = {}
        for tname, table in input_tables.items():
            cols = table.column_names()
            packed[tname] = table.reduce(
                **{"_pw_packed_ids": reducers.tuple(table.id)},
                **{c: reducers.tuple(table[c]) for c in cols},
            )

        outputs = {}
        for tname, spec in table_specs.items():
            table = input_tables[tname]
            in_cols = [
                n for n, s in spec.items() if isinstance(s, input_attribute)
            ]
            out_attrs = {
                n: s
                for n, s in spec.items()
                if isinstance(s, attribute) and not isinstance(s, method)
            }
            if not out_attrs:
                continue

            # single batched computation over all rows of all tables; the
            # packed singletons share the same (empty-groupby) key, so they
            # can be unified onto one universe for the combined view
            base = packed[tname]
            all_packed_cols = []
            layout = []
            for pname, ptable in packed.items():
                pcols = input_tables[pname].column_names()
                layout.append((pname, pcols))
                if pname != tname:
                    ptable = ptable._unsafe_promise_universe(base)
                all_packed_cols.append(ptable["_pw_packed_ids"])
                all_packed_cols.extend(ptable[c] for c in pcols)

            def compute(ids, *flat, _spec=out_attrs, _tname=tname, _layout=layout):
                data: dict[str, dict] = {}
                pos = 0
                for pname, pcols in _layout:
                    p_ids = flat[pos]
                    pos += 1
                    cols_vals = flat[pos : pos + len(pcols)]
                    pos += len(pcols)
                    data[pname] = {
                        k: dict(zip(pcols, vals))
                        for k, vals in zip(
                            p_ids, zip(*cols_vals) if cols_vals else [()] * len(p_ids)
                        )
                    }

                def row_lookup(t, k):
                    return data[t][k]

                out_rows = []
                for key in ids:
                    proxy = _RowProxy(
                        table_specs, list(input_tables), _tname, key, row_lookup
                    )
                    out_rows.append(
                        (key,)
                        + tuple(s.fn(proxy) for s in _spec.values())
                    )
                return tuple(out_rows)

            applied = base.select(
                rows=apply_with_type(
                    compute, dt.ANY, base["_pw_packed_ids"], *all_packed_cols
                )
            )
            flat = applied.flatten(applied.rows)
            from pathway_tpu.internals.expression import GetExpression

            sel = {"_pw_row_id": GetExpression(flat.rows, 0)}
            for i, n in enumerate(out_attrs):
                sel[n] = GetExpression(flat.rows, i + 1)
            result = flat.select(**sel)
            result = (
                result._with_id_unchecked(result["_pw_row_id"])
                .without("_pw_row_id")
                ._unsafe_promise_universe(table)
            )
            outputs[tname] = result

        return SimpleNamespace(**outputs)

    build.__name__ = cls.__name__
    return build
