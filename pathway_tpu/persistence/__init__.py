"""pw.persistence — checkpoint/resume (reference:
python/pathway/persistence/__init__.py:13 Backend / :88 Config; engine
side src/persistence/: input snapshots (input_snapshot.rs:217), offset
frontiers (frontier.rs), commit tracker (tracker.rs:47), backends
(backends/{file,s3,memory,mock}.rs)).

Model: every connector's parsed event batches are journaled with their
commit timestamps (write-ahead, before the engine steps them); connector
subjects may persist their own scan state (`snapshot_state`/`seek`). On
restart the journal replays first — byte-identical batches at fresh
timestamps — then the subject resumes from its stored state, so outputs
continue exactly once past the last durable commit.
"""

from __future__ import annotations

import io as _io
import json as _json
import os
import pickle
import threading
from dataclasses import dataclass, field
from typing import Any

from pathway_tpu.internals import faults as _faults

# Trust boundary: anyone able to write the persistence root can influence
# what restarts load. Journal entries and subject scan states hold plain
# engine values, so they are deserialized through an allow-listed
# unpickler (no arbitrary class resolution -> no code execution on load,
# matching the reference's non-executable bincode snapshots). Operator
# snapshots may legitimately contain user-defined reducer state and DO use
# full pickle — the persistence root must be trusted to the same degree as
# the program's own code for OPERATOR_PERSISTING mode.
_SAFE_MODULES = {
    "collections",
    "datetime",
    "pathway_tpu.internals.api",
}
# builtins and numpy must be NAME-allowlisted, not module-allowlisted:
# builtins.eval/exec and numpy.testing._private.utils.runstring (a thin
# exec wrapper) would reopen the code-execution hole
_SAFE_BUILTINS = {
    "list", "dict", "set", "frozenset", "tuple", "bytearray", "complex",
    "bytes", "str", "int", "float", "bool", "range", "slice", "object",
}
# the reconstructors ndarray/dtype/scalar pickles actually reference
_SAFE_NUMPY = {
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.numeric", "_frombuffer"),
}


class _SafeUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if module == "builtins":
            if name in _SAFE_BUILTINS:
                return super().find_class(module, name)
        elif module.split(".")[0] == "numpy":
            if (module, name) in _SAFE_NUMPY or (
                module == "numpy" and name.startswith(("int", "uint", "float", "bool", "complex"))
            ):
                return super().find_class(module, name)
        elif module in _SAFE_MODULES:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"persistence journal refuses to resolve {module}.{name}; "
            "only plain engine values are allowed in journal/subject-state "
            "records"
        )


def _safe_loads(data: bytes):
    return _SafeUnpickler(_io.BytesIO(data)).load()


class _BackendBase:
    def write(self, key: str, data: bytes) -> None: ...

    def read(self, key: str) -> bytes | None: ...

    def list_keys(self, prefix: str) -> list[str]: ...


class _FsBackend(_BackendBase):
    def __init__(self, path: str):
        self.root = path
        os.makedirs(path, exist_ok=True)

    def _p(self, key: str) -> str:
        return os.path.join(self.root, key)

    def write(self, key: str, data: bytes) -> None:
        path = self._p(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic cut point (reference: tracker.rs)

    def append(self, key: str, data: bytes) -> None:
        path = self._p(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "ab") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    def read(self, key: str) -> bytes | None:
        try:
            with open(self._p(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def list_keys(self, prefix: str) -> list[str]:
        out = []
        for root, _, files in os.walk(self.root):
            for name in files:
                rel = os.path.relpath(os.path.join(root, name), self.root)
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._p(key))
        except FileNotFoundError:
            pass


class _ObjectStoreBackend(_BackendBase):
    """Persistence over an object store (reference: S3 backend,
    src/persistence/backends/s3.rs:47 — whole-object put/get, no append).

    Appends map to sequential part objects ``<key>.part/<n>``; reads
    concatenate the base object plus parts in order, so the journal's
    append-only contract holds on stores without native append. ``client``
    is anything with ``upload(path, bytes)``, ``download(path) -> bytes |
    None`` and ``list(prefix) -> [path]`` — the GCS adapter below, or a
    fake in tests.
    """

    def __init__(self, client, root: str = ""):
        self.client = client
        self.root = root.strip("/")

    def _p(self, key: str) -> str:
        return f"{self.root}/{key}" if self.root else key

    def write(self, key: str, data: bytes) -> None:
        # truncate-replace semantics (matching _FsBackend.write): stale
        # appended parts must not survive a rewrite of the base object
        delete = getattr(self.client, "delete", None)
        if delete is not None:
            for part in self.client.list(self._p(key) + ".part/"):
                delete(part)
        self.client.upload(self._p(key), data)

    def append(self, key: str, data: bytes) -> None:
        part_prefix = self._p(key) + ".part/"
        existing = self.client.list(part_prefix)
        self.client.upload(part_prefix + f"{len(existing):08d}", data)

    def read(self, key: str) -> bytes | None:
        base = self.client.download(self._p(key))
        parts = sorted(self.client.list(self._p(key) + ".part/"))
        if base is None and not parts:
            return None
        chunks = [base or b""]
        for p in parts:
            chunk = self.client.download(p)
            if chunk is not None:
                chunks.append(chunk)
        return b"".join(chunks)

    def list_keys(self, prefix: str) -> list[str]:
        out = set()
        for path in self.client.list(self._p(prefix)):
            rel = path[len(self.root) + 1 :] if self.root else path
            out.add(rel.split(".part/")[0])
        return sorted(out)

    def delete(self, key: str) -> None:
        delete = getattr(self.client, "delete", None)
        if delete is None:
            return
        for part in self.client.list(self._p(key) + ".part/"):
            delete(part)
        delete(self._p(key))


class _GcsClient:
    """google-cloud-storage adapter for _ObjectStoreBackend."""

    def __init__(self, bucket_name: str, client=None):
        if client is None:
            from google.cloud import storage

            client = storage.Client()
        self.bucket = client.bucket(bucket_name)
        self._client = client
        self._bucket_name = bucket_name

    def upload(self, path: str, data: bytes) -> None:
        self.bucket.blob(path).upload_from_string(data)

    def download(self, path: str) -> bytes | None:
        blob = self.bucket.blob(path)
        try:
            return blob.download_as_bytes()
        except Exception:
            return None

    def list(self, prefix: str) -> list[str]:
        return [b.name for b in self._client.list_blobs(
            self._bucket_name, prefix=prefix
        )]

    def delete(self, path: str) -> None:
        try:
            self.bucket.blob(path).delete()
        except Exception:
            pass  # already gone


class _S3PersistClient:
    """io/_s3.S3Client adapter for _ObjectStoreBackend.

    Only a definitive 404 maps to "absent": transient transport errors
    MUST propagate — treating them as missing journals would resume from
    an empty/truncated journal and replay inputs past the last durable
    commit (breaking exactly-once), and a swallowed failed delete would
    leave stale .part objects corrupting the next read's concatenation.
    """

    def __init__(self, client):
        self._client = client

    def upload(self, path: str, data: bytes) -> None:
        self._client.put_object(path, data)

    def download(self, path: str) -> bytes | None:
        import urllib.error

        try:
            return self._client.get_object(path)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def list(self, prefix: str) -> list[str]:
        return [o.key for o in self._client.list_objects(prefix)]

    def delete(self, path: str) -> None:
        # S3Client.delete_object already treats 404 as success and
        # re-raises anything else
        self._client.delete_object(path)


class _MemoryBackend(_BackendBase):
    def __init__(self):
        self.data: dict[str, bytes] = {}

    def write(self, key: str, data: bytes) -> None:
        self.data[key] = data

    def append(self, key: str, data: bytes) -> None:
        self.data[key] = self.data.get(key, b"") + data

    def read(self, key: str) -> bytes | None:
        return self.data.get(key)

    def list_keys(self, prefix: str) -> list[str]:
        return sorted(k for k in self.data if k.startswith(prefix))

    def delete(self, key: str) -> None:
        self.data.pop(key, None)


class Backend:
    """reference: persistence/__init__.py:13 — factory namespace."""

    def __init__(self, engine_backend: _BackendBase):
        self._backend = engine_backend

    @classmethod
    def filesystem(cls, path: str) -> "Backend":
        return cls(_FsBackend(path))

    @classmethod
    def memory(cls) -> "Backend":
        return cls(_MemoryBackend())

    @classmethod
    def mock(cls, events=None) -> "Backend":
        return cls(_MemoryBackend())

    @classmethod
    def gcs(cls, bucket: str, *, root_path: str = "", client=None) -> "Backend":
        """Google Cloud Storage backend (reference: backends/s3.rs — same
        object-store model). ``client`` overrides the google-cloud-storage
        Client (tests inject fakes/emulators)."""
        return cls(_ObjectStoreBackend(_GcsClient(bucket, client), root_path))

    @classmethod
    def object_store(cls, client, *, root_path: str = "") -> "Backend":
        """Persistence over any upload/download/list client (the transport
        behind gcs(); usable for S3/MinIO-compatible clients too)."""
        return cls(_ObjectStoreBackend(client, root_path))

    @classmethod
    def s3(cls, root_path: str, bucket_settings=None, *, _opener=None) -> "Backend":
        """S3/MinIO persistence backend (reference:
        persistence/backends/s3.rs:47) over the dependency-free SigV4
        client (io/_s3.py). ``bucket_settings`` is an AwsS3Settings;
        ``root_path`` may be ``s3://bucket/prefix`` or a bare prefix."""
        from pathway_tpu.io._s3 import AwsS3Settings, S3Client
        from pathway_tpu.io.s3 import _split_path

        bucket, prefix = _split_path(root_path)
        settings = (bucket_settings or AwsS3Settings()).with_bucket(bucket)
        client = _S3PersistClient(S3Client(settings, opener=_opener))
        return cls(_ObjectStoreBackend(client, prefix))


@dataclass
class Config:
    """reference: persistence/__init__.py:88."""

    backend: Backend
    snapshot_interval_ms: int = 0
    persistence_mode: str = "PERSISTING"

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend=backend, **kwargs)


class PersistenceManager:
    """Engine-side journal/restore driver wired into the Runtime."""

    def __init__(self, config: Config):
        self.backend = config.backend._backend
        self.mode = (config.persistence_mode or "PERSISTING").upper()
        self.snapshot_interval_ms = config.snapshot_interval_ms
        self.lock = threading.Lock()

    # -- journaling (write-ahead, called before the engine steps) ----------
    def journal_batch(
        self, conn_name: str, time: int, deltas: list, state: Any = None
    ) -> None:
        # crash here = rows accepted by the engine this run but never
        # journaled; restart rescans them from the last durable state
        _faults.fault_point("persistence.journal_write")
        # the subject scan state rides INSIDE the journal entry: one atomic
        # append, so the journaled prefix and the state that claims it can
        # never diverge across a crash (two separate writes could)
        payload = pickle.dumps((time, deltas, state))
        header = len(payload).to_bytes(8, "little")
        with self.lock:
            self.backend.append(f"journal/{conn_name}", header + payload)
        # crash here = journaled but control never returned to the engine
        # loop; restart replays the entry exactly once
        _faults.fault_point("persistence.journal_write.post")

    def save_subject_state(self, conn_name: str, state: Any) -> None:
        _faults.fault_point("persistence.checkpoint")
        with self.lock:
            self.backend.write(
                f"subject_state/{conn_name}", pickle.dumps(state)
            )

    # -- restore ------------------------------------------------------------
    def load_journal(self, conn_name: str) -> list[tuple[int, list, Any]]:
        raw = self.backend.read(f"journal/{conn_name}")
        if not raw:
            return []
        out = []
        pos = 0
        while pos + 8 <= len(raw):
            n = int.from_bytes(raw[pos : pos + 8], "little")
            pos += 8
            if pos + n > len(raw):
                break  # torn tail from a crash mid-append: drop it
            entry = _safe_loads(raw[pos : pos + n])
            if len(entry) == 2:  # pre-state journal format
                entry = (*entry, None)
            out.append(entry)
            pos += n
        return out

    def load_subject_state(self, conn_name: str) -> Any | None:
        raw = self.backend.read(f"subject_state/{conn_name}")
        return _safe_loads(raw) if raw else None

    # -- operator snapshots (reference: operator_snapshot.rs) --------------
    def save_operator_snapshot(
        self,
        node_states: list,
        subject_states: dict,
        fingerprint: list,
        *,
        key: str = "operator_snapshot",
    ) -> None:
        # crash here = this snapshot never became durable; restart resumes
        # from the previous consistent cut
        _faults.fault_point("persistence.checkpoint")
        with self.lock:
            self.backend.write(
                key,
                pickle.dumps((node_states, subject_states, fingerprint)),
            )

    def load_operator_snapshot(self, *, key: str = "operator_snapshot"):
        raw = self.backend.read(key)
        return pickle.loads(raw) if raw else None

    # -- multi-process consistent cut (reference: tracker.rs:47,160-193 —
    # per-worker persistent storage; a snapshot timestamp only advances
    # when every worker has durably written it) ---------------------------
    def write_marker(self, name: str, value: Any) -> None:
        """Tiny commit-marker record (e.g. the globally agreed snapshot
        tag). Written by rank 0 only AFTER every rank acked its rank-local
        snapshot, so the marker always names a complete consistent cut."""
        with self.lock:
            self.backend.write(f"marker/{name}", pickle.dumps(value))

    def read_marker(self, name: str) -> Any | None:
        raw = self.backend.read(f"marker/{name}")
        return _safe_loads(raw) if raw else None

    def delete_key(self, key: str) -> None:
        """Best-effort cleanup of superseded rank snapshots."""
        try:
            with self.lock:
                self.backend.delete(key)
        except (AttributeError, OSError):
            pass

    def list_keys(self, prefix: str) -> list[str]:
        return self.backend.list_keys(prefix)

    def prune_operator_snapshots(self, prefix: str, keep: set) -> None:
        """Best-effort prune of a rank's superseded snapshot tags,
        retaining every tag in ``keep``. The runtime passes the
        just-committed tag AND the previously committed one: a rank
        crashing between its restore-read of the marker and a peer's
        post-commit prune must still find the snapshot it is loading on
        the next rollback — deleting all-but-the-newest would race the
        restore (ISSUE 4 prune-race fix). Non-integer suffixes (foreign
        keys under the prefix) are left alone."""
        for key in self.list_keys(prefix):
            try:
                tag = int(key[len(prefix):].split("/")[0])
            except ValueError:
                continue
            if tag not in keep:
                self.delete_key(key)
