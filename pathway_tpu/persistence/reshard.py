"""Restore-side re-shard reader (ISSUE 11, elastic mesh).

A committed operator snapshot is a per-rank cut: rank *r* of an N-rank
mesh persists ``operator_snapshot/r{r}/{tag}`` holding exactly the
state entries whose keys the stable shard mint
(``parallel/procgroup.shard_hash`` → ``protocol.shard_owner``) assigns
to *r* at world N. Because the 64-bit blake2b digest is
world-INDEPENDENT, restoring that cut into a *different* world size M
is a pure re-bucketing: take the union of all N ranks' entries, keep on
new rank *m* exactly those with ``shard_owner(digest, M) == m``. The
kept sets form a partition of the union — no entry is lost, none is
duplicated — which is the exactly-once-across-rescale property
``python -m pathway_tpu.analysis --mesh --rescale`` model-checks (the
``drop_reshard_shard`` mutant breaks precisely the keep filter here)
and ``tests/test_rescale.py`` pins as a round-trip property for
N, M ∈ {1..4} in both directions.

Node-state semantics (``engine/nodes.py`` declares the policy per node
class via ``Node.RESHARD`` / ``Node.RESHARD_ATTRS``):

* ``"keyed"`` — state containers are keyed by the node's upstream
  exchange shard key (frozen grouping values, join keys, or row
  Pointers for id-routed exchanges): union + keep-filter. This is every
  stateful node fed through a hash exchange — the keys the containers
  are addressed by ARE the values ``stable_shard`` routed on.
* ``"union"`` — plain first-wins union, no filter: rank-local source
  state (pk-upsert memos, scan dedup) whose entries are inert on ranks
  that will not re-read their keys, and replicated static state.
* ``"replicate"`` — identical on every old rank (broadcast-fed sides):
  adopt old rank 0's copy verbatim.

Connector scan states: a source that reads on rank 0 only carries one
state — it passes through. A partition-aware source
(``_distributed_partitioned``) owns a key/path shard per rank and must
implement ``reshard_scan_state(states: list) -> state`` to merge the
old ranks' states for the new world (``io/fs.py`` ships one for the
path-sharded scanner); without the hook the rescale is REFUSED with an
error naming the connector — silently re-reading or dropping a shard's
scan position would break exactly-once.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

from pathway_tpu.engine.stream import MultisetState, TableState
from pathway_tpu.parallel import protocol as _proto
from pathway_tpu.parallel.procgroup import shard_hash


def keep_fn(rank: int, world: int) -> Callable[[Any], bool]:
    """The new-world keep filter over raw OR frozen key values: freezing
    is idempotent under the mint's canonical byte serialization, so
    ``keep(frozen_gvals) == keep(gvals)`` — one filter serves python
    stores (frozen keys) and native dumps (raw keys) alike. Drives the
    shared ``protocol.reshard_keep`` transition — the same function the
    rescale model checker explores."""
    return lambda value: _proto.reshard_keep(shard_hash(value), rank, world)


# -- generic container merge / filter ---------------------------------------

def merge_values(values: list):
    """First-wins union of one state attribute across the old ranks.
    Keyed containers of rank-partitioned state are key-disjoint by
    construction (each key lived on exactly one old rank) and
    replicated state is identical on every rank, so first-wins is
    either a true union or a no-op."""
    values = [v for v in values if v is not None]
    if not values:
        return None
    first = values[0]
    if isinstance(first, MultisetState):
        out = MultisetState()
        for v in values:
            for k, d in v.data.items():
                if k not in out.data:
                    out.data[k] = d
        return out
    if isinstance(first, TableState):
        out = TableState()
        for v in reversed(values):
            out.rows.update(v.rows)
        out.rows.update(first.rows)
        return out
    if isinstance(first, dict):
        out = {}
        for v in reversed(values):
            out.update(v)
        out.update(first)
        return out
    if isinstance(first, (set, frozenset)):
        out = set()
        for v in values:
            out |= v
        return out
    if isinstance(first, list):
        seen = set()
        out = []
        for v in values:
            for item in v:
                try:
                    marker = item if isinstance(item, (str, int, tuple)) \
                        else repr(item)
                except Exception:
                    marker = id(item)
                if marker not in seen:
                    seen.add(marker)
                    out.append(item)
        return out
    return first  # scalars: replicated or rank-equal


def filter_value(value, keep: Callable[[Any], bool]):
    """Keep-filter a keyed container by its keys; non-container values
    pass through (the merge already picked one copy)."""
    if isinstance(value, MultisetState):
        out = MultisetState()
        for k, d in value.data.items():
            if keep(k):
                out.data[k] = d
        return out
    if isinstance(value, TableState):
        out = TableState()
        out.rows = {k: r for k, r in value.rows.items() if keep(k)}
        return out
    if isinstance(value, dict):
        return {k: v for k, v in value.items() if keep(k)}
    if isinstance(value, (set, frozenset)):
        return type(value)(k for k in value if keep(k))
    return value


def reshard_node_state(
    node, states: list, rank: int, world: int
) -> dict | None:
    """One node's re-sharded state from the old ranks' state dicts.
    Dispatch order: a node-level ``reshard_state`` override (native
    store dumps need entry-level key access), then the class policy."""
    states = [s for s in states if s]
    if not states:
        return None
    keep = keep_fn(rank, world)
    override = getattr(node, "reshard_state", None)
    if override is not None:
        return override(states, keep)
    policy = getattr(node, "RESHARD", "keyed")
    per_attr = getattr(node, "RESHARD_ATTRS", None) or {}
    if policy == "refuse":
        if any(_state_nonempty(v) for s in states for v in s.values()):
            raise RuntimeError(
                f"rescale: node {type(node).__name__} holds rank-local "
                "state (release heaps / watermark stashes) whose "
                "placement cannot be re-derived from a key — this plan "
                "cannot rescale while that state is non-empty"
            )
        return None
    attrs = set()
    for s in states:
        attrs.update(s)
    if "__native__" in attrs:
        raise RuntimeError(
            f"rescale: node {type(node).__name__} persisted a native "
            "store dump but declares no reshard_state override — "
            "cannot re-bucket opaque entries"
        )
    out = {}
    for attr in attrs:
        pol = per_attr.get(attr, policy)
        values = [s.get(attr) for s in states]
        if pol == "replicate":
            merged = next((v for v in values if v is not None), None)
        else:
            merged = merge_values(values)
        if pol == "keyed" and merged is not None:
            merged = filter_value(merged, keep)
        out[attr] = merged
    return out


# -- whole-snapshot reader ---------------------------------------------------

EXCHANGE_NODE_NAME = "ExchangeNode"


def align_fingerprints(old_fp: list, new_fp: list) -> list:
    """new-node-index -> old-node-index (or None) across a world-size
    change. Exchange boundaries exist only in multi-rank lowerings
    (``Scope._exchange`` returns the input table at world 1) and are
    stateless, so a cut crossing the world==1 boundary aligns the
    remaining nodes by order and name; any other shape difference is a
    real program change and refuses."""
    old = [(i, n) for i, n in enumerate(old_fp) if n != EXCHANGE_NODE_NAME]
    new = [(i, n) for i, n in enumerate(new_fp) if n != EXCHANGE_NODE_NAME]
    if [n for _, n in old] != [n for _, n in new]:
        raise RuntimeError(
            "operator snapshot does not match this pipeline's graph "
            "shape across the rescale — the program changed since the "
            "cut was taken"
        )
    mapping: list = [None] * len(new_fp)
    for (oi, _), (ni, _) in zip(old, new):
        mapping[ni] = oi
    return mapping

def load_world_snapshots(
    persistence, tag: int, old_world: int, key_prefix: str = "operator_snapshot"
) -> list:
    """Every old rank's ``(node_states, subject_states, fingerprint)``
    at the committed tag — all-or-nothing: a missing rank snapshot
    under a marker that names the tag is a broken two-phase cut and
    raises (the caller's gather/bcast turns that into a clean abort)."""
    snaps = []
    for r in range(old_world):
        snap = persistence.load_operator_snapshot(
            key=f"{key_prefix}/r{r}/{tag}"
        )
        if snap is None:
            raise RuntimeError(
                f"rescale restore: commit marker names tag {tag} at world "
                f"{old_world} but rank {r}'s snapshot is missing — the "
                "two-phase cut is broken"
            )
        snaps.append(snap)
    return snaps


def reshard_subject_states(
    conn_names: Iterable[str],
    snaps: list,
    subjects: dict,
) -> dict:
    """Per-connector scan state for the new rank, from the union of the
    old ranks' subject states. A subject carrying a
    ``reshard_scan_state`` hook ALWAYS re-merges through it — even a
    single old state must be re-filtered for the new world (a 1→N grow
    hands every new rank the full old coverage otherwise, and a
    path-sharded scanner would then retract its peers' files as
    deleted). Without the hook, one claiming rank (non-partitioned
    sources read on rank 0 only) passes through; several claiming ranks
    refuse — refusing beats silently replaying or dropping a shard's
    scan position."""
    out = {}
    for name in conn_names:
        states = [
            snap[1][name] for snap in snaps
            if isinstance(snap[1], dict) and snap[1].get(name) is not None
        ]
        if not states:
            continue
        subject = subjects.get(name)
        hook = getattr(subject, "reshard_scan_state", None)
        if hook is not None:
            out[name] = hook(states)
            continue
        if len(states) == 1:
            out[name] = states[0]
            continue
        raise RuntimeError(
            f"rescale restore: connector {name!r} has scan state on "
            f"{len(states)} old ranks but its subject implements no "
            "reshard_scan_state(states) hook — cannot re-partition "
            "its scan position across a world-size change"
        )
    return out


def partition_roundtrip(keys: Iterable, n: int, m: int) -> bool:
    """Test helper for the pinned property: re-bucketing a committed
    store's keys from N to M shards is a partition (every key in
    exactly one new shard) and N→M→N round-trips bit-identical."""
    srt = lambda ks: sorted(ks, key=repr)  # noqa: E731 - mixed key types
    by_n = {r: srt(k for k in keys if _owner(k, n) == r)
            for r in range(n)}
    union = [k for r in range(n) for k in by_n[r]]
    by_m = {}
    for r in range(m):
        keep = keep_fn(r, m)
        by_m[r] = srt(k for k in union if keep(k))
    flat = [k for r in range(m) for k in by_m[r]]
    if srt(flat) != srt(union):
        return False  # lost or duplicated under N→M
    back = {}
    union_m = [k for r in range(m) for k in by_m[r]]
    for r in range(n):
        keep = keep_fn(r, n)
        back[r] = srt(k for k in union_m if keep(k))
    return back == by_n


def _owner(value, world: int) -> int:
    return _proto.shard_owner(shard_hash(value), world)


def _state_nonempty(value) -> bool:
    """Does a persisted state value hold anything a re-shard could
    misplace? Scalars (watermarks) merge harmlessly; containers count."""
    if value is None:
        return False
    if isinstance(value, (MultisetState,)):
        return bool(value.data)
    if isinstance(value, TableState):
        return bool(value.rows)
    if isinstance(value, (dict, set, frozenset, list, tuple)):
        return len(value) > 0
    return False
