"""Epoch-aligned incremental index snapshots (ISSUE 17, device fault
domain).

The mesh plane commits consistent cuts through a two-phase marker
(``persistence/__init__.py write_marker``); device-resident index state
(``ops/knn.KnnShard``, ``parallel/sharded_knn.ShardedKnnIndex``) rides
the SAME cut as *delta segments*: at each snapshot the index transfers
only the HBM rows touched since the last cut (device->host gather of the
dirty slots), writes them as one durable segment object, and returns a
tiny *manifest* (the segment chain) as its node state. The manifest is
what the runtime pickles into ``operator_snapshot/r{rank}/{tag}`` — it
becomes visible exactly when the marker moves, so a crash between
segment write and marker leaves only an orphan object the next cut at
the same tag atomically overwrites. Restore folds the committed chain
back into HBM instead of re-embedding the corpus (the ≥10x bar the
device chaos smoke pins), and an N→M re-shard re-buckets folded entries
through the same ``shard_hash``/``shard_owner`` mint the exchange plane
uses.

Cut/restore decisions are pure transitions in ``parallel/protocol.py``
(``index_cut_decide``, ``index_restore_verdict``) — identity-pinned by
tests so no second copy of the policy exists to drift:

* quiet epoch (nothing dirty) -> ``skip``: the manifest re-lists the
  existing chain, O(1) metadata, no device traffic (pinned by the
  quiet-epoch test);
* chain longer than ``PATHWAY_INDEX_SNAPSHOT_SEGMENTS`` -> ``fold``:
  one full base segment replaces the chain (the ``TxnDeltaSink``
  folded-manifest compaction pattern), superseded segments retire and
  are pruned with two-cut retention (the ISSUE 4 prune-race rule);
* otherwise -> ``delta``.

Segment objects live under ``index_segment/{name}/r{rank}/{tag}`` —
outside the runtime's ``operator_snapshot/`` prefix, so its tag pruning
never touches them; pruning the chain is this module's job.

The runtime arms a cut context (:func:`cut`) around every node
``state_dict``/``load_state`` pass; indexes opt in by calling
:func:`snapshot_index`/:func:`restore_index`. With no context armed (or
``PATHWAY_DEVICE_SNAPSHOT=0``) the index falls back to an inline full
state — the pre-ISSUE-17 behavior, still correct, just O(corpus).
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import faults as _faults
from pathway_tpu.parallel import protocol as _proto

# how many delta segments may chain before a cut folds them into one
# base segment (PATHWAY_INDEX_SNAPSHOT_SEGMENTS; <=0 disables folding)
_DEFAULT_MAX_SEGMENTS = 8

_SEGMENT_PREFIX = "index_segment"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _segments_enabled() -> bool:
    raw = str(os.environ.get("PATHWAY_DEVICE_SNAPSHOT", "1")).strip().lower()
    return raw not in ("0", "false", "no")


# -- cut context -------------------------------------------------------------

@dataclass
class CutContext:
    """One snapshot/restore pass: where segments go and under which tag.
    Armed by the runtime around node state_dict/load_state (every save
    and restore path shares this), read by the indexes — the Node API
    itself stays unchanged."""

    persistence: Any
    tag: int
    rank: int = 0
    world: int = 1
    stats: Any = None  # ProberStats for the index_* counters, or None


_LOCAL = threading.local()


def current() -> CutContext | None:
    return getattr(_LOCAL, "ctx", None)


class cut:
    """Context manager arming a :class:`CutContext` for the current
    thread. Re-entrant arming replaces (save paths never nest)."""

    def __init__(self, persistence, tag: int, rank: int = 0,
                 world: int = 1, stats: Any = None):
        self._ctx = CutContext(persistence, int(tag), int(rank),
                               int(world), stats)

    def __enter__(self) -> CutContext:
        self._prev = getattr(_LOCAL, "ctx", None)
        _LOCAL.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc) -> None:
        _LOCAL.ctx = self._prev


# -- index-name mint ---------------------------------------------------------

_NAME_LOCK = threading.Lock()
_NAME_COUNTS: dict[str, int] = {}


def next_index_name(prefix: str = "knn") -> str:
    """Deterministic per-process mint: graph construction order is
    deterministic for a given program, so a restarted run's indexes get
    the same names (their segment keys must line up across restarts)."""
    with _NAME_LOCK:
        n = _NAME_COUNTS.get(prefix, 0)
        _NAME_COUNTS[prefix] = n + 1
    return f"{prefix}{n}"


def reset_name_mint() -> None:
    """Test/driver hook: a fresh GraphRunner run re-mints from zero."""
    with _NAME_LOCK:
        _NAME_COUNTS.clear()


# -- segment store -----------------------------------------------------------

def segment_key(name: str, rank: int, tag: int) -> str:
    return f"{_SEGMENT_PREFIX}/{name}/r{rank}/{tag}"


def _write_segment(ctx: CutContext, name: str, payload: dict) -> tuple[str, int]:
    key = segment_key(name, ctx.rank, ctx.tag)
    data = pickle.dumps(payload)
    with ctx.persistence.lock:
        ctx.persistence.backend.write(key, data)
    return key, len(data)


def _read_segment(persistence, key: str) -> dict | None:
    raw = persistence.backend.read(key)
    return pickle.loads(raw) if raw else None


# -- snapshot ---------------------------------------------------------------

def _gather_rows(index, keys: list) -> np.ndarray:
    """Device->host transfer of ONLY the named keys' HBM rows (the
    whole point of delta segments: per-cut traffic scales with the
    epoch's dirty set, not corpus size)."""
    if not keys:
        return np.zeros((0, index.dimension), np.float32)
    import jax.numpy as jnp  # deferred: module stays importable sans jax

    slots = np.asarray([index.key_to_slot[k] for k in keys], np.int32)
    return np.asarray(index.vectors[jnp.asarray(slots)], dtype=np.float32)


def _entries_payload(index, keys: list, extra) -> dict:
    return {
        "keys": list(keys),
        "seqs": np.asarray([index.key_seq[k] for k in keys], np.int64),
        "vectors": _gather_rows(index, keys),
        "extra": (
            {k: extra[k] for k in keys if k in extra}
            if extra is not None else None
        ),
    }


def snapshot_index(index, *, extra=None) -> dict:
    """Emit the index's node state for the current cut.

    ``extra`` is an optional key->payload mapping that rides the
    segments (the KNN adapter's per-key filter metadata) so no separate
    O(corpus) dict is pickled per cut. Caller must NOT hold
    ``index.lock`` — taken here.
    """
    with index.lock:
        _faults.fault_point("device.snapshot", phase="cut")
        ctx = current()
        if ctx is None or not _segments_enabled():
            # no persistence cut armed (direct state_dict calls, tests,
            # in-memory snapshots): inline full state, pre-ISSUE-17 shape
            live = sorted(index.key_to_slot, key=lambda k: index.key_seq[k])
            state = _entries_payload(index, live, extra)
            state["__index_inline__"] = True
            state["next_seq"] = index._next_seq
            state["metric"] = index.metric.value
            state["dimension"] = index.dimension
            return state

        dirty_live = [k for k in index._dirty if k in index.key_to_slot]
        removed = list(index._dirty_removed)
        max_segments = _env_int(
            "PATHWAY_INDEX_SNAPSHOT_SEGMENTS", _DEFAULT_MAX_SEGMENTS
        )
        verdict = _proto.index_cut_decide(
            len(dirty_live) + len(removed), len(index._segments), max_segments
        )
        if verdict != "skip":
            if verdict == "fold":
                # compact: one base segment holding the full live corpus
                # replaces the chain; the replaced keys retire and are
                # pruned two cuts later (a crashed peer restoring the
                # PREVIOUS marker must still find its chain)
                keys = sorted(
                    index.key_to_slot, key=lambda k: index.key_seq[k]
                )
                payload = _entries_payload(index, keys, extra)
                payload["removes"] = []
                retired = [s["key"] for s in index._segments]
                index._segments = []
            else:
                dirty_live.sort(key=lambda k: index.key_seq[k])
                payload = _entries_payload(index, dirty_live, extra)
                payload["removes"] = removed
                retired = []
            key, nbytes = _write_segment(ctx, index.snapshot_name, payload)
            _faults.fault_point("device.snapshot", phase="post_segment")
            index._segments = index._segments + [{
                "key": key,
                "tag": ctx.tag,
                "rows": len(payload["keys"]),
                "removes": len(payload["removes"]),
                "bytes": nbytes,
            }]
            if retired:
                index._retired.append(retired)
            index._dirty.clear()
            index._dirty_removed.clear()
            if ctx.stats is not None:
                ctx.stats.on_index_snapshot_bytes(nbytes)
        # two-cut retention before deleting retired segments: the
        # previous marker may still name a manifest referencing them
        while len(index._retired) > 2:
            for key in index._retired.pop(0):
                ctx.persistence.delete_key(key)
        return {
            "__index_segments__": True,
            "name": index.snapshot_name,
            "dimension": index.dimension,
            "metric": index.metric.value,
            "count": len(index.key_to_slot),
            "next_seq": index._next_seq,
            "segments": list(index._segments),
            "retired": [list(r) for r in index._retired],
        }


# -- restore ----------------------------------------------------------------

def _fold_segments(persistence, manifest: dict) -> tuple[dict, int]:
    """Replay the committed chain into key -> (seq, row, extra_payload).
    Raises on a broken chain — the ``index_restore_verdict`` transition
    says ``refuse``: silently serving an index with holes would violate
    the zero-lost-entries bar the chaos grid pins."""
    segments = manifest.get("segments", ())
    missing = 0
    payloads = []
    for seg in segments:
        payload = _read_segment(persistence, seg["key"])
        if payload is None:
            missing += 1
        payloads.append(payload)
    verdict = _proto.index_restore_verdict(True, missing)
    if verdict == "refuse":
        raise RuntimeError(
            f"index restore: manifest {manifest.get('name')!r} names "
            f"{len(segments)} segment(s) but {missing} are missing from "
            "the persistence store — refusing to serve a partial index"
        )
    acc: dict[Any, tuple] = {}
    for payload in payloads:
        for k in payload.get("removes", ()):
            acc.pop(k, None)
        vecs = payload["vectors"]
        extra = payload.get("extra") or {}
        for i, k in enumerate(payload["keys"]):
            acc[k] = (int(payload["seqs"][i]), vecs[i], extra.get(k))
    return acc, int(manifest.get("next_seq", 0))


def _resolve_state(state: dict, persistence) -> tuple[dict, int, list, bool]:
    """Any accepted state shape -> (entries, next_seq, segment_chain,
    rebased). ``rebased`` means the restored corpus is NOT backed by a
    chain this rank can extend (inline or resharded state): the index
    must mark everything dirty so its next cut writes a fresh base."""
    if state.get("__index_reshard__"):
        keep = state["keep"]
        merged: dict[Any, tuple] = {}
        next_seq = 0
        for part in state["parts"]:
            entries, ns, _, _ = _resolve_state(part, persistence)
            next_seq = max(next_seq, ns)
            for k, v in entries.items():
                if keep is None or keep(k):
                    merged[k] = v
        return merged, next_seq, [], True
    if state.get("__index_segments__"):
        if persistence is None and state.get("segments"):
            raise RuntimeError(
                "index restore: state is a segment manifest but no "
                "persistence cut is armed — cannot read the chain"
            )
        entries, next_seq = _fold_segments(persistence, state)
        return entries, next_seq, list(state.get("segments", ())), False
    # inline full state (__index_inline__ or the legacy adapter shape)
    entries = {}
    vecs = state["vectors"]
    extra = state.get("extra") or {}
    seqs = state.get("seqs")
    for i, k in enumerate(state["keys"]):
        seq = int(seqs[i]) if seqs is not None else i
        entries[k] = (seq, np.asarray(vecs[i], np.float32), extra.get(k))
    return entries, int(state.get("next_seq", len(entries))), [], True


def restore_index(index, state: dict) -> dict:
    """Rebuild the index's HBM shards from a committed state; returns
    the folded per-key extra payloads (the adapter's metadata). Restored
    rows are re-written with ``normalize=False`` — segments carry the
    rows exactly as stored, so scores (and the ``key_seq`` tie-break)
    come back bit-identical to the uninterrupted run."""
    ctx = current()
    _faults.fault_point("device.restore", phase="restore")
    t0 = time.perf_counter()
    entries, next_seq, chain, rebased = _resolve_state(
        state, ctx.persistence if ctx is not None else None
    )
    dim = state.get("dimension")
    if dim is not None and int(dim) != index.dimension:
        raise RuntimeError(
            f"index restore: snapshot dimension {dim} != index "
            f"dimension {index.dimension}"
        )
    ordered = sorted(entries.items(), key=lambda kv: kv[1][0])
    with index.lock:
        index._load_entries(
            [(k, seq, row) for k, (seq, row, _x) in ordered]
        )
        index._next_seq = max(next_seq, index._next_seq)
        index._segments = chain
        index._retired = []
        if rebased:
            # not backed by an extendable chain: next cut writes a base
            index._dirty = dict.fromkeys(index.key_to_slot)
        else:
            index._dirty.clear()
        index._dirty_removed.clear()
    if ctx is not None and ctx.stats is not None:
        ctx.stats.on_index_restore_seconds(time.perf_counter() - t0)
    return {k: x for k, (_s, _r, x) in ordered if x is not None}
