"""pw.sql — SQL to Table-DSL translation (reference:
python/pathway/internals/sql.py:726 — sqlglot-based; no sqlglot here, so a
hand-rolled parser covers the dialect the reference documents: SELECT
projections/expressions, FROM with aliases, INNER JOIN ... ON, WHERE,
GROUP BY + aggregates (COUNT/SUM/MIN/MAX/AVG), HAVING, UNION ALL)."""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import coalesce, if_else

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<num>\d+(?:\.\d+)?)"
    r"|(?P<str>'[^']*')"
    r"|(?P<op><=|>=|<>|!=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.)"
    r"|(?P<ident>[A-Za-z_][A-Za-z0-9_]*))",
    re.S,
)

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "as", "join",
    "inner", "left", "right", "outer", "on", "and", "or", "not", "union",
    "all", "distinct", "null", "true", "false", "like",
}

_AGGREGATES = {"count", "sum", "min", "max", "avg"}


def _tokenize(sql: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            rest = sql[pos:].strip()
            if not rest:
                break
            raise ValueError(f"SQL syntax error near {rest[:30]!r}")
        pos = m.end()
        for kind in ("num", "str", "op", "ident"):
            tok = m.group(kind)
            if tok is not None:
                if kind == "ident" and tok.lower() in _KEYWORDS:
                    out.append(("kw", tok.lower()))
                else:
                    out.append((kind, tok))
                break
    return out


class _Parser:
    def __init__(self, tokens):
        self.toks = tokens
        self.pos = 0

    def peek(self, offset=0):
        i = self.pos + offset
        return self.toks[i] if i < len(self.toks) else (None, None)

    def next(self):
        tok = self.peek()
        self.pos += 1
        return tok

    def accept(self, kind, value=None):
        k, v = self.peek()
        if k == kind and (value is None or v == value):
            self.pos += 1
            return v
        return None

    def expect(self, kind, value=None):
        got = self.accept(kind, value)
        if got is None:
            raise ValueError(
                f"SQL: expected {value or kind}, got {self.peek()!r}"
            )
        return got

    # -- grammar ----------------------------------------------------------
    def parse_query(self):
        q = self.parse_select()
        while self.accept("kw", "union"):
            self.expect("kw", "all")
            rhs = self.parse_select()
            q = ("union_all", q, rhs)
        return q

    def parse_select(self):
        self.expect("kw", "select")
        distinct = bool(self.accept("kw", "distinct"))
        projections = [self.parse_projection()]
        while self.accept("op", ","):
            projections.append(self.parse_projection())
        self.expect("kw", "from")
        table = self.parse_table_ref()
        joins = []
        while True:
            how = "inner"
            if self.accept("kw", "inner"):
                self.expect("kw", "join")
            elif self.accept("kw", "left"):
                self.accept("kw", "outer")
                self.expect("kw", "join")
                how = "left"
            elif self.accept("kw", "right"):
                self.accept("kw", "outer")
                self.expect("kw", "join")
                how = "right"
            elif self.accept("kw", "join"):
                pass
            else:
                break
            other = self.parse_table_ref()
            self.expect("kw", "on")
            cond = self.parse_expr()
            joins.append((how, other, cond))
        where = None
        if self.accept("kw", "where"):
            where = self.parse_expr()
        group_by = []
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by.append(self.parse_expr())
            while self.accept("op", ","):
                group_by.append(self.parse_expr())
        having = None
        if self.accept("kw", "having"):
            having = self.parse_expr()
        return (
            "select", projections, table, joins, where, group_by, having,
            distinct,
        )

    def parse_projection(self):
        if self.peek() == ("op", "*"):
            self.next()
            return ("star", None)
        e = self.parse_expr()
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("ident")
        elif self.peek()[0] == "ident":
            alias = self.next()[1]
        return ("expr", e, alias)

    def parse_table_ref(self):
        name = self.expect("ident")
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("ident")
        elif self.peek()[0] == "ident":
            alias = self.next()[1]
        return (name, alias)

    def parse_expr(self):
        return self.parse_or()

    def parse_or(self):
        e = self.parse_and()
        while self.accept("kw", "or"):
            e = ("or", e, self.parse_and())
        return e

    def parse_and(self):
        e = self.parse_not()
        while self.accept("kw", "and"):
            e = ("and", e, self.parse_not())
        return e

    def parse_not(self):
        if self.accept("kw", "not"):
            return ("not", self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self):
        e = self.parse_add()
        k, v = self.peek()
        if k == "op" and v in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            return ("cmp", v, e, self.parse_add())
        return e

    def parse_add(self):
        e = self.parse_mul()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("+", "-"):
                self.next()
                e = ("arith", v, e, self.parse_mul())
            else:
                return e

    def parse_mul(self):
        e = self.parse_atom()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("*", "/", "%"):
                self.next()
                e = ("arith", v, e, self.parse_atom())
            else:
                return e

    def parse_atom(self):
        k, v = self.peek()
        if k == "op" and v == "(":
            self.next()
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        if k == "num":
            self.next()
            return ("const", float(v) if "." in v else int(v))
        if k == "str":
            self.next()
            return ("const", v[1:-1])
        if k == "kw" and v in ("null", "true", "false"):
            self.next()
            return ("const", {"null": None, "true": True, "false": False}[v])
        if k == "ident":
            name = self.next()[1]
            if self.peek() == ("op", "("):  # function call
                self.next()
                if name.lower() == "count" and self.peek() == ("op", "*"):
                    self.next()
                    self.expect("op", ")")
                    return ("agg", "count", None)
                args = []
                if self.peek() != ("op", ")"):
                    args.append(self.parse_expr())
                    while self.accept("op", ","):
                        args.append(self.parse_expr())
                self.expect("op", ")")
                if name.lower() in _AGGREGATES:
                    return ("agg", name.lower(), args[0] if args else None)
                return ("fn", name.lower(), args)
            if self.peek() == ("op", "."):
                self.next()
                col = self.expect("ident")
                return ("col", name, col)
            return ("col", None, name)
        raise ValueError(f"SQL: unexpected token {self.peek()!r}")


def _has_agg(node) -> bool:
    if not isinstance(node, tuple):
        return False
    if node[0] == "agg":
        return True
    return any(_has_agg(c) for c in node[1:] if isinstance(c, tuple))


class _Translator:
    def __init__(self, tables: dict[str, Any]):
        self.tables = tables

    def run(self, node):
        kind = node[0]
        if kind == "union_all":
            import pathway_tpu as pw

            return pw.Table.concat_reindex(self.run(node[1]), self.run(node[2]))
        return self.select(node)

    def _resolve_col(self, scope, tab, col):
        if tab is not None:
            table = scope.get(tab)
            if table is None:
                raise KeyError(f"SQL: unknown table alias {tab!r}")
            return table[col]
        for table in scope.values():
            if col in table.column_names():
                return table[col]
        raise KeyError(f"SQL: unknown column {col!r}")

    def to_expr(self, node, scope, agg_ctx=None):
        kind = node[0]
        if kind == "const":
            return expr_mod.smart_coerce(node[1])
        if kind == "col":
            return self._resolve_col(scope, node[1], node[2])
        if kind == "cmp":
            _, sym, l, r = node
            le = self.to_expr(l, scope, agg_ctx)
            re_ = self.to_expr(r, scope, agg_ctx)
            if sym == "=":
                return le == re_
            if sym in ("<>", "!="):
                return le != re_
            return {"<": le < re_, "<=": le <= re_, ">": le > re_, ">=": le >= re_}[sym]
        if kind == "arith":
            _, sym, l, r = node
            le = self.to_expr(l, scope, agg_ctx)
            re_ = self.to_expr(r, scope, agg_ctx)
            return {
                "+": le + re_, "-": le - re_, "*": le * re_,
                "/": le / re_, "%": le % re_,
            }[sym]
        if kind == "and":
            return self.to_expr(node[1], scope, agg_ctx) & self.to_expr(node[2], scope, agg_ctx)
        if kind == "or":
            return self.to_expr(node[1], scope, agg_ctx) | self.to_expr(node[2], scope, agg_ctx)
        if kind == "not":
            return ~self.to_expr(node[1], scope, agg_ctx)
        if kind == "agg":
            from pathway_tpu.internals import reducers

            _, name, arg = node
            if name == "count":
                return reducers.count()
            arg_e = self.to_expr(arg, scope)
            return {
                "sum": reducers.sum, "min": reducers.min,
                "max": reducers.max, "avg": reducers.avg,
            }[name](arg_e)
        if kind == "fn":
            _, name, args = node
            exprs = [self.to_expr(a, scope, agg_ctx) for a in args]
            if name == "coalesce":
                return coalesce(*exprs)
            if name == "abs":
                return if_else(exprs[0] < 0, -exprs[0], exprs[0])
            raise ValueError(f"SQL: unsupported function {name!r}")
        raise ValueError(f"SQL: cannot translate {node!r}")

    def select(self, node):
        (_, projections, (tname, talias), joins, where, group_by, having,
         distinct) = node
        base = self.tables[tname]
        scope = {tname: base}
        if talias:
            scope[talias] = base
        current = base
        for how, (oname, oalias), cond in joins:
            other = self.tables[oname]
            scope[oname] = other
            if oalias:
                scope[oalias] = other
            cond_e = self.to_expr(cond, scope)
            joined = current.join(other, cond_e, how=how)
            # materialize join as a table carrying all columns of both sides
            cols = {}
            for t in (current, other):
                for c in t.column_names():
                    if c not in cols:
                        cols[c] = t[c]
            current = joined.select(**cols)
            # aliases now refer to the materialized join where possible
            scope = {k: current for k in scope}
            scope["__current__"] = current
        scope_final = {"__current__": current, **{
            k: (current if set(v.column_names()) <= set(current.column_names()) else v)
            for k, v in scope.items() if k != "__current__"
        }}

        if where is not None:
            current = current.filter(self.to_expr(where, scope_final))
            scope_final = {k: current for k in scope_final}

        has_aggs = group_by or any(
            _has_agg(p[1]) for p in projections if p[0] == "expr"
        )
        if has_aggs:
            group_exprs = [self.to_expr(g, scope_final) for g in group_by]
            grouped = current.groupby(*group_exprs)
            out_cols = {}
            for i, p in enumerate(projections):
                if p[0] == "star":
                    raise ValueError("SQL: SELECT * not allowed with GROUP BY")
                _, e, alias = p
                name = alias or _default_name(e, i)
                out_cols[name] = self.to_expr(e, scope_final)
            if having is not None:
                out_cols["_pw_having"] = self.to_expr(having, scope_final)
            result = grouped.reduce(**out_cols)
            if having is not None:
                result = result.filter(result["_pw_having"]).without("_pw_having")
            return result

        out_cols = {}
        for i, p in enumerate(projections):
            if p[0] == "star":
                for c in current.column_names():
                    out_cols[c] = current[c]
                continue
            _, e, alias = p
            name = alias or _default_name(e, i)
            out_cols[name] = self.to_expr(e, scope_final)
        result = current.select(**out_cols)
        if distinct:
            cols = result.column_names()
            result = result.groupby(*[result[c] for c in cols]).reduce(
                *[result[c] for c in cols]
            )
        return result


def _default_name(e, i: int) -> str:
    if isinstance(e, tuple) and e[0] == "col":
        return e[2]
    if isinstance(e, tuple) and e[0] == "agg":
        return e[1]
    return f"col_{i}"


def sql(query: str, **tables) -> Any:
    """Translate a SQL query over the given tables (reference: pw.sql,
    internals/sql.py)."""
    ast = _Parser(_tokenize(query)).parse_query()
    return _Translator(tables).run(ast)
