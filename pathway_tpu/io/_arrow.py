"""Columnar egress — NativeBatch → Arrow record batches (ISSUE 14).

The engine's fused chain keeps batches as C-owned typed column buffers
(``pwexec.NativeBatch``) all the way to the egress nodes; this module is
the boundary where those buffers become *Arrow record batches* through
the Arrow C data interface (``exec.cpp nb_export_arrow`` — GIL-free
assembly, buffer donation, one ``pa.RecordBatch._import_from_c`` on this
side), so sinks and ``on_batch`` subscribers consume columns without the
engine ever expanding rows into Python objects.

Two builders, one contract:

* :func:`nb_to_arrow` — the zero-copy path for NativeBatches. Returns
  ``None`` when a column mixes value tags (only reachable through
  untyped object sources); the caller falls back to the row path and the
  ``capture_rows_expanded_total`` counter makes the degradation visible.
* :func:`deltas_to_arrow` — the graceful fallback for tuple-delta
  batches (retractions, object columns, no toolchain): builds the batch
  column-wise in Python; cells outside the Arrow scalar set are PICKLED
  into a binary column tagged with ``pw_pickled`` field metadata (see
  :func:`unpickle_columns`), so an Arrow-mode subscriber still receives
  *every* delivery as a record batch.

Shared schema shape: the table's value columns (nullable), then a
``diff`` int64 column (±1; NativeBatches are insert-only net form, so
the zero-copy path emits a constant +1), and optionally a ``_key``
fixed_size_binary(16) column carrying the engine's 128-bit row keys
little-endian (``key_to_bytes``/``key_from_bytes`` round-trip them to
``Pointer``).
"""

from __future__ import annotations

import pickle
from typing import Any, Iterable

_PICKLED_META = b"pw_pickled"

_pa_cached: Any = False


def get_pyarrow():
    """pyarrow, or None when not importable (cached; the egress then
    stays on the row path — a missing wheel must degrade, not crash)."""
    global _pa_cached
    if _pa_cached is False:
        try:
            import pyarrow as pa

            _pa_cached = pa
        except Exception:
            _pa_cached = None
    return _pa_cached


def arrow_capable() -> bool:
    """Can this process export columnar egress batches at all?
    (pyarrow + the native toolchain + the knob not forcing rows)."""
    from pathway_tpu.analysis.eligibility import nb_capture_forced_off

    if nb_capture_forced_off() or get_pyarrow() is None:
        return False
    return _pwexec() is not None


def _pwexec():
    try:
        from pathway_tpu.native import get_pwexec

        ex = get_pwexec()
    except Exception:
        return None
    if ex is None or not hasattr(ex, "nb_export_arrow"):
        return None
    return ex


def key_to_bytes(key: Any) -> bytes:
    """128-bit row key → the 16 little-endian bytes the C export emits
    for ``_key`` (shared by the row-path builder so rows-vs-arrow parity
    holds bit-identically on the key column too)."""
    return (int(key) & ((1 << 128) - 1)).to_bytes(16, "little")


def key_from_bytes(raw: bytes) -> int:
    return int.from_bytes(raw, "little")


def nb_to_arrow(
    nb, cols: Iterable[str], *, include_key: bool = False,
    include_diff: bool = True,
):
    """Zero-copy export of one NativeBatch as a ``pa.RecordBatch``.
    ``None`` = not exportable this batch (mixed-tag column / toolchain
    or pyarrow missing) — the caller falls back to the row path."""
    pa = get_pyarrow()
    ex = _pwexec()
    if pa is None or ex is None:
        return None
    out = ex.nb_export_arrow(
        nb, tuple(cols), bool(include_key), bool(include_diff)
    )
    if out is None:
        return None
    s_addr, a_addr = out
    try:
        return pa.RecordBatch._import_from_c(a_addr, s_addr)
    finally:
        # the import MOVES the shell contents and marks them released;
        # arrow_shells_free returns the malloc'd shells (and releases
        # the donation if the import never ran)
        ex.arrow_shells_free(s_addr, a_addr)


_ARROW_SCALARS = (bool, int, float, str)


def deltas_to_arrow(
    deltas, cols, *, include_key: bool = False, pickle_objects: bool = True,
):
    """Row-fallback builder: tuple deltas ``[(key, row, diff), ...]`` →
    one record batch, column-wise. Cells outside the Arrow scalar set
    (Json, tuples, ndarrays, >64-bit ints) pickle into a binary column
    with ``pw_pickled`` field metadata when ``pickle_objects`` — sinks
    that must serialize *values* (csv/parquet) pass ``False`` and take
    ``None`` as their row-path verdict instead.

    Hot-path discipline: this runs per delivered batch on egress nodes
    whose input chain is NOT columnar (e.g. groupby output), so the
    per-row work is kept to one slice comprehension per column plus
    C-speed bulk ops — ``set(map(type, ...))`` for the type scan, one
    typed ``pa.array`` per column — never a per-cell Python type check
    unless the column actually pickles. (NOT ``zip(*rows)``: splatting
    a 395k-row batch into a call is slower than the comprehensions.)"""
    pa = get_pyarrow()
    if pa is None:
        return None
    cols = list(cols)
    col_vals = [[row[j] for _k, row, _d in deltas] for j in range(len(cols))]
    arrays = []
    fields = []
    for name, vals in zip(cols, col_vals):
        arr, field = _build_column(pa, name, vals, pickle_objects)
        if arr is None:
            return None
        arrays.append(arr)
        fields.append(field)
    # column order mirrors the C export: value columns, _key, diff
    if include_key:
        keys = list(map(key_to_bytes, (k for k, _row, _d in deltas)))
        arrays.append(pa.array(keys, pa.binary(16)))
        fields.append(pa.field("_key", pa.binary(16), nullable=False))
    diffs = [d for _k, _row, d in deltas]
    arrays.append(pa.array(diffs, pa.int64()))
    fields.append(pa.field("diff", pa.int64(), nullable=False))
    return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))


def _build_column(pa, name, vals, pickle_objects):
    """(array, field) for one column; (None, None) = not representable
    without pickling and the caller vetoed it. Typing mirrors the C
    export exactly: one EXACT scalar type per column plus nulls (bool is
    final; Pointer/IntEnum/tagged-str subclasses must keep identity →
    pickle; a mixed int/float column would silently promote under
    pa.array inference, diverging from the zero-copy path, so it routes
    to pickle too) — same policy as exec.cpp nb_put."""
    types = set(map(type, vals))
    types.discard(type(None))
    if not types:
        typ = pa.null()
        return pa.array(vals, typ), pa.field(name, typ)
    if len(types) == 1:
        t = next(iter(types))
        if t in _ARROW_TYPE_MAP:
            typ = _ARROW_TYPE_MAP[t](pa)
            try:
                return pa.array(vals, typ), pa.field(name, typ)
            except (OverflowError, pa.lib.ArrowInvalid):
                pass  # >64-bit ints and friends: pickle below
    if not pickle_objects:
        return None, None
    blobs = [
        None if v is None else pickle.dumps(v, protocol=4) for v in vals
    ]
    return (
        pa.array(blobs, pa.binary()),
        pa.field(name, pa.binary(), metadata={_PICKLED_META: b"1"}),
    )


_ARROW_TYPE_MAP = {
    bool: lambda pa: pa.bool_(),
    int: lambda pa: pa.int64(),
    float: lambda pa: pa.float64(),
    str: lambda pa: pa.string(),
}


def is_pickled_field(field) -> bool:
    meta = field.metadata or {}
    return meta.get(_PICKLED_META) == b"1"


def unpickle_columns(rb):
    """Materialize a record batch's pickled columns back into Python
    objects: ``{name: [values...]}`` for exactly the ``pw_pickled``
    columns (empty dict when none — the common all-columnar case)."""
    out = {}
    for i, field in enumerate(rb.schema):
        if is_pickled_field(field):
            out[field.name] = [
                None if v is None else pickle.loads(v)
                for v in rb.column(i).to_pylist()
            ]
    return out


def record_batch_rows(rb, cols):
    """Iterate a record batch back as ``(row_tuple, diff)`` — the
    universal consumer-side adapter (tests, TUI, legacy callbacks).
    Pickled columns are unpickled; ``_key`` is skipped unless asked for
    via ``cols``."""
    names = list(cols)
    pickled = unpickle_columns(rb)
    data = {}
    for name in names + ["diff"]:
        if name in pickled:
            data[name] = pickled[name]
        else:
            data[name] = rb.column(rb.schema.get_field_index(name)).to_pylist()
    for i in range(rb.num_rows):
        yield tuple(data[c][i] for c in names), data["diff"][i]
