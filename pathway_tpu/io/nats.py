"""pw.io.nats — connector surface (reference: python/pathway/io/nats (native NatsReader/Writer data_storage.rs:2226/:2300)).

Client transport gated on its library; the configuration surface matches
the reference so templates parse and fail only at run time with a clear
dependency error."""

from __future__ import annotations

from pathway_tpu.io._gated import require


def read(*args, schema=None, mode="streaming", autocommit_duration_ms=1500,
         name=None, **kwargs):
    require('nats')
    raise NotImplementedError(
        "pw.io.nats.read: client library found, but no nats service "
        "transport is wired in this build"
    )


def write(table, *args, name=None, **kwargs):
    require('nats')
    raise NotImplementedError(
        "pw.io.nats.write: client library found, but no nats service "
        "transport is wired in this build"
    )
