"""pw.io.bigquery — BigQuery output connector (reference:
python/pathway/io/bigquery — streaming inserts per commit)."""

from __future__ import annotations

from pathway_tpu.internals.parse_graph import G


def write(table, dataset_name: str, table_name: str, *,
          service_user_credentials_file: str | None = None,
          name: str | None = None, **kwargs) -> None:
    from google.cloud import bigquery

    if service_user_credentials_file is not None:
        client = bigquery.Client.from_service_account_json(
            service_user_credentials_file
        )
    else:
        client = bigquery.Client()
    target = f"{dataset_name}.{table_name}"
    cols = table.column_names()
    buffer: list[dict] = []

    def on_change(key, row, time_, diff):
        payload = dict(zip(cols, row))
        payload["time"] = time_
        payload["diff"] = diff
        buffer.append(payload)

    def on_time_end(time_):
        if buffer:
            client.insert_rows_json(target, list(buffer))
            buffer.clear()

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table),
            on_change=on_change,
            on_time_end=on_time_end,
        )

    G.add_operator([table], [], lower, "bigquery_write", is_output=True)
