"""Helper for connectors whose client libraries are not installed in this
environment: the full reference parameter surface is kept, and the missing
dependency is reported at call time (the reference behaves the same — its
connector modules import their client lazily and fail with an ImportError
naming the package)."""

from __future__ import annotations

import importlib
from typing import Any


def require(module: str, package_hint: str | None = None):
    try:
        return importlib.import_module(module)
    except ImportError as e:
        raise ImportError(
            f"this connector requires the `{package_hint or module}` package"
        ) from e


def gated_fn(system: str, module: str, package_hint: str | None = None):
    def fn(*args, **kwargs):
        require(module, package_hint)
        raise NotImplementedError(
            f"pw.io.{system}: client `{module}` is present but this "
            f"connector's transport is not wired in this build yet"
        )

    fn.__name__ = system
    fn.__doc__ = (
        f"pw.io.{system} (reference parity surface; requires `{package_hint or module}`)"
    )
    return fn
