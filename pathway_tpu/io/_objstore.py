"""Shared object-store polling scanner (reference:
src/connectors/scanner/s3.rs:268 + posix_like.rs:301 — object polling
with metadata diffing and deletion detection).

One scan protocol for every object store (GCS, S3, MinIO, ...): a
subclass supplies listing, download and identity; this base owns the
incremental semantics — changed objects (by stamp) retract their
previous rows before re-emitting, deleted objects retract, bookkeeping
is updated only after emission so flush snapshots never claim rows they
lack (io/_connector.py commit-boundary protocol).
"""

from __future__ import annotations

import csv as _csv
import io as _io
import json as _json
import time
from typing import Any, Iterable

from pathway_tpu.internals.api import Json, ref_scalar
from pathway_tpu.io.python import ConnectorSubject


def parse_object_bytes(data: bytes, fmt: str) -> list[dict]:
    """Object payload -> rows, by connector format name."""
    rows: list[dict] = []
    if fmt in ("csv", "dsv"):
        for rec in _csv.DictReader(_io.StringIO(data.decode("utf-8", "replace"))):
            rows.append(dict(rec))
    elif fmt in ("json", "jsonlines"):
        for line in data.decode("utf-8", "replace").splitlines():
            line = line.strip()
            if line:
                rows.append(_json.loads(line))
    elif fmt == "plaintext":
        for line in data.decode("utf-8", "replace").splitlines():
            rows.append({"data": line})
    elif fmt in ("plaintext_by_object", "plaintext_by_file"):
        rows.append({"data": data.decode("utf-8", "replace")})
    elif fmt == "binary":
        rows.append({"data": data})
    else:
        raise ValueError(f"unknown format {fmt!r}")
    return rows


class ObjectStoreSubject(ConnectorSubject):
    """Subclasses implement `_list`/`_get`/`_uri` and set `_scheme`."""

    _scheme = "obj"

    def __init__(self, fmt, with_metadata, mode, refresh_interval=5.0):
        super().__init__()
        self.fmt = fmt
        self.with_metadata = with_metadata
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._seen: dict[str, Any] = {}      # object -> stamp
        self._emitted: dict[str, list] = {}  # object -> [(key, row)]
        self._stop = False

    # -- store interface ---------------------------------------------------
    def _list(self) -> Iterable[tuple[str, Any, dict]]:
        """Yield (name, change_stamp, metadata_extras) per live object."""
        raise NotImplementedError

    def _get(self, name: str) -> bytes:
        raise NotImplementedError

    def _uri(self, name: str) -> str:
        raise NotImplementedError

    # -- scan protocol -----------------------------------------------------
    def _scan_once(self):
        current = set()
        for name, stamp, extras in self._list():
            current.add(name)
            if self._seen.get(name) == stamp:
                continue
            try:
                data = self._get(name)
            except Exception:
                # object vanished between list and download: the next
                # poll's deletion path retracts it; don't kill the pipeline
                continue
            for old_key, old_row in self._emitted.pop(name, []):
                self._remove(old_key, old_row)
            rows = parse_object_bytes(data, self.fmt)
            if self.with_metadata:
                meta = {
                    "path": self._uri(name),
                    "size": len(data),
                    "seen_at": int(time.time()),
                    **extras,
                }
                for r in rows:
                    r["_metadata"] = Json(meta)
            keyed = [
                (ref_scalar(self._scheme, self._uri(name), i), row)
                for i, row in enumerate(rows)
            ]
            for key, row in keyed:
                self._upsert(key, row)
            # bookkeeping after emission: flush snapshots stay consistent
            self._emitted[name] = keyed
            self._seen[name] = stamp
        for name in list(self._emitted):
            if name not in current:
                for old_key, old_row in self._emitted.pop(name, []):
                    self._remove(old_key, old_row)
                self._seen.pop(name, None)
        self.commit()

    def run(self):
        self._scan_once()
        if self.mode == "static":
            return
        while not self._stop:
            time.sleep(self.refresh_interval)
            self._scan_once()

    def on_stop(self):
        self._stop = True

    def snapshot_state(self):
        return {"seen": dict(self._seen), "emitted": dict(self._emitted)}

    def seek(self, state) -> None:
        self._seen = dict(state.get("seen", {}))
        self._emitted = dict(state.get("emitted", {}))
