"""pw.io.airbyte — Airbyte-catalog sources (reference:
python/pathway/io/airbyte/__init__.py:1-341 + io/airbyte/logic.py +
vendored third_party/airbyte_serverless).

Docker-less execution is first-class: declarative (YAML-manifest) sources
and plain executables speaking the Airbyte protocol run with the standard
library alone; the venv path installs ``airbyte-<name>`` from PyPI; only
image-only connectors still require a local Docker runtime (the
reference's own constraint for non-Python connectors)."""

from __future__ import annotations

import json
import logging
import time as _time
from typing import Any, Sequence

from pathway_tpu.internals.api import Json
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io._airbyte import (
    AirbyteSourceError,
    DeclarativeAirbyteSource,
    DockerAirbyteSource,
    ExecutableAirbyteSource,
    VenvAirbyteSource,
)


class _AirbyteRecordSchema(Schema):
    data: Json


def _load_connection(config_file_path: str) -> dict:
    from pathway_tpu.internals.yaml_loader import load_yaml

    with open(config_file_path) as f:
        cfg = load_yaml(f)
    if not isinstance(cfg, dict) or "source" not in cfg:
        raise ValueError(
            f"{config_file_path}: expected a connection file with a "
            "'source' section (pathway airbyte create-source layout)"
        )
    return cfg


def _construct_source(
    source_cfg: dict,
    streams: Sequence[str],
    env_vars: dict | None,
    enforce_method: str | None,
    config_dir: str,
):
    import os

    config = source_cfg.get("config")
    if "manifest" in source_cfg or "manifest_path" in source_cfg:
        manifest = source_cfg.get("manifest")
        if manifest is None:
            from pathway_tpu.internals.yaml_loader import load_yaml

            path = source_cfg["manifest_path"]
            if not os.path.isabs(path):
                path = os.path.join(config_dir, path)
            with open(path) as f:
                manifest = load_yaml(f)
        return DeclarativeAirbyteSource(manifest, config=config, streams=streams)
    if "executable" in source_cfg:
        return ExecutableAirbyteSource(
            source_cfg["executable"], config=config, streams=streams,
            env_vars=env_vars,
        )
    image = source_cfg.get("docker_image")
    if image is None:
        raise ValueError(
            "source section needs one of: manifest / manifest_path, "
            "executable, docker_image"
        )
    connector = image.removeprefix("airbyte/").partition(":")[0]
    if enforce_method == "pypi":
        return VenvAirbyteSource(
            connector, config=config, streams=streams, env_vars=env_vars
        )
    if enforce_method == "docker":
        return DockerAirbyteSource(
            image, config=config, streams=streams, env_vars=env_vars
        )
    # auto: prefer the python package when PyPI is reachable, else docker
    try:
        return VenvAirbyteSource(
            connector, config=config, streams=streams, env_vars=env_vars
        )
    except (AirbyteSourceError, OSError) as exc:
        logging.getLogger(__name__).info(
            "airbyte: venv path unavailable (%s); trying docker", exc
        )
        return DockerAirbyteSource(
            image, config=config, streams=streams, env_vars=env_vars
        )


def read(
    config_file_path: str,
    streams: Sequence[str],
    *,
    mode: str = "streaming",
    execution_type: str = "local",
    env_vars: dict | None = None,
    enforce_method: str | None = None,
    refresh_interval_ms: int = 60000,
    name: str | None = None,
    **kwargs,
):
    """Returns a table with a ``data`` Json column per Airbyte record
    (reference: io/airbyte/__init__.py read). Incremental streams carry
    their Airbyte STATE between syncs; with persistence configured the
    state also survives restarts (snapshot_state/seek protocol)."""
    import os

    from pathway_tpu.io import python as io_python

    if execution_type not in ("local", "remote"):
        raise ValueError(
            "pw.io.airbyte: execution_type must be 'local' or 'remote'"
        )
    cfg = _load_connection(config_file_path)
    if execution_type == "local" and (
        "remote_runner_url" in kwargs or "remote_runner_token" in kwargs
    ):
        # a remote runner configured while running locally means data
        # would silently leave the intended execution boundary — refuse
        raise ValueError(
            "remote_runner_url/remote_runner_token were given but "
            "execution_type is 'local'; pass execution_type='remote'"
        )
    if execution_type == "remote":
        # provider-neutral HTTPS runner (the reference's remote mode runs
        # on GCP Cloud Run — python/pathway/io/airbyte/__init__.py); the
        # endpoint comes from the kwarg or the connection file's
        # `remote_runner` section
        from pathway_tpu.io._airbyte import RemoteAirbyteSource

        runner = kwargs.pop("remote_runner_url", None) or (
            cfg.get("remote_runner") or {}
        ).get("url")
        token = kwargs.pop("remote_runner_token", None) or (
            cfg.get("remote_runner") or {}
        ).get("token")
        if not runner:
            raise ValueError(
                "execution_type='remote' needs remote_runner_url= or a "
                "remote_runner: {url: ...} section in the connection file"
            )
        source = RemoteAirbyteSource(
            runner, cfg["source"], streams, env_vars, token
        )
    else:
        source = _construct_source(
            cfg["source"],
            streams,
            env_vars,
            enforce_method,
            os.path.dirname(os.path.abspath(config_file_path)),
        )

    class _AirbyteSubject(io_python.ConnectorSubject):
        _deletions_enabled = False

        def __init__(self):
            super().__init__()
            self._state: Any = None  # LEGACY whole-state blob
            self._stream_states: dict[str, Any] = {}
            # full-refresh streams re-deliver everything each sync; the
            # subject diffs each sync against the previous snapshot so the
            # table stays a faithful mirror instead of accumulating
            # duplicates (content-keyed upsert/retract)
            self._prev_snapshot: dict[Any, dict] = {}
            self._cur_snapshot: dict[Any, dict] = {}

        # persistence protocol: the Airbyte state IS the scan state
        def snapshot_state(self):
            return {
                "state": self._state,
                "streams": self._stream_states,
                "snapshot": dict(self._prev_snapshot),
            }

        def seek(self, state) -> None:
            self._state = state.get("state")
            self._stream_states = dict(state.get("streams") or {})
            self._prev_snapshot = dict(state.get("snapshot") or {})

        def _compose_state(self) -> dict | None:
            if not self._stream_states:
                return self._state
            return {
                "type": "GLOBAL",
                "global": {
                    "stream_states": [
                        {
                            "stream_descriptor": {"name": sname},
                            "stream_state": st,
                        }
                        for sname, st in self._stream_states.items()
                    ],
                },
            }

        def _handle_state(self, payload: dict) -> None:
            # reference: io/airbyte/logic.py — LEGACY / GLOBAL / STREAM
            state_type = payload.get("type", "LEGACY")
            if state_type == "LEGACY":
                self._state = payload.get("data")
            elif state_type == "GLOBAL":
                for entry in payload.get("global", {}).get(
                    "stream_states", []
                ):
                    self._stream_states[
                        entry["stream_descriptor"]["name"]
                    ] = entry.get("stream_state", {})
            elif state_type in ("STREAM", "PER_STREAM"):
                entry = payload.get("stream", {})
                self._stream_states[
                    entry["stream_descriptor"]["name"]
                ] = entry.get("stream_state", {})
            else:
                logging.getLogger(__name__).warning(
                    "airbyte: unknown state type %r ignored", state_type
                )

        def _record_key(self, stream: str, data) -> Any:
            from pathway_tpu.internals.api import ref_scalar

            return ref_scalar(
                "airbyte", stream, json.dumps(data, sort_keys=True, default=str)
            )

        def _one_sync(self) -> int:
            n = 0
            saw_state = False
            self._cur_snapshot = {}
            for message in source.extract(self._compose_state()):
                mtype = message.get("type")
                if mtype == "RECORD":
                    stream = message["record"].get("stream", "")
                    data = message["record"].get("data")
                    key = self._record_key(stream, data)
                    self._cur_snapshot[key] = data
                    if key not in self._prev_snapshot:
                        self._upsert(key, {"data": Json(data)})
                    n += 1
                elif mtype == "STATE":
                    saw_state = True
                    self._handle_state(message["state"])
                    self.commit()
            # snapshot diff: rows the source stopped reporting retract.
            # Incremental (STATE-carrying) sources deliver only new rows
            # per sync, so their previous rows must NOT retract — the
            # union of all syncs is the table.
            if saw_state:
                self._prev_snapshot.update(self._cur_snapshot)
            else:
                for key, data in self._prev_snapshot.items():
                    if key not in self._cur_snapshot:
                        self._remove(key, {"data": Json(data)})
                self._prev_snapshot = self._cur_snapshot
            self.commit()
            return n

        def run(self):
            if mode == "static":
                self._one_sync()
                return
            failures = 0
            while not self._finished:
                try:
                    self._one_sync()
                    failures = 0
                except Exception:
                    # transient source failures retry with the refresh
                    # cadence (reference: io/airbyte MAX_RETRIES=5)
                    failures += 1
                    if failures >= 5:
                        raise
                    logging.getLogger(__name__).warning(
                        "airbyte: sync failed (%d/5), retrying", failures,
                        exc_info=True,
                    )
                _time.sleep(refresh_interval_ms / 1000.0)

        def on_stop(self):
            source.on_stop()

    return io_python.read(
        _AirbyteSubject(),
        schema=_AirbyteRecordSchema,
        autocommit_duration_ms=None,
        name=name or f"airbyte:{os.path.basename(config_file_path)}",
    )
