"""pw.io.airbyte — 300+ sources via airbyte connectors (reference:
python/pathway/io/airbyte + vendored third_party/airbyte_serverless; runs
connector images via local Docker or GCP Cloud Run). Requires a container
runtime; surface kept for template compatibility."""

from __future__ import annotations


def read(config_file_path: str, streams: list[str], *, mode: str = "streaming",
         execution_type: str = "local", enforce_method=None,
         refresh_interval_ms: int = 60000, name=None, **kwargs):
    import shutil

    if shutil.which("docker") is None:
        raise RuntimeError(
            "pw.io.airbyte requires a local Docker runtime (or Cloud Run "
            "credentials) to execute Airbyte connector images"
        )
    raise NotImplementedError(
        "pw.io.airbyte: docker present, but the airbyte-serverless driver "
        "is not wired in this build"
    )
