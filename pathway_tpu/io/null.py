"""pw.io.null — sink that discards rows (reference: python/pathway/io/null;
native NullWriter, data_storage.rs:1387). Used to force materialization of
a pipeline without producing output."""

from __future__ import annotations

from pathway_tpu.internals.parse_graph import G


def write(table, *, name: str | None = None, **kwargs) -> None:
    def lower(ctx):
        ctx.scope.output(ctx.engine_table(table), on_change=lambda *a: None)

    G.add_operator([table], [], lower, "null_write", is_output=True)
