"""pw.io.kafka — Kafka connector (reference: python/pathway/io/kafka +
native KafkaReader/KafkaWriter, data_storage.rs:692/:1250). Full parameter
surface; transport gated on `confluent_kafka` (partitioned reads map to
per-worker consumers in the reference — here one consumer drives the
engine's commit cadence)."""

from __future__ import annotations

import json as _json
from typing import Any

from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read


def _require_kafka():
    try:
        import confluent_kafka

        return confluent_kafka
    except ImportError as e:
        raise ImportError(
            "pw.io.kafka requires the `confluent-kafka` package"
        ) from e


class _KafkaSubject(ConnectorSubject):
    # multi-process runs: every rank consumes, each owning the topic
    # partitions that hash to it (reference: per-worker partitioned
    # consumption, data_storage.rs:692)
    _distributed_partitioned = True

    def __init__(self, rdkafka_settings, topics, *, format="json",
                 schema=None, message_parser=None):
        super().__init__()
        self.settings = dict(rdkafka_settings or {})
        self.topics = list(topics)
        self.format = format
        self.schema = schema
        self.message_parser = message_parser
        self._stop = False
        self._offsets: dict = {}

    # commit cadence: the connector protocol journals a stateful subject's
    # rows only at its commit() boundaries (io/_connector.py), so offsets
    # must be committed regularly — on idle polls and every N messages
    _COMMIT_EVERY = 1000

    def _owned_partitions(self, ck, consumer):
        """Partition p of topic t belongs to rank p % processes —
        deterministic, no rebalance coordination. Topics that do not
        exist yet (metadata error / empty partition set) resolve on a
        later refresh, matching subscribe()'s metadata-refresh pickup."""
        from pathway_tpu.internals.config import get_pathway_config

        c = get_pathway_config()
        owned = []
        for topic in self.topics:
            meta = consumer.list_topics(topic, timeout=10)
            entry = meta.topics.get(topic)
            if entry is None or entry.error is not None:
                continue
            for p in entry.partitions:
                if p % c.processes == c.process_id:
                    owned.append(ck.TopicPartition(topic, p))
        return owned

    def _subscribe(self, ck, consumer) -> None:
        from pathway_tpu.internals.config import get_pathway_config

        if get_pathway_config().processes <= 1:
            consumer.subscribe(self.topics)
            self._manual_assign = False
            return
        self._manual_assign = True
        self._assigned = self._owned_partitions(ck, consumer)
        consumer.assign(self._assigned)

    def _maybe_reassign(self, ck, consumer) -> None:
        """Pick up late-created topics and added partitions (refreshed on
        idle polls; subscribe() consumers get this from rebalances)."""
        if not self._manual_assign:
            return
        owned = self._owned_partitions(ck, consumer)
        current = {(tp.topic, tp.partition) for tp in self._assigned}
        fresh = {(tp.topic, tp.partition) for tp in owned}
        if fresh != current:
            self._assigned = owned
            consumer.assign(owned)

    _REASSIGN_EVERY_IDLE = 60  # idle polls (~30 s) between metadata checks

    def run(self):
        ck = _require_kafka()
        consumer = ck.Consumer(self.settings)
        self._subscribe(ck, consumer)
        idle = 0
        since_commit = 0
        try:
            while not self._stop:
                msg = consumer.poll(0.5)
                if msg is None or msg.error():
                    if since_commit:
                        self.commit()
                        since_commit = 0
                    idle += 1
                    if idle >= self._REASSIGN_EVERY_IDLE:
                        idle = 0
                        self._maybe_reassign(ck, consumer)
                    continue
                idle = 0
                raw = msg.value()
                self._offsets[(msg.topic(), msg.partition())] = msg.offset()
                if self.message_parser is not None:
                    self.message_parser(self, raw)
                elif self.format == "json":
                    self.next_json(_json.loads(raw))
                elif self.format == "raw":
                    self.next_bytes(raw)
                else:
                    self.next_str(
                        raw.decode() if isinstance(raw, bytes) else raw
                    )
                since_commit += 1
                if since_commit >= self._COMMIT_EVERY:
                    self.commit()
                    since_commit = 0
        finally:
            if since_commit:
                self.commit()
            consumer.close()

    def on_stop(self):
        self._stop = True

    def snapshot_state(self):
        return {"offsets": dict(self._offsets)}

    def seek(self, state):
        self._offsets = dict(state.get("offsets", {}))


def read(
    rdkafka_settings: dict,
    topic: str | list[str] | None = None,
    *,
    schema: type[Schema] | None = None,
    format: str = "json",
    autocommit_duration_ms: int | None = 1500,
    json_field_paths: dict | None = None,
    parallel_readers: int | None = None,
    topic_names: list[str] | None = None,
    name: str | None = None,
    **kwargs,
):
    _require_kafka()
    topics = topic_names or ([topic] if isinstance(topic, str) else list(topic or []))
    subject = _KafkaSubject(
        rdkafka_settings, topics, format=format, schema=schema
    )
    return python_read(
        subject,
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"kafka:{','.join(topics)}",
    )


def write(
    table,
    rdkafka_settings: dict,
    topic_name: str,
    *,
    format: str = "json",
    name: str | None = None,
    **kwargs,
) -> None:
    ck = _require_kafka()
    producer = ck.Producer(rdkafka_settings)
    cols = table.column_names()

    def on_change(key, row, time_, diff):
        payload = dict(zip(cols, row))
        payload["time"] = time_
        payload["diff"] = diff
        producer.produce(
            topic_name, _json.dumps(payload, default=str).encode()
        )
        producer.poll(0)

    def on_end():
        producer.flush()

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table), on_change=on_change, on_end=on_end
        )

    G.add_operator([table], [], lower, "kafka_write", is_output=True)
