"""pw.io — IO connector surface (reference: python/pathway/io/, §2.3 of
SURVEY: one module per system, each constructing engine data storage).

Connectors with external service dependencies (kafka, postgres, s3, ...)
are stubbed with informative errors until their native backends land.
"""

from __future__ import annotations

from pathway_tpu.io import csv, fs, http, jsonlines, plaintext, python
from pathway_tpu.io._connector import SupervisorPolicy
from pathway_tpu.io._subscribe import subscribe

__all__ = [
    "SupervisorPolicy",
    "airbyte",
    "bigquery",
    "csv",
    "debezium",
    "deltalake",
    "elasticsearch",
    "fs",
    "gcs",
    "gdrive",
    "http",
    "jsonlines",
    "kafka",
    "logstash",
    "minio",
    "mongodb",
    "nats",
    "null",
    "plaintext",
    "postgres",
    "pubsub",
    "pyfilesystem",
    "python",
    "redpanda",
    "s3",
    "s3_csv",
    "slack",
    "sqlite",
    "subscribe",
]

_LAZY_CONNECTORS = {
    "airbyte", "bigquery", "debezium", "deltalake", "elasticsearch",
    "gcs", "gdrive", "kafka", "logstash", "minio", "mongodb", "nats", "null",
    "postgres", "pubsub", "pyfilesystem", "redpanda", "s3", "s3_csv",
    "slack", "sqlite",
}


def __getattr__(name):
    if name in _LAZY_CONNECTORS:
        import importlib

        mod = importlib.import_module(f"pathway_tpu.io.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(name)


class OnChangeCallback:  # typing alias used in reference signatures
    pass
