"""pw.io — IO connector surface (reference: python/pathway/io/, §2.3 of
SURVEY: one module per system, each constructing engine data storage).

Connectors with external service dependencies (kafka, postgres, s3, ...)
are stubbed with informative errors until their native backends land.
"""

from __future__ import annotations

from pathway_tpu.io import csv, fs, http, jsonlines, plaintext, python
from pathway_tpu.io._subscribe import subscribe

__all__ = ["csv", "fs", "http", "jsonlines", "plaintext", "python", "subscribe"]


class OnChangeCallback:  # typing alias used in reference signatures
    pass
