"""Minimal S3 REST transport — dependency-free (urllib + SigV4).

The reference's S3 scanner is native Rust over the S3 REST API
(reference: src/connectors/scanner/s3.rs:268, persistence/backends/s3.rs).
This build takes the same stance: no boto3 — a small AWS Signature V4
client implementing exactly the operations the connectors need
(ListObjectsV2, GetObject, PutObject, DeleteObject). Works against AWS,
MinIO, DigitalOcean Spaces, Wasabi, or any S3-compatible endpoint
(path-style supported).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Any


class AwsS3Settings:
    """S3 connection settings (reference: internals/_io_helpers.py:17
    AwsS3Settings — same constructor surface)."""

    def __init__(
        self,
        *,
        bucket_name=None,
        access_key=None,
        secret_access_key=None,
        with_path_style=False,
        region=None,
        endpoint=None,
        session_token=None,
    ):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.session_token = session_token
        self.with_path_style = with_path_style
        self.region_explicit = region is not None
        self.region = region or "us-east-1"
        self.endpoint = endpoint

    def with_bucket(self, bucket: str | None) -> "AwsS3Settings":
        """Copy with the path-derived bucket resolved — callers' settings
        objects are never mutated and stay reusable across buckets."""
        out = AwsS3Settings(
            bucket_name=bucket or self.bucket_name,
            access_key=self.access_key,
            secret_access_key=self.secret_access_key,
            with_path_style=self.with_path_style,
            region=self.region if self.region_explicit else None,
            endpoint=self.endpoint,
            session_token=self.session_token,
        )
        return out

    @classmethod
    def new_from_path(cls, s3_path: str) -> "AwsS3Settings":
        bucket = s3_path.removeprefix("s3://").split("/", 1)[0]
        return cls(bucket_name=bucket)


_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


@dataclass
class S3Object:
    key: str
    etag: str
    size: int
    last_modified: str
    owner: str | None = None


class S3Client:
    """SigV4-signed HTTP client for one bucket."""

    def __init__(self, settings: AwsS3Settings, opener=None):
        if settings.bucket_name is None:
            raise ValueError("S3 settings need bucket_name")
        self.s = settings
        # opener injection point for tests (urllib-compatible .open)
        self._opener = opener or urllib.request.build_opener()

    # -- endpoint shaping --------------------------------------------------
    def _base(self) -> tuple[str, str, str]:
        """(scheme://authority, host header value, path prefix)"""
        s = self.s
        if s.endpoint:
            ep = s.endpoint
            if "://" not in ep:
                ep = "https://" + ep
            parsed = urllib.parse.urlsplit(ep)
            if s.with_path_style:
                return (
                    f"{parsed.scheme}://{parsed.netloc}",
                    parsed.netloc,
                    f"/{s.bucket_name}",
                )
            host = f"{s.bucket_name}.{parsed.netloc}"
            return f"{parsed.scheme}://{host}", host, ""
        host = f"{s.bucket_name}.s3.{s.region}.amazonaws.com"
        return f"https://{host}", host, ""

    # -- SigV4 -------------------------------------------------------------
    def _sign(
        self,
        method: str,
        path: str,
        query: dict[str, str],
        host: str,
        payload_hash: str,
        now: datetime.datetime | None = None,
        extra_headers: dict[str, str] | None = None,
    ) -> dict[str, str]:
        s = self.s
        now = now or datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers = {
            "host": host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }
        if extra_headers:
            headers.update(
                {k.lower(): v for k, v in extra_headers.items()}
            )
        if s.session_token:
            headers["x-amz-security-token"] = s.session_token
        if not s.access_key:
            # anonymous access (public buckets / unauthenticated MinIO)
            return {k: v for k, v in headers.items() if k != "host"}
        canonical_query = "&".join(
            f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
            for k, v in sorted(query.items())
        )
        signed_headers = ";".join(sorted(headers))
        canonical_headers = "".join(
            f"{k}:{headers[k].strip()}\n" for k in sorted(headers)
        )
        # `path` arrives already percent-encoded (see _request) — signing
        # must use it verbatim or keys needing encoding 403-mismatch
        canonical_request = "\n".join(
            [
                method,
                path,
                canonical_query,
                canonical_headers,
                signed_headers,
                payload_hash,
            ]
        )
        scope = f"{datestamp}/{s.region}/s3/aws4_request"
        string_to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical_request.encode()).hexdigest(),
            ]
        )

        def _hmac(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k_date = _hmac(("AWS4" + s.secret_access_key).encode(), datestamp)
        k_region = _hmac(k_date, s.region)
        k_service = _hmac(k_region, "s3")
        k_signing = _hmac(k_service, "aws4_request")
        signature = hmac.new(
            k_signing, string_to_sign.encode(), hashlib.sha256
        ).hexdigest()
        headers["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={s.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        )
        return {k: v for k, v in headers.items() if k != "host"}

    def _request(
        self,
        method: str,
        key: str = "",
        query: dict[str, str] | None = None,
        body: bytes | None = None,
        extra_headers: dict[str, str] | None = None,
    ):
        base, host, prefix = self._base()
        query = query or {}
        path = prefix + "/" + urllib.parse.quote(key, safe="/")
        payload_hash = (
            hashlib.sha256(body).hexdigest() if body else _EMPTY_SHA256
        )
        headers = self._sign(
            method, path, query, host, payload_hash,
            extra_headers=extra_headers,
        )
        qs = urllib.parse.urlencode(sorted(query.items()))
        url = base + path + (f"?{qs}" if qs else "")
        req = urllib.request.Request(
            url, data=body, method=method, headers=headers
        )
        return self._opener.open(req, timeout=60)

    # -- operations --------------------------------------------------------
    def list_objects(self, prefix: str = "") -> list[S3Object]:
        out: list[S3Object] = []
        token: str | None = None
        while True:
            query = {"list-type": "2", "prefix": prefix}
            if token:
                query["continuation-token"] = token
            with self._request("GET", "", query) as resp:
                tree = ET.fromstring(resp.read())
            ns = ""
            if tree.tag.startswith("{"):
                ns = tree.tag.split("}")[0] + "}"
            for item in tree.iter(f"{ns}Contents"):
                def _txt(tag, default=""):
                    el = item.find(f"{ns}{tag}")
                    return el.text if el is not None and el.text else default

                owner_el = item.find(f"{ns}Owner/{ns}ID")
                out.append(
                    S3Object(
                        key=_txt("Key"),
                        etag=_txt("ETag"),
                        size=int(_txt("Size", "0")),
                        last_modified=_txt("LastModified"),
                        owner=owner_el.text if owner_el is not None else None,
                    )
                )
            trunc = tree.find(f"{ns}IsTruncated")
            if trunc is not None and (trunc.text or "").lower() == "true":
                nxt = tree.find(f"{ns}NextContinuationToken")
                token = nxt.text if nxt is not None else None
                if not token:
                    return out
            else:
                return out

    def get_object(self, key: str) -> bytes:
        with self._request("GET", key) as resp:
            return resp.read()

    def put_object(self, key: str, data: bytes) -> None:
        with self._request("PUT", key, body=data) as resp:
            resp.read()

    def put_object_if_absent(self, key: str, data: bytes) -> None:
        """Conditional create (``If-None-Match: *``): raises
        FileExistsError when the key already exists. AWS S3 (since the
        2024 conditional-writes GA) and MinIO both honor it — the
        put-if-absent primitive Delta log commits need for
        mutually-exclusive version creation."""
        try:
            with self._request(
                "PUT", key, body=data, extra_headers={"if-none-match": "*"}
            ) as resp:
                resp.read()
        except urllib.error.HTTPError as e:
            if e.code in (409, 412):  # exists (412 AWS/MinIO, 409 GCS-compat)
                raise FileExistsError(key) from e
            raise

    def delete_object(self, key: str) -> None:
        try:
            with self._request("DELETE", key) as resp:
                resp.read()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
