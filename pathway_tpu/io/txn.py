"""Transactional egress — epoch-aligned two-phase-commit sinks
(ISSUE 12 tentpole).

The mesh is exactly-once *inside* the engine across rollback and even
across world changes, but a plain file/object-store sink re-observes the
uncommitted suffix a rollback re-emits — so every such output was
silently at-least-once under exactly the failures the rest of the stack
survives. This module closes the hole with a two-phase-commit protocol
aligned to the engine's snapshot cuts:

* **stage** — during a wave, each rank writes output into rank-scoped
  staged segment files keyed by ``(rank, epoch, commit-timestamp)``;
  nothing staged is externally visible;
* **pre-commit** — at the snapshot cut the staged set is atomically
  tagged with the cut's tag (one directory rename), so the set the
  marker will commit is frozen *before* the marker moves;
* **finalize** — only once the ``snapshot_commit`` marker has durably
  landed at-or-past the tag do staged units become visible (atomic
  renames into the finalized segment store + a write-temp/fsync/rename
  republish of the visible file — a crash mid-write can never leave a
  partial file visible);
* **recover** — on restore, recovery scans pending staged units and
  takes one verdict per unit through the shared
  :func:`~pathway_tpu.parallel.protocol.sink_recover` transition:
  finalize everything at-or-below the committed cut, discard the rest.
  Staged units are ``(tag, world)``-scoped like the snapshot marker, so
  recovery after an N→M rescale re-assigns pending partitions through
  the shared ``shard_owner`` mint.

Correct by construction like every mesh protocol so far: the
stage/pre-commit/finalize/recover *decisions* are pure transitions in
``parallel/protocol.py`` that this module binds verbatim (identity
pinned by tests) and ``analysis/meshcheck.py --mesh --sink``
exhaustively model-checks over all crash interleavings — including a
rescale window. The seeded ``finalize_before_marker`` mutant (finalize
at pre-commit, before the marker lands) is the canonical 2PC bug and
must be caught with a minimal replayable trace.

What remains at-least-once: runs without ``OPERATOR_PERSISTING`` have
no snapshot marker to align with, so sinks finalize at every commit
timestamp (still torn-write-proof via atomic rename, but a crash loses
no committed marker to recover against — the run restarts from
scratch). ``pw.io.subscribe``/``on_batch`` consumers get a delivery
envelope ``(epoch, commit_ts, seq)`` so external systems can dedup that
remaining surface.
"""

from __future__ import annotations

import io as _io
import json as _json
import os
import re
import shutil
import time as _time
from typing import NamedTuple

from pathway_tpu.internals import faults as _faults
from pathway_tpu.parallel import protocol as _proto

# the shared transition table entries this module drives through — the
# SAME objects analysis/meshcheck.py explores (identity pinned by
# tests/test_txn_sinks.py, like NBDecision and the wave protocol)
SINK_MAY_FINALIZE = _proto.TRANSITIONS["sink_may_finalize"]
SINK_RECOVER = _proto.TRANSITIONS["sink_recover"]
SHARD_OWNER = _proto.TRANSITIONS["shard_owner"]


class DeliveryEnvelope(NamedTuple):
    """The delivery metadata handed to ``pw.io.subscribe(...,
    on_batch=..., with_envelope=True)`` consumers: ``epoch`` is the
    mesh recovery epoch the batch was emitted in (0 outside supervised
    meshes), ``commit_ts`` the engine commit timestamp (monotone across
    restarts — wall-clock-floored), and ``seq`` a per-subscription
    sequence number strictly monotone within one process incarnation.

    What it buys an external consumer of this at-least-once surface:
    ``(epoch, commit_ts)`` orders every delivery, and a REDELIVERY
    WINDOW is always detectable — a mesh rollback bumps ``epoch``, and
    any restart resets ``seq`` (a ``seq`` at-or-below the consumer's
    high-water for the same epoch marks the stream as rewound; note a
    non-mesh OPERATOR_PERSISTING restart keeps ``epoch`` 0, so the
    ``seq`` reset is the signal there). Within the window the engine
    re-emits the uncommitted suffix with FRESH timestamps, so exact
    dedup needs the consumer's own row keys (upserts) — or the
    transactional sinks, which do it below this API. Batches arriving
    with ``seq`` strictly above the high-water and no epoch change are
    guaranteed first deliveries and can be applied without any key
    lookup."""

    epoch: int
    commit_ts: int
    seq: int


def _env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    return raw.strip().lower() not in ("0", "false", "no")


def _fsync_enabled() -> bool:
    return _env_bool("PATHWAY_SINK_FSYNC", True)


def txn_enabled() -> bool:
    """PATHWAY_SINK_TXN=0 disables epoch alignment entirely (sinks then
    finalize at every commit timestamp, still via atomic rename)."""
    return _env_bool("PATHWAY_SINK_TXN", True)


def _fsync_file(f) -> None:
    if _fsync_enabled():
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    """Durable rename point: fsync the containing directory so the
    rename itself survives power loss (best-effort — not every fs
    supports O_DIRECTORY fds)."""
    if not _fsync_enabled():
        return
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def note_egress_seconds(stats, name: str, seconds: float) -> None:
    """Per-sink egress-seconds accounting (ISSUE 14), shared by every
    transactional sink so the guard/label policy cannot diverge."""
    if stats is not None and hasattr(stats, "on_sink_egress_seconds"):
        stats.on_sink_egress_seconds(name, seconds)


def write_atomic(path: str, data: bytes) -> None:
    """THE torn-write fix (ISSUE 12 satellite): every finalization —
    and every plain-file sink write, even outside mesh mode — routes
    through write-temp + fsync + atomic rename, so a crash mid-write
    can never leave a partial file visible."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + f".pw-tmp-{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        _fsync_file(f)
    os.replace(tmp, path)
    _fsync_dir(d)


_SEG_RE = re.compile(
    r"^seg-e(\d+)-t(\d+)-(\d+)\.dat$"
)
_TAG_RE = re.compile(r"^t(\d+)$")


def seg_name(epoch: int, commit_ts: int, seq: int) -> str:
    """Staged-unit file name: the ``(epoch, commit-timestamp, seq)``
    key, zero-padded so lexicographic order IS delivery order (epochs
    are monotone across rollbacks, timestamps monotone within one)."""
    return f"seg-e{epoch:08d}-t{commit_ts:020d}-{seq:08d}.dat"


class TransactionalSink:
    """Protocol base for two-phase-commit sinks. The runtime drives the
    four verbs around its snapshot lifecycle (engine/runtime.py):

    * ``arm(stats, txn, rank, world, epoch)`` — once at run start;
      ``txn=False`` (no OPERATOR_PERSISTING cut to align with) makes
      ``on_time_end`` finalize immediately;
    * ``precommit(tag)`` — at the snapshot cut, BEFORE the
      ``snapshot_commit`` marker moves;
    * ``finalize(tag)`` — after the marker (and, on a mesh, the
      snapshot barrier) landed at ``tag``;
    * ``recover(marker_tag, world)`` — at restore, before any new data
      flows; also with ``marker_tag=None`` for a from-scratch start.

    ``abort_for_rollback()`` is the epoch-abort courtesy hook
    (io/_connector.py ``abort_sinks_for_rollback``): best-effort
    discard of un-pre-committed staging before the supervised exit —
    recovery would discard it anyway, this just reclaims disk early.
    """

    name: str = "sink"

    def arm(
        self, *, stats=None, txn=False, rank=0, world=1, epoch=0,
        lineage=None,
    ):
        """``lineage`` is the persistence store's egress lineage id
        (minted once per store; None outside epoch-aligned mode) —
        sinks whose dedup records outlive the persistence directory
        (the Delta ``txn`` appId) must scope them by it."""
        raise NotImplementedError

    def precommit(self, tag: int) -> None:
        raise NotImplementedError

    def finalize(self, tag: int) -> None:
        raise NotImplementedError

    def recover(self, marker_tag: int | None, world: int) -> None:
        raise NotImplementedError

    def abort_for_rollback(self) -> None:  # pragma: no cover - courtesy
        pass


class TxnFileSink(TransactionalSink):
    """Two-phase-commit file sink backing ``pw.io.fs/csv/jsonlines``
    writers.

    Layout for an output file ``F`` (all under ``F.pw-txn/``, or
    ``PATHWAY_SINK_STAGE_DIR`` when set):

    * ``final/`` — finalized segment files; the visible file ``F`` is
      the deterministic concatenation (header + segments in name
      order) republished atomically after every finalize;
    * ``stage/r{rank}/e{epoch}/open/`` — sealed-but-unpre-committed
      segments of the current epoch;
    * ``stage/r{rank}/e{epoch}/t{tag}/`` — the pre-committed set of
      cut ``tag`` (one atomic directory rename at pre-commit).

    Gather sinks are single-writer (rank 0 owns the file), but the
    recovery claim still routes through the shared ``shard_owner``
    mint over the sink's partition id so the behavior matches the
    partitioned (Delta) sinks and the model checker."""

    def __init__(self, filename: str, *, format: str = "csv", cols=()):
        self.filename = os.path.abspath(filename)
        self.format = format
        self.cols = list(cols)
        self.name = f"fs:{os.path.basename(filename)}"
        base = os.environ.get("PATHWAY_SINK_STAGE_DIR", "").strip()
        if base:
            # stage under a user-chosen root, keyed by the output's
            # basename + a short path hash so two outputs never collide
            import zlib as _zlib

            key = (
                f"{os.path.basename(self.filename)}-"
                f"{_zlib.crc32(self.filename.encode()) & 0xFFFFFFFF:08x}"
            )
            self.root = os.path.join(os.path.abspath(base), key)
        else:
            self.root = self.filename + ".pw-txn"
        self._txn = False
        self._rank = 0
        self._world = 1
        self._epoch = 0
        self._stats = None
        self._armed = False
        # incarnation token: names this process's open staging dir so a
        # recovery scan can tell LIVE staging (rows this incarnation
        # already sealed — e.g. program-embedded static rows injected
        # before the restore window) from a dead incarnation's
        # un-pre-committed leftovers, which no cut claims and which the
        # restored engine will re-emit (keeping them would duplicate)
        import uuid as _uuid

        self._incarnation = _uuid.uuid4().hex[:12]
        self._started = False  # lazy fresh-start for unarmed (static) runs
        self._buf: list[bytes] = []
        self._buf_time: int | None = None
        self._seg_seq = 0
        self._staged_tag = -1
        self._finalized_tag = -1

    # -- layout helpers ----------------------------------------------------

    def _final_dir(self) -> str:
        return os.path.join(self.root, "final")

    def _stage_dir(self, rank: int | None = None, epoch: int | None = None):
        p = os.path.join(self.root, "stage")
        if rank is not None:
            p = os.path.join(p, f"r{rank}")
            if epoch is not None:
                p = os.path.join(p, f"e{epoch:08d}")
        return p

    def _open_dir(self) -> str:
        return os.path.join(
            self._stage_dir(self._rank, self._epoch),
            f"open-{self._incarnation}",
        )

    def _header(self) -> bytes:
        if self.format == "csv":
            out = _io.StringIO()
            import csv as _csv

            _csv.writer(out).writerow(self.cols + ["time", "diff"])
            return out.getvalue().encode()
        return b""

    # -- encoding ----------------------------------------------------------

    def _encode(self, deltas, time: int) -> bytes:
        if self.format == "csv":
            out = _io.StringIO()
            import csv as _csv

            w = _csv.writer(out)
            for _k, row, d in deltas:
                w.writerow(list(row) + [time, d])
            return out.getvalue().encode()
        lines = []
        for _k, row, d in deltas:
            payload = dict(zip(self.cols, row))
            payload["time"] = time
            payload["diff"] = d
            lines.append(_json.dumps(payload, default=str))
        return ("\n".join(lines) + "\n").encode() if lines else b""

    def _encode_arrow(self, rb, time: int) -> bytes:
        """Serialize one Arrow record batch (value columns + ``diff``)
        straight off its columns — each column converts to a Python
        list in one C pass (pyarrow ``to_pylist``), so no engine row
        tuples ever exist; the byte output is IDENTICAL to
        ``_encode`` over the equivalent deltas (the parity battery
        pins it)."""
        col_vals = [
            rb.column(rb.schema.get_field_index(c)).to_pylist()
            for c in self.cols
        ]
        diffs = rb.column(rb.schema.get_field_index("diff")).to_pylist()
        if self.format == "csv":
            out = _io.StringIO()
            import csv as _csv

            w = _csv.writer(out)
            w.writerows(
                list(vals) + [time, d]
                for vals, d in zip(zip(*col_vals), diffs)
            )
            return out.getvalue().encode()
        lines = []
        for vals, d in zip(zip(*col_vals), diffs):
            payload = dict(zip(self.cols, vals))
            payload["time"] = time
            payload["diff"] = d
            lines.append(_json.dumps(payload, default=str))
        return ("\n".join(lines) + "\n").encode() if lines else b""

    def _note_egress(self, seconds: float) -> None:
        note_egress_seconds(self._stats, self.name, seconds)

    # -- engine callbacks --------------------------------------------------

    def on_batch(self, time: int, deltas) -> None:
        self._ensure_started()
        t0 = _time.perf_counter()
        data = self._encode(deltas, time)
        if data:
            self._buf.append(data)
            self._buf_time = time
        self._note_egress(_time.perf_counter() - t0)

    def on_batch_arrow(self, time: int, rb) -> None:
        """Columnar staging (ISSUE 14): the OutputNode delivers the
        fused chain's NativeBatch output as an Arrow record batch and
        the sink serializes it column-wise — no row round-trip."""
        self._ensure_started()
        t0 = _time.perf_counter()
        if rb is not None and rb.num_rows:
            data = self._encode_arrow(rb, time)
            if data:
                self._buf.append(data)
                self._buf_time = time
        self._note_egress(_time.perf_counter() - t0)

    def on_time_end(self, time: int) -> None:
        self._seal(time)
        if not self._txn:
            # no snapshot cut to align with: finalize immediately (the
            # documented at-least-once boundary outside OPERATOR_
            # PERSISTING), still torn-write-proof via atomic rename
            self._finalize_pending(marker_tag=None, unconditional=True)

    def on_end(self) -> None:
        self._ensure_started()
        if self._buf_time is not None:
            self._seal(self._buf_time)
        if not self._txn:
            self._finalize_pending(marker_tag=None, unconditional=True)
            self._publish()
            # from-scratch runs have nothing to recover against next
            # time: the segment store is garbage once published
            shutil.rmtree(self.root, ignore_errors=True)
        # txn mode: the runtime's final cut (snapshot + marker +
        # finalize) already drove the 2PC before on_end fires

    # -- the 2PC verbs -----------------------------------------------------

    def arm(
        self, *, stats=None, txn=False, rank=0, world=1, epoch=0,
        lineage=None,
    ):
        self._stats = stats
        self._txn = txn and txn_enabled()
        self._rank = rank
        self._world = world
        self._epoch = epoch
        self._armed = True
        if not self._txn and SHARD_OWNER(0, world) == rank:
            # from-scratch semantics — but ONLY the writer rank may
            # clear the shared staging root: a late-arming non-writer
            # rank must not race the writer's fresh output away
            self._fresh()
        self._started = True

    def _ensure_started(self) -> None:
        if self._started:
            return
        # unarmed (static / analyzer) run: from-scratch semantics
        self._fresh()
        self._started = True

    def _fresh(self) -> None:
        """From-scratch start (no committed cut to recover against):
        stale staging AND stale finalized segments from a previous run
        are discarded — the run regenerates everything."""
        shutil.rmtree(self.root, ignore_errors=True)

    def _seal(self, time: int) -> None:
        """Stage the buffered rows of commit ``time`` as one durable
        segment file. Staged output is invisible until finalized."""
        if not self._buf:
            return
        self._ensure_started()
        _faults.fault_point("sink.stage", rank=self._rank)
        data = b"".join(self._buf)
        self._buf = []
        self._buf_time = None
        self._seg_seq += 1
        name = seg_name(self._epoch, time, self._seg_seq)
        d = self._open_dir()
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(data)
            _fsync_file(f)
        os.replace(tmp, os.path.join(d, name))
        _fsync_dir(d)
        if self._stats is not None:
            self._stats.on_sink_staged(self.name)
            self._note_lag()

    def heap_nbytes(self) -> int:
        """Bytes of encoded-but-unsealed rows buffered in memory — the
        memory accountant's ``txn_staging`` component (ISSUE 19).
        Sealed/staged units live on DISK and are deliberately not
        counted: the watermark ladder governs heap, not the lake."""
        return sum(len(b) for b in self._buf)

    def precommit(self, tag: int) -> None:
        """Freeze the staged set under the cut's tag BEFORE the marker
        moves: one atomic directory rename (open -> t{tag}). Runs on
        every rank inside the snapshot collective window, so the set
        the marker commits can never change after the marker lands."""
        if not self._txn:
            return
        if self._buf_time is not None:
            self._seal(self._buf_time)
        open_dir = self._open_dir()
        if not os.path.isdir(open_dir) or not os.listdir(open_dir):
            self._staged_tag = max(self._staged_tag, tag)
            return
        dst = os.path.join(
            self._stage_dir(self._rank, self._epoch), f"t{tag:020d}"
        )
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.isdir(dst):
            # a retried cut at the same tag: merge (segment names are
            # globally unique, so plain moves cannot collide)
            for n in os.listdir(open_dir):
                os.replace(os.path.join(open_dir, n), os.path.join(dst, n))
            os.rmdir(open_dir)
        else:
            os.replace(open_dir, dst)
        _fsync_dir(os.path.dirname(dst))
        self._staged_tag = max(self._staged_tag, tag)
        self._note_lag()

    def finalize(self, tag: int) -> None:
        """The marker landed at ``tag``: staged units at-or-below it
        become externally visible. Driven per unit through the shared
        ``sink_may_finalize`` transition — the same function the model
        checker explores (and the ``finalize_before_marker`` mutant
        breaks). Single-writer: only the owner of the sink's partition
        (rank 0 of a gather sink) touches the visible file — every
        other rank stages nothing and must not race the publish."""
        if not self._txn:
            return
        self._finalized_tag = max(self._finalized_tag, tag)
        self._note_lag()
        if SHARD_OWNER(0, self._world) != self._rank:
            return
        if self._finalize_pending(marker_tag=tag):
            # republish only when segments actually finalized: a quiet
            # cut must not rewrite the whole committed file (recover()
            # keeps its unconditional publish for crash convergence)
            self._publish()

    def recover(self, marker_tag: int | None, world: int) -> None:
        """Restore-time scan of pending staged output: one shared
        ``sink_recover`` verdict per unit — finalize everything the
        committed cut covers, discard the rest (including dead-epoch
        ``open`` staging). Idempotent: a second recovery finds nothing
        pending and republishes the identical file. The claim routes
        through ``shard_owner`` over the staged partition id, so after
        an N→M rescale the pending partitions of dead ranks are
        re-owned deterministically by exactly one rank of the new
        world."""
        self._armed = True
        self._started = True
        self._world = world
        _faults.fault_point("sink.recover", rank=self._rank)
        stage_root = self._stage_dir()
        recovered = aborted = 0
        if os.path.isdir(stage_root):
            for rdir in sorted(os.listdir(stage_root)):
                if not rdir.startswith("r"):
                    continue
                try:
                    partition = int(rdir[1:])
                except ValueError:
                    continue
                if SHARD_OWNER(partition, world) != self._rank:
                    continue  # another rank of this world owns it
                rpath = os.path.join(stage_root, rdir)
                for edir in sorted(os.listdir(rpath)):
                    epath = os.path.join(rpath, edir)
                    for unit in sorted(os.listdir(epath)):
                        upath = os.path.join(epath, unit)
                        m = _TAG_RE.match(unit)
                        if m is None:
                            if (
                                unit == f"open-{self._incarnation}"
                                and marker_tag is None
                            ):
                                # THIS incarnation's live staging on a
                                # from-scratch start (static rows sealed
                                # before the restore window) — a later
                                # cut will pre-commit it
                                continue
                            # discard: either a dead incarnation's
                            # un-pre-committed staging (no cut claims
                            # it), or THIS incarnation's pre-restore
                            # staging under a committed marker — the
                            # only rows staged before recovery are the
                            # re-injected static rows, which the
                            # restored cut already committed (keeping
                            # them would duplicate them every restart)
                            aborted += self._count_segs(upath)
                            shutil.rmtree(upath, ignore_errors=True)
                            continue
                        unit_tag = int(m.group(1))
                        verdict = SINK_RECOVER(unit_tag, marker_tag)
                        if verdict == "finalize":
                            recovered += self._adopt_unit(upath)
                        else:
                            aborted += self._count_segs(upath)
                            shutil.rmtree(upath, ignore_errors=True)
        if marker_tag is None and SHARD_OWNER(0, world) == self._rank:
            # nothing committed: the restored engine re-emits everything,
            # so previously finalized output must go too
            n = 0
            fdir = self._final_dir()
            if os.path.isdir(fdir):
                n = len(os.listdir(fdir))
            shutil.rmtree(fdir, ignore_errors=True)
            aborted += n
        if SHARD_OWNER(0, world) == self._rank:
            self._publish()
        if self._stats is not None:
            if recovered:
                self._stats.on_sink_recovered(self.name, recovered)
            if aborted:
                self._stats.on_sink_aborted(self.name, aborted)
        if marker_tag is not None:
            self._finalized_tag = max(self._finalized_tag, marker_tag)
            self._staged_tag = max(self._staged_tag, marker_tag)
        self._note_lag()

    def abort_for_rollback(self) -> None:
        """Epoch abort: discard this epoch's un-pre-committed staging
        (recovery would discard it anyway — this reclaims it early and
        makes the abort observable on the counters)."""
        d = self._open_dir()
        n = self._count_segs(d)
        shutil.rmtree(d, ignore_errors=True)
        self._buf = []
        self._buf_time = None
        if n and self._stats is not None:
            self._stats.on_sink_aborted(self.name, n)

    # -- internals ---------------------------------------------------------

    @staticmethod
    def _count_segs(path: str) -> int:
        try:
            return sum(
                1 for n in os.listdir(path) if _SEG_RE.match(n)
            )
        except OSError:
            return 0

    def _adopt_unit(self, unit_dir: str) -> int:
        """Move a pending unit's segments into final/ (atomic per-file
        renames; already-present names are skipped, which is what makes
        a crash mid-finalize — and a double recovery — idempotent)."""
        fdir = self._final_dir()
        os.makedirs(fdir, exist_ok=True)
        n = 0
        for name in sorted(os.listdir(unit_dir)):
            if not _SEG_RE.match(name):
                continue
            dst = os.path.join(fdir, name)
            if not os.path.exists(dst):
                os.replace(os.path.join(unit_dir, name), dst)
                n += 1
        _fsync_dir(fdir)
        shutil.rmtree(unit_dir, ignore_errors=True)
        return n

    def _pending_units(self):
        """(tag, path) of this rank+epoch's pre-committed units."""
        d = self._stage_dir(self._rank, self._epoch)
        if not os.path.isdir(d):
            return []
        out = []
        for unit in sorted(os.listdir(d)):
            m = _TAG_RE.match(unit)
            if m is not None:
                out.append((int(m.group(1)), os.path.join(d, unit)))
        return out

    def _finalize_pending(
        self, marker_tag: int | None, unconditional: bool = False
    ) -> int:
        n = 0
        # unconditional path (non-txn): everything sealed moves straight
        # to final — the open dir is the only staging that exists
        if unconditional:
            d = self._open_dir()
            if os.path.isdir(d) and os.listdir(d):
                _faults.fault_point("sink.finalize", rank=self._rank)
                n += self._adopt_unit(d)
                self._publish()
        else:
            for unit_tag, upath in self._pending_units():
                if SINK_MAY_FINALIZE(unit_tag, marker_tag):
                    _faults.fault_point("sink.finalize", rank=self._rank)
                    n += self._adopt_unit(upath)
        if n and self._stats is not None:
            self._stats.on_sink_finalized(self.name, n)
        return n

    def _publish(self) -> None:
        """Republish the visible file by STREAMING the finalized
        segment store (header + segments in name order) into a temp
        file, fsync, atomic rename — O(1) memory no matter how large
        the committed output grows, deterministic, and convergent after
        any crash. The whole-file rewrite is the torn-write guarantee;
        per-cut write amplification is O(committed output), which suits
        committed aggregates and bounded outputs — unbounded raw-volume
        streams should prefer the append-only Delta sink."""
        d = os.path.dirname(self.filename)
        os.makedirs(d, exist_ok=True)
        tmp = self.filename + f".pw-tmp-{os.getpid()}"
        with open(tmp, "wb") as out:
            out.write(self._header())
            fdir = self._final_dir()
            if os.path.isdir(fdir):
                for name in sorted(os.listdir(fdir)):
                    if not _SEG_RE.match(name):
                        continue
                    with open(os.path.join(fdir, name), "rb") as seg:
                        shutil.copyfileobj(seg, out)
            _fsync_file(out)
        os.replace(tmp, self.filename)
        _fsync_dir(d)

    def _note_lag(self) -> None:
        if self._stats is not None and self._txn:
            self._stats.on_sink_epoch_lag(
                self.name,
                max(0, self._staged_tag - self._finalized_tag),
            )
