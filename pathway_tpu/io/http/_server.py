"""REST server connector (reference: python/pathway/io/http/_server.py —
PathwayWebserver :329, rest_connector :624, RestServerSubject :525).

One aiohttp application (owned by a PathwayWebserver) serves any number of
routes; each route is a connector: an incoming request becomes a row in the
queries table, the caller's response future resolves when the paired
response-writer table produces the row with the same id.

Serving gateway (ROADMAP item 1 — serve at the device bound): requests do
NOT commit one-by-one. Each admitted request joins the route's dynamic
batch window; the window closes on ``PATHWAY_SERVE_WINDOW_MS`` elapsed or
``PATHWAY_SERVE_MAX_BATCH`` collected — whichever first — and the whole
window enters the dataflow as ONE commit (= one dataflow timestamp = one
BSP round = one fused KNN+rerank device dispatch downstream, because the
external-index operator batches queries per timestamp). Responses fan out
per window through the batched subscribe path (``on_batch``), one
cross-thread hop per window instead of one per row. Admission is bounded
(``PATHWAY_SERVE_QUEUE_CAP``): overflow is shed with 503 + ``Retry-After``
sized from the observed service rate, and shed/timed-out requests are
evicted from their window so they never occupy a batch slot or a device
dispatch. aiohttp keeps HTTP/1.1 connections alive, so a closed-loop
client pays the TCP+TLS setup once, not per query.

Serving through rollback (ISSUE 9): under a mesh supervisor with
``--serve-frontend``, the PUBLIC listener lives in the supervisor's
epoch-survivable frontend (``_frontend.py``) and this gateway binds the
loopback ``PATHWAY_SERVE_BACKEND_PORT`` instead — a mesh rollback then
parks in-flight requests at the frontend and replays them into
epoch+1's first windows rather than resetting connections. This module
adds the epoch-abort half (``abort_windows_for_rollback``: an
all-parked window commits nothing), stable request keys from the
frontend's ``X-Pathway-Request-Id``, and a circuit breaker on the
dispatch path whose open state answers DEGRADED from the last committed
snapshot (``brownout_answer`` + ``Degraded: true`` header) under
``PATHWAY_SERVE_BROWNOUT=1`` instead of shedding.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json as _json
import math
import os
import queue as _queue
import threading
import time as _time
from typing import Any, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import faults as _faults
from pathway_tpu.internals import memory as _memory
from pathway_tpu.internals.device import PLANE as _DEVICE, device_site
from pathway_tpu.internals.api import Json, Pointer, ref_scalar
from pathway_tpu.internals.monitoring import ServeMetrics
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read

# the dispatch circuit breaker and the brownout/shed verdicts are
# protocol decisions (parallel/protocol.py breaker_decide) shared with
# the serving model checker — see ISSUE 9
from pathway_tpu.parallel import protocol as _proto

device_site(
    "serve.window",
    # host-only site: the window commit launches no device work itself
    # (the downstream index site records its own device-bounded span),
    # so the model is honestly zero — registered anyway because every
    # begin() site must be in the registry (lint_gil pass 4)
    cost_model=lambda *a: (0.0, 0.0),
    dtypes=(),
    where="pathway_tpu/io/http/_server.py:_dispatch_window",
    description="serving gateway windowed commit (host-only record, "
                "device time honestly zero)",
)


def _env_knob(name: str, default: float) -> float:
    """Best-effort env read for the serving knobs; the registry
    (analysis/knobs.py) validates the same names at runtime startup, so
    a malformed value is rejected there with a rich KnobError — here it
    just falls back to the default."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


@dataclasses.dataclass
class EndpointDocumentation:
    summary: str | None = None
    description: str | None = None
    tags: Sequence[str] = ()
    method_types: Sequence[str] | None = None


def _openapi_type(dtype) -> dict:
    """pw dtype -> OpenAPI schema object (reference: _server.py:126-329
    generates the schema from the route's pw.Schema)."""
    if dtype is dt.INT:
        return {"type": "integer", "format": "int64"}
    if dtype is dt.FLOAT:
        return {"type": "number", "format": "double"}
    if dtype is dt.BOOL:
        return {"type": "boolean"}
    if dtype is dt.STR:
        return {"type": "string"}
    if dtype is dt.BYTES:
        return {"type": "string", "format": "byte"}
    if dtype is dt.JSON:
        return {}  # any JSON value
    name = getattr(dtype, "name", None) or str(dtype)
    if "Optional" in name:
        wrapped = getattr(dtype, "wrapped", None)
        if callable(wrapped):  # DType.wrapped is a method
            wrapped = wrapped()
        if wrapped is not None:
            inner = _openapi_type(wrapped)
            inner["nullable"] = True
            return inner
    if name.startswith(("List", "Tuple", "Array")):
        return {"type": "array", "items": {}}
    return {}


def _schema_request_body(schema: type[Schema]) -> dict:
    hints = schema.typehints()
    defaults = schema.default_values()
    props = {}
    required = []
    for col in schema.column_names():
        spec = _openapi_type(hints.get(col))
        if col in defaults:
            try:
                _json.dumps(defaults[col])
                spec["default"] = defaults[col]
            except TypeError:
                pass
        else:
            required.append(col)
        props[col] = spec
    body: dict[str, Any] = {"type": "object", "properties": props}
    if required:
        body["required"] = required
    return body


def _schema_query_params(schema: type[Schema]) -> list[dict]:
    hints = schema.typehints()
    defaults = schema.default_values()
    return [
        {
            "name": col,
            "in": "query",
            "required": col not in defaults,
            "schema": _openapi_type(hints.get(col)),
        }
        for col in schema.column_names()
    ]


def _validate_payload_types(schema: type[Schema], payload: dict) -> str | None:
    """Schema-driven request validation: wrong-typed fields are rejected
    with 400 before they enter the dataflow."""
    hints = schema.typehints()
    for col, value in payload.items():
        t = hints.get(col)
        if value is None or t is None:
            continue
        if t is dt.INT and not (
            isinstance(value, int) and not isinstance(value, bool)
        ):
            return f"field {col!r} must be an integer"
        if t is dt.FLOAT and not (
            isinstance(value, (int, float)) and not isinstance(value, bool)
        ):
            return f"field {col!r} must be a number"
        if t is dt.BOOL and not isinstance(value, bool):
            return f"field {col!r} must be a boolean"
        if t is dt.STR and not isinstance(value, str):
            return f"field {col!r} must be a string"
    return None


class PathwayWebserver:
    """Shared aiohttp server; routes register before pw.run() starts it
    (reference: _server.py:329)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 8080,
                 with_cors: bool = False, with_schema_endpoint: bool = True):
        self.public_host, self.public_port = host, port
        # epoch-survivable frontend mode (ISSUE 9): when the mesh
        # supervisor runs a ServingFrontend it owns the public listener
        # across rollbacks and hands this epoch's gateway a loopback
        # backend port via PATHWAY_SERVE_BACKEND_PORT — the pipeline
        # program keeps naming its public host:port unchanged. The
        # rewrite applies ONLY to the webserver whose configured port is
        # the frontend's public port (PATHWAY_SERVE_PUBLIC_PORT): a
        # program with a second webserver on another port must not have
        # both rebound onto one backend port (instant EADDRINUSE and a
        # rollback loop). Without the public-port var (standalone
        # frontends, older supervisors) every webserver rewrites, as
        # before.
        backend = os.environ.get("PATHWAY_SERVE_BACKEND_PORT")
        public = os.environ.get("PATHWAY_SERVE_PUBLIC_PORT")
        if backend:
            try:
                if not public or int(public) == port:
                    host, port = "127.0.0.1", int(backend)
            except ValueError:
                pass
        self.host = host
        self.port = port
        self.with_cors = with_cors
        self._routes: list[tuple[str, tuple[str, ...], Any, Any]] = []
        self._openapi: dict[str, Any] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._thread: threading.Thread | None = None
        self.with_schema_endpoint = with_schema_endpoint

    def _register_route(self, route, methods, handler, docs, schema=None) -> None:
        self._routes.append((route, methods, handler, docs))
        ops: dict[str, Any] = {}
        for m in methods:
            op: dict[str, Any] = {
                "summary": getattr(docs, "summary", None) or route,
                "responses": {
                    "200": {"description": "OK"},
                    "400": {"description": "Invalid request"},
                    "504": {"description": "Processing timeout"},
                },
            }
            desc = getattr(docs, "description", None)
            if desc:
                op["description"] = desc
            tags = list(getattr(docs, "tags", ()) or ())
            if tags:
                op["tags"] = tags
            if schema is not None:
                if m == "GET":
                    op["parameters"] = _schema_query_params(schema)
                else:
                    op["requestBody"] = {
                        "required": True,
                        "content": {
                            "application/json": {
                                "schema": _schema_request_body(schema)
                            }
                        },
                    }
            ops[m.lower()] = op
        self._openapi[route] = ops

    def openapi_document(self) -> dict:
        return {
            "openapi": "3.0.3",
            "info": {"title": "Pathway REST connector", "version": "1.0.0"},
            "servers": [{"url": f"http://{self.host}:{self.port}"}],
            "paths": self._openapi,
        }

    def _ensure_started(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)

    def _run(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        app = web.Application()
        for route, methods, handler, _docs in self._routes:
            for m in methods:
                app.router.add_route(m, route, handler)
        if self.with_schema_endpoint:
            async def schema_handler(request):
                return web.json_response(self.openapi_document())

            app.router.add_route("GET", "/_schema", schema_handler)
            app.router.add_route("GET", "/openapi.json", schema_handler)

        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        self._started.set()
        loop.run_forever()


class _PendingRequest:
    """One admitted request riding a batch window."""

    __slots__ = (
        "key", "values", "future", "admitted_at", "evicted",
        # Server-Timing stamps (PATHWAY_SERVE_TIMING=1; ISSUE 15
        # satellite): window-close, dispatch-start and response-resolve
        # perf_counter readings, so each response can decompose its own
        # latency into queue/window/dispatch/egress without a trace file
        "t_closed", "t_dispatch0", "t_resolved",
    )

    def __init__(self, key, values, future):
        self.key = key
        self.values = values
        self.future = future
        self.admitted_at = _time.perf_counter()
        self.evicted = False
        self.t_closed = None
        self.t_dispatch0 = None
        self.t_resolved = None


class RestServerSubject(ConnectorSubject):
    """Request-coalescing serving gateway over the python connector.

    Pipeline per request: admission (bounded; overflow shed with 503 +
    Retry-After) → dynamic batch window (closes on
    ``PATHWAY_SERVE_WINDOW_MS`` or ``PATHWAY_SERVE_MAX_BATCH``, whichever
    first) → a dispatch worker turns the window into upserts + ONE
    ``commit()`` (one dataflow timestamp, one fused device dispatch
    downstream) → the response table's batched subscribe callback
    resolves the whole window's futures in one cross-thread hop.
    Timed-out/disconnected requests are evicted from their window before
    dispatch; ``delete_completed_queries`` retractions are batched and
    ride the next window's commit instead of paying their own."""

    # serving requests are ephemeral: they must never enter the input
    # journal (io/_connector.py) — a rolled-back epoch's journaled
    # queries replayed at epoch+1 would double-dispatch the very
    # requests the frontend is already replaying with live futures
    _ephemeral = True

    def __init__(
        self,
        webserver: PathwayWebserver,
        route: str,
        methods: tuple[str, ...],
        schema: type[Schema],
        delete_completed_queries: bool,
        request_validator=None,
        documentation=None,
        window_ms: float | None = None,
        max_batch: int | None = None,
        queue_cap: int | None = None,
        timeout_s: float | None = None,
        workers: int | None = None,
        brownout_answer=None,
        breaker_threshold: int | None = None,
        breaker_cooldown_s: float | None = None,
    ):
        super().__init__()
        self.webserver = webserver
        self.route = route
        self.schema = schema
        self.delete_completed_queries = delete_completed_queries
        self.request_validator = request_validator
        self._tasks: dict[Pointer, asyncio.Future] = {}
        self._seq = 0
        self._lock = threading.Lock()
        # gateway knobs: explicit args win, then the serve/REST env knobs
        self.window_s = (
            window_ms
            if window_ms is not None
            else _env_knob("PATHWAY_SERVE_WINDOW_MS", 5.0)
        ) / 1000.0
        self.max_batch = int(
            max_batch
            if max_batch is not None
            else _env_knob("PATHWAY_SERVE_MAX_BATCH", 32)
        )
        self.queue_cap = int(
            queue_cap
            if queue_cap is not None
            else _env_knob("PATHWAY_SERVE_QUEUE_CAP", 2048)
        )
        self.timeout_s = (
            timeout_s
            if timeout_s is not None
            else _env_knob("PATHWAY_REST_TIMEOUT_S", 120.0)
        )
        self.workers = int(
            workers
            if workers is not None
            else _env_knob("PATHWAY_SERVE_WORKERS", 1)
        )
        # -- brownout + dispatch circuit breaker (ISSUE 9) ---------------
        # consecutive dispatch failures or request-deadline breaches
        # open the breaker; while open, requests answer DEGRADED from
        # the last committed snapshot (brownout_answer, Degraded: true)
        # under PATHWAY_SERVE_BROWNOUT=1 instead of shedding
        self.brownout_answer = brownout_answer
        self.brownout_enabled = str(
            os.environ.get("PATHWAY_SERVE_BROWNOUT", "0")
        ).strip().lower() in ("1", "true", "yes")
        self.breaker_threshold = int(
            breaker_threshold
            if breaker_threshold is not None
            else _env_knob("PATHWAY_SERVE_BREAKER_THRESHOLD", 5)
        )
        self.breaker_cooldown_s = (
            breaker_cooldown_s
            if breaker_cooldown_s is not None
            else _env_knob("PATHWAY_SERVE_BREAKER_COOLDOWN_S", 5.0)
        )
        self._breaker = "closed"
        self._breaker_failures = 0  # consecutive, dispatch + deadline
        self._breaker_opened_at = 0.0
        self._breaker_lock = threading.Lock()
        # X-Pathway-Request-Id is honored ONLY behind the
        # epoch-survivable frontend (loopback backend bind): on a public
        # gateway the header is client-spoofable — two requests naming
        # the same id would collide on one dataflow key and future slot
        self._frontend_mode = bool(
            os.environ.get("PATHWAY_SERVE_BACKEND_PORT")
        )
        # Server-Timing response header (ISSUE 15 satellite): per-request
        # queue/window/dispatch/egress ms, so a client-observed p50
        # decomposes without a trace file
        self._server_timing = str(
            os.environ.get("PATHWAY_SERVE_TIMING", "0")
        ).strip().lower() in ("1", "true", "yes")
        self.serve_metrics = ServeMetrics(route=route)
        # collecting window (event-loop thread only) + closed-window queue
        # drained by the dispatch workers
        self._window: list[_PendingRequest] = []
        self._window_timer = None
        self._windows_q: "_queue.Queue" = _queue.Queue()
        self._commit_lock = threading.Lock()
        self._inflight = 0  # admitted, unresponded (event-loop thread)
        # delete_completed_queries retractions batched onto later commits
        self._removals: list[tuple[Pointer, dict]] = []
        self._removals_lock = threading.Lock()
        self._removal_timer = None
        self._live: dict[Pointer, dict] = {}  # dispatched, not yet removed
        # rolling (t, n) response counts — the observed service rate that
        # sizes Retry-After when admission sheds
        self._recent_done: list[tuple[float, int]] = []
        # EWMA of the response drain rate (responses/s) — the honest
        # denominator for pace_retry_after when the memory ladder sheds
        # (ISSUE 19): the 10 s rolling qps reads near-zero exactly when
        # the governor has been throttling, which would tell clients to
        # come back immediately into a pressured engine
        self._done_rate_ewma = 0.0
        self._done_rate_t: float | None = None
        self._dispatchers: list[threading.Thread] = []
        self._gateway_up = False
        # device OOM -> serving brownout (ISSUE 17): an HBM-growth
        # refusal on the index is not a per-request failure streak, it
        # is an immediate capacity loss — trip the breaker open at once
        # so requests answer Degraded from the last committed snapshot
        # instead of piling onto a device that cannot grow
        from pathway_tpu.internals import device as _devsup

        self._oom_listener = lambda site: self._on_device_oom(site)
        _devsup.on_oom(self._oom_listener)
        webserver._register_route(
            route, methods, self._handle, documentation, schema=schema
        )

    # -- lifecycle --------------------------------------------------------
    def _ensure_gateway(self) -> None:
        # raced by the connector thread (run) and the event loop (first
        # request): the commit lock keeps worker startup single-shot
        if self._gateway_up:
            return
        with self._commit_lock:
            if self._gateway_up:
                return
            for i in range(max(1, self.workers)):
                t = threading.Thread(
                    target=self._dispatch_loop,
                    name=f"pw-serve-{self.route}-{i}",
                    daemon=True,
                )
                t.start()
                self._dispatchers.append(t)
            self._gateway_up = True

    def run(self):
        self.webserver._ensure_started()
        self._ensure_gateway()
        # stays alive for the whole pipeline; requests drive windows/commits
        self._shutdown = threading.Event()
        self._shutdown.wait()

    def on_stop(self):
        if hasattr(self, "_shutdown"):
            self._shutdown.set()
        if self._gateway_up:
            self._gateway_up = False
            for _ in self._dispatchers:
                self._windows_q.put(None)
            for t in self._dispatchers:
                t.join(timeout=2)
            self._dispatchers.clear()

    def abort_windows_for_rollback(self) -> int:
        """Epoch-abort half of request parking (engine/runtime.py calls
        this before the supervised exit): queued-but-undispatched windows
        are aborted — every member evicted, so a racing dispatch worker
        commits NOTHING for them (the all-parked-window invariant) — and
        their requests are left to the frontend, which holds the real
        client futures and replays them into epoch+1. Returns the number
        of windows aborted."""
        n = 0
        sentinels = 0
        while True:
            try:
                window = self._windows_q.get_nowait()
            except _queue.Empty:
                break
            if window is None:
                # a worker stop sentinel (on_stop racing the rollback):
                # swallowing it would leave a dispatch worker blocked in
                # get() past its join timeout — put it back
                sentinels += 1
                continue
            for p in window:
                p.evicted = True
            if window:
                n += 1
        for _ in range(sentinels):
            self._windows_q.put(None)
        # the collecting (not yet closed) window parks the same way —
        # and counts: in the low-traffic case it is often the ONLY
        # window, and the abort must still be observable
        if any(not p.evicted for p in self._window):
            n += 1
        for p in self._window:
            p.evicted = True
        if n:
            self.serve_metrics.on_windows_aborted(n)
        return n

    def _on_device_oom(self, site: str) -> None:
        """Flip the breaker straight to open on a device OOM: the
        failure streak heuristic is for transient dispatch errors, but
        refused HBM growth means every future write dispatch fails
        until the operator intervenes or load drops."""
        with self._breaker_lock:
            self._breaker = "open"
            self._breaker_failures = max(
                self._breaker_failures, self.breaker_threshold
            )
            self._breaker_opened_at = _time.monotonic()
        if self.serve_metrics.breaker_state != "open":
            self.serve_metrics.set_breaker("open")

    # -- dispatch circuit breaker (protocol.breaker_decide) ----------------
    def _breaker_now(self) -> str:
        """Current breaker verdict; transitions open -> half_open after
        the cooldown so ONE probe window can close it again."""
        with self._breaker_lock:
            state = _proto.breaker_decide(
                self._breaker,
                self._breaker_failures,
                self.breaker_threshold,
                _time.monotonic() - self._breaker_opened_at,
                self.breaker_cooldown_s,
            )
            self._breaker = state
        if self.serve_metrics.breaker_state != state:
            self.serve_metrics.set_breaker(state)
        return state

    def _breaker_record(self, ok: bool) -> None:
        with self._breaker_lock:
            if ok:
                self._breaker_failures = 0
                self._breaker = "closed"
            elif self.breaker_threshold > 0:
                self._breaker_failures += 1
                if self._breaker != "closed":
                    # a failing half_open probe (or a failure while
                    # already open) re-arms the full cooldown
                    self._breaker = "open"
                    self._breaker_opened_at = _time.monotonic()
                elif _proto.breaker_decide(
                    "closed",
                    self._breaker_failures,
                    self.breaker_threshold,
                    0.0,
                    self.breaker_cooldown_s,
                ) == "open":
                    self._breaker = "open"
                    self._breaker_opened_at = _time.monotonic()
        state = self._breaker
        if self.serve_metrics.breaker_state != state:
            self.serve_metrics.set_breaker(state)

    # -- request path (webserver event loop) ------------------------------
    async def _handle(self, request):
        from aiohttp import web

        cols = self.schema.column_names()
        defaults = self.schema.default_values()
        if request.method == "GET":
            # query-string values are strings — coerce to the schema
            # types; a value that does not parse as its typed column is a
            # client error, reported with the offending field (it must
            # never enter the dataflow as a raw string in a typed column)
            hints = self.schema.typehints()
            payload = {}
            for key, value in request.query.items():
                t = hints.get(key)
                try:
                    if t is dt.INT:
                        value = int(value)
                    elif t is dt.FLOAT:
                        value = float(value)
                    elif t is dt.BOOL:
                        low = value.lower()
                        if low in ("1", "true", "yes"):
                            value = True
                        elif low in ("0", "false", "no"):
                            value = False
                        else:
                            raise ValueError(value)
                except (TypeError, ValueError):
                    return web.json_response(
                        {
                            "error": (
                                f"field {key!r} must be "
                                f"{_coercion_target(t)}, got {value!r}"
                            )
                        },
                        status=400,
                    )
                payload[key] = value
        else:
            try:
                payload = await request.json()
            except Exception:
                payload = {}
        if self.request_validator is not None:
            try:
                err = self.request_validator(payload)
                if err is not None:
                    return web.json_response({"error": str(err)}, status=400)
            except Exception as e:
                return web.json_response({"error": str(e)}, status=400)
        missing = [
            c for c in cols if c not in payload and c not in defaults
        ]
        if missing:
            return web.json_response(
                {"error": f"missing fields: {missing}"}, status=400
            )
        if request.method != "GET":
            type_err = _validate_payload_types(self.schema, payload)
            if type_err is not None:
                return web.json_response({"error": type_err}, status=400)
        values = {c: payload.get(c, defaults.get(c)) for c in cols}
        # JSON-typed columns wrap payload fragments
        for c, typ in self.schema.typehints().items():
            if typ is dt.JSON and values.get(c) is not None and not isinstance(values[c], Json):
                values[c] = Json(values[c])

        metrics = self.serve_metrics
        metrics.on_request()
        # dispatch circuit breaker (ISSUE 9): consecutive dispatch
        # failures / deadline breaches opened it — answer DEGRADED from
        # the last committed snapshot (no update-fold, no device
        # dispatch) instead of shedding when brownout is on; cooldown
        # half-opens it so one probe window can close it again.
        # The memory-governance ladder (ISSUE 19) feeds the same path:
        # at "brownout"/"abort" the runtime is shedding load to stay
        # inside its budget, so serving answers degraded (or sheds with
        # a drain-rate-honest Retry-After) instead of queuing new work
        # into a pressured engine.
        mem_state = _memory.ladder_state()
        mem_degraded = mem_state in ("brownout", "abort")
        if self.breaker_threshold > 0 or mem_degraded:
            breaker = (
                self._breaker_now()
                if self.breaker_threshold > 0
                else "closed"
            )
            if breaker == "open" or mem_degraded:
                if self.brownout_enabled and self.brownout_answer is not None:
                    try:
                        result = await asyncio.get_event_loop()\
                            .run_in_executor(
                                None, self.brownout_answer, dict(values)
                            )
                    except Exception as exc:
                        return web.json_response(
                            {"error": f"brownout answer failed: {exc}"},
                            status=503,
                            headers={
                                "Retry-After": str(
                                    self._retry_after_s(mem_state)
                                )
                            },
                        )
                    metrics.on_brownout()
                    return web.json_response(
                        result, headers={"Degraded": "true"}
                    )
                metrics.on_shed()
                return web.json_response(
                    {"error": (
                        "memory pressure, retry later"
                        if mem_degraded
                        else "device dispatch degraded, retry later"
                    )},
                    status=503,
                    headers={
                        "Retry-After": str(
                            self._retry_after_s(mem_state)
                            if mem_degraded
                            else _proto.serve_retry_after(
                                self.breaker_cooldown_s
                            )
                        )
                    },
                )
        # admission control: bounded in-flight backlog; overflow is shed
        # rather than queued into latency the client will time out on
        # anyway (the device is behind the N/C capacity line)
        if self._inflight >= self.queue_cap:
            metrics.on_shed()
            return web.json_response(
                {"error": "overloaded, retry later"},
                status=503,
                headers={"Retry-After": str(self._retry_after_s(mem_state))},
            )
        # the epoch-survivable frontend stamps its own request id so a
        # request REPLAYED into epoch+1 keys the same dataflow row — an
        # upsert, idempotent even if the dead epoch's row survived in a
        # restored snapshot (the park/replay exactly-once boundary).
        # Only trusted in frontend mode: the loopback bind means the
        # header can only come from the frontend itself.
        rid = (
            request.headers.get("X-Pathway-Request-Id")
            if self._frontend_mode
            else None
        )
        if rid is not None:
            key = ref_scalar("rest", self.route, "rid", rid)
        else:
            with self._lock:
                self._seq += 1
                key = ref_scalar("rest", self.route, self._seq)
        future: asyncio.Future = asyncio.get_event_loop().create_future()
        self._tasks[key] = future
        pending = _PendingRequest(key, values, future)
        if self._server_timing:
            # the response fan-in only sees the future — hang the
            # pending off it so the resolve stamp lands per request
            future._pw_pending = pending
        self._inflight += 1
        self._join_window(pending)
        try:
            result = await asyncio.wait_for(future, timeout=self.timeout_s)
        except asyncio.TimeoutError:
            # evicted: if the window has not dispatched yet, the request
            # vanishes before it can occupy a batch slot / device dispatch
            pending.evicted = True
            metrics.on_timeout()
            # a deadline breach is a breaker signal: a wedged device
            # path shows up as timeouts long before dispatch exceptions
            self._breaker_record(False)
            return web.json_response({"error": "timeout"}, status=504)
        except asyncio.CancelledError:
            # client disconnected: same eviction semantics as a timeout
            pending.evicted = True
            raise
        finally:
            self._inflight -= 1
            self._tasks.pop(key, None)
        metrics.on_latency_ms(
            (_time.perf_counter() - pending.admitted_at) * 1000.0
        )
        if self._server_timing:
            return web.json_response(
                result,
                headers={
                    "Server-Timing": _server_timing_header(pending)
                },
            )
        return web.json_response(result)

    def _retry_after_s(self, mem_state: str = "ok") -> int:
        """Seconds until the current backlog drains at the observed
        service rate — the Retry-After a shed client should honor.
        During a memory-ladder episode (``pacing``/``brownout``/
        ``abort``) the horizon comes from the SAME ``pace_retry_after``
        transition the pacing model checks: in-flight backlog over the
        EWMA drain rate — honest exactly when the rolling qps reads
        near-zero because the governor has been throttling."""
        now = _time.monotonic()
        with self._lock:  # _resolve_batch appends from the engine thread
            self._recent_done = [
                (t, n) for t, n in self._recent_done if now - t <= 10.0
            ]
            qps = sum(n for _, n in self._recent_done) / 10.0
            ewma = self._done_rate_ewma
        if mem_state not in ("", "ok"):
            return max(
                1,
                math.ceil(
                    _proto.pace_retry_after(max(self._inflight, 1), ewma)
                ),
            )
        if qps <= 0:
            return 1
        return max(1, min(60, math.ceil(self._inflight / qps)))

    # -- batch window (event-loop thread) ---------------------------------
    def _join_window(self, pending: _PendingRequest) -> None:
        self._ensure_gateway()  # first request may beat the run() thread
        self._window.append(pending)
        if self.window_s <= 0 or len(self._window) >= self.max_batch:
            self._close_window(self._window)
            return
        if len(self._window) == 1:
            self._window_timer = asyncio.get_event_loop().call_later(
                self.window_s, self._close_window, self._window
            )

    def _close_window(self, window: list) -> None:
        if window is not self._window:
            return  # already closed by the max-batch trigger
        if self._window_timer is not None:
            self._window_timer.cancel()
            self._window_timer = None
        self._window = []
        if self._server_timing:
            now = _time.perf_counter()
            for p in window:
                p.t_closed = now
        self._windows_q.put(window)

    # -- dispatch workers (threads) ---------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            window = self._windows_q.get()
            if window is None:
                return
            try:
                self._dispatch_window(window)
            except Exception:
                # consecutive dispatch failures open the circuit breaker
                self._breaker_record(False)
                # a failing dispatch must fail the window's futures, not
                # kill the worker (clients would hang to their timeouts)
                loop = self.webserver._loop
                if loop is not None:
                    futures = [
                        p.future for p in window if not p.evicted
                    ]

                    def _fail(futures=futures):
                        for f in futures:
                            if not f.done():
                                f.set_exception(
                                    RuntimeError("gateway dispatch failed")
                                )

                    loop.call_soon_threadsafe(_fail)

    def _dispatch_window(self, window: list) -> None:
        """The windowed commit: every live request of the window upserts,
        batched completed-query retractions piggyback, then ONE commit —
        the whole window is one dataflow timestamp. The lock keeps
        concurrent workers' windows atomic (interleaved upserts would
        merge two windows into one flush)."""
        with self._commit_lock:
            live = [p for p in window if not p.evicted]
            with self._removals_lock:
                removals, self._removals = self._removals, []
            if not live and not removals:
                return
            # chaos slot: kill with the window formed but its upserts
            # not yet committed (the all-parked-window invariant: this
            # window must commit NOTHING at epoch+1 unless replayed)
            _faults.fault_point("serve.dispatch", phase="window")
            # device plane (ISSUE 15): the gateway's fused window
            # dispatch as a timed record — one commit = one downstream
            # device dispatch. Host-only here (the JAX launch happens in
            # the engine's step, where the index site records its own
            # device-bounded span), so no output to block on: the record
            # carries the window's wall span and the dispatch-queue
            # depth, and its device time is honestly zero.
            dev = _DEVICE.begin("serve.window") if _DEVICE.on else None
            if self._server_timing:
                now = _time.perf_counter()
                for p in live:
                    p.t_dispatch0 = now
            try:
                for p in live:
                    if self.delete_completed_queries:
                        # tracked only for the later retraction — an
                        # unconditional record would grow per request
                        # forever on keep-queries servers
                        self._live[p.key] = p.values
                    self._upsert(p.key, p.values)
                for key, values in removals:
                    self._remove(key, values)
                self.commit()
            except BaseException:
                if dev is not None:
                    # close the record on the failure path too — an
                    # abandoned record would leak dispatch-queue depth
                    _DEVICE.end(dev, None, block=False)
                if removals:
                    # the swapped-out retractions must not vanish with
                    # the failed dispatch — re-queue them for the next
                    # window (their keys already left _live)
                    with self._removals_lock:
                        self._removals[:0] = removals
                raise
            # chaos slot: window committed in-memory, responses not yet
            # delivered — the frontend must replay (the rollback cut
            # discards this commit) without double-answering anyone
            _faults.fault_point("serve.dispatch", phase="committed")
            if dev is not None:
                _DEVICE.end(dev, None, block=False)
            if live:
                self.serve_metrics.on_window(len(live))

    # -- response fan-in (engine output thread) ---------------------------
    def _resolve_batch(self, resolved: list[tuple[Pointer, Any]]) -> None:
        """One delivered response batch (= one window downstream):
        resolve every future in a single cross-thread hop and queue the
        completed rows' retractions onto the next commit."""
        # breaker success is RESPONSE DELIVERY, not window commit: a
        # wedged device path keeps committing windows in-memory while
        # answers never arrive — commits must not reset the
        # deadline-breach streak or the breaker could never open for
        # exactly the scenario it exists for
        self._breaker_record(True)
        loop = self.webserver._loop
        t_resolved = _time.perf_counter() if self._server_timing else None
        futures = []
        for key, result in resolved:
            future = self._tasks.get(key)
            if future is not None:
                futures.append((future, result))
                if t_resolved is not None:
                    p = getattr(future, "_pw_pending", None)
                    if p is not None:
                        p.t_resolved = t_resolved
            if self.delete_completed_queries:
                values = self._live.pop(key, None)
                if values is not None:
                    with self._removals_lock:
                        self._removals.append((key, values))
        with self._lock:  # _retry_after_s prunes from the event loop
            now = _time.monotonic()
            self._recent_done.append((now, len(resolved)))
            del self._recent_done[:-256]
            if self._done_rate_t is not None:
                dt_s = max(now - self._done_rate_t, 1e-3)
                inst = len(resolved) / dt_s
                self._done_rate_ewma += 0.3 * (inst - self._done_rate_ewma)
            self._done_rate_t = now
        if loop is not None and futures:
            def _set():
                for future, result in futures:
                    if not future.done():
                        future.set_result(result)

            loop.call_soon_threadsafe(_set)
        if self.delete_completed_queries and self._removals:
            # under load the retractions ride the next window's commit;
            # when traffic pauses, a lazy flush (4 windows, min 50 ms)
            # clears the tail without paying a commit per response batch
            if loop is not None and self._removal_timer is None:
                delay = max(4 * self.window_s, 0.05)

                def _arm():
                    self._removal_timer = loop.call_later(
                        delay, self._flush_removals
                    )

                loop.call_soon_threadsafe(_arm)

    def _flush_removals(self) -> None:
        self._removal_timer = None
        self._windows_q.put([])  # removal-only window

    def _resolve(self, key: Pointer, value: Any) -> None:
        """Single-row compatibility shim over the batched fan-in."""
        self._resolve_batch([(key, value)])


def _server_timing_header(p: _PendingRequest) -> str:
    """RFC-style ``Server-Timing`` value decomposing one response's
    latency (PATHWAY_SERVE_TIMING=1; ISSUE 15 satellite):

    * ``queue``    — admission to window close (batch-window wait);
    * ``window``   — window close to dispatch start (worker pickup);
    * ``dispatch`` — the windowed commit through the dataflow to the
      response batch resolving (the engine + device share);
    * ``egress``   — future resolve to response serialization.

    Missing stamps (a replayed/brownout path) collapse to 0 rather than
    lying with negative durations."""
    now = _time.perf_counter()
    t_admit = p.admitted_at
    t_closed = p.t_closed if p.t_closed is not None else t_admit
    t_d0 = p.t_dispatch0 if p.t_dispatch0 is not None else t_closed
    t_res = p.t_resolved if p.t_resolved is not None else now
    legs = (
        ("queue", t_closed - t_admit),
        ("window", t_d0 - t_closed),
        ("dispatch", t_res - t_d0),
        ("egress", now - t_res),
    )
    return ", ".join(
        f"{name};dur={max(0.0, s) * 1000.0:.2f}" for name, s in legs
    )


def _coercion_target(t) -> str:
    if t is dt.INT:
        return "an integer"
    if t is dt.FLOAT:
        return "a number"
    return "a boolean (1/0/true/false/yes/no)"


def rest_connector(
    host: str | None = None,
    port: int | None = None,
    *,
    webserver: PathwayWebserver | None = None,
    route: str = "/",
    schema: type[Schema] | None = None,
    methods: Sequence[str] = ("POST",),
    autocommit_duration_ms: int | None = None,
    keep_queries: bool | None = None,
    delete_completed_queries: bool | None = None,
    request_validator=None,
    documentation: EndpointDocumentation | None = None,
    window_ms: float | None = None,
    max_batch: int | None = None,
    queue_cap: int | None = None,
    timeout_s: float | None = None,
    workers: int | None = None,
    brownout_answer=None,
    breaker_threshold: int | None = None,
    breaker_cooldown_s: float | None = None,
):
    """Returns (queries_table, response_writer) (reference: _server.py:624).

    response_writer(table) — table keyed like queries with a `result`
    column; writing it resolves the matching pending HTTP requests, one
    batched callback per delivered window.

    The gateway coalesces requests into batch windows (``window_ms`` /
    ``max_batch``, defaulting to the registered serve knobs) and
    commits one dataflow timestamp per window, so
    ``autocommit_duration_ms`` defaults to None — the window IS the
    commit cadence, and a timer flush racing a window's upserts would
    split one window across two timestamps.
    """
    if webserver is None:
        webserver = PathwayWebserver(
            host=host or "0.0.0.0", port=port or 8080
        )
    if delete_completed_queries is None:
        delete_completed_queries = (
            not keep_queries if keep_queries is not None else False
        )
    if schema is None:
        raise ValueError("rest_connector requires a schema")

    subject = RestServerSubject(
        webserver,
        route,
        tuple(m.upper() for m in methods),
        schema,
        delete_completed_queries,
        request_validator,
        documentation,
        window_ms=window_ms,
        max_batch=max_batch,
        queue_cap=queue_cap,
        timeout_s=timeout_s,
        workers=workers,
        brownout_answer=brownout_answer,
        breaker_threshold=breaker_threshold,
        breaker_cooldown_s=breaker_cooldown_s,
    )
    queries = python_read(
        subject, schema=schema, autocommit_duration_ms=autocommit_duration_ms
    )

    def response_writer(response_table) -> None:
        cols = tuple(response_table.column_names())
        try:
            result_idx = cols.index("result")
        except ValueError:
            result_idx = None

        def on_batch(time_, deltas):
            # one callback per delivered batch (= one window): the whole
            # window's futures resolve in a single cross-thread hop —
            # the batched-subscribe egress, not a per-row callback
            resolved = []
            for key, row, diff in deltas:
                if diff <= 0:
                    continue
                if result_idx is not None:
                    result = row[result_idx]
                else:
                    result = dict(zip(cols, row))
                if isinstance(result, Json):
                    result = result.value
                resolved.append((key, result))
            if resolved:
                subject._resolve_batch(resolved)

        def lower(ctx):
            ctx.scope.output(
                ctx.engine_table(response_table), on_batch=on_batch
            )

        G.add_operator(
            [response_table], [], lower, "rest_response", is_output=True
        )

    return queries, response_writer
