"""REST server connector (reference: python/pathway/io/http/_server.py —
PathwayWebserver :329, rest_connector :624, RestServerSubject :525).

One aiohttp application (owned by a PathwayWebserver) serves any number of
routes; each route is a connector: an incoming request becomes a row in the
queries table, the caller's response future resolves when the paired
response-writer table produces the row with the same id."""

from __future__ import annotations

import asyncio
import dataclasses
import json as _json
import threading
from typing import Any, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import Json, Pointer, ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read


@dataclasses.dataclass
class EndpointDocumentation:
    summary: str | None = None
    description: str | None = None
    tags: Sequence[str] = ()
    method_types: Sequence[str] | None = None


def _openapi_type(dtype) -> dict:
    """pw dtype -> OpenAPI schema object (reference: _server.py:126-329
    generates the schema from the route's pw.Schema)."""
    if dtype is dt.INT:
        return {"type": "integer", "format": "int64"}
    if dtype is dt.FLOAT:
        return {"type": "number", "format": "double"}
    if dtype is dt.BOOL:
        return {"type": "boolean"}
    if dtype is dt.STR:
        return {"type": "string"}
    if dtype is dt.BYTES:
        return {"type": "string", "format": "byte"}
    if dtype is dt.JSON:
        return {}  # any JSON value
    name = getattr(dtype, "name", None) or str(dtype)
    if "Optional" in name:
        wrapped = getattr(dtype, "wrapped", None)
        if callable(wrapped):  # DType.wrapped is a method
            wrapped = wrapped()
        if wrapped is not None:
            inner = _openapi_type(wrapped)
            inner["nullable"] = True
            return inner
    if name.startswith(("List", "Tuple", "Array")):
        return {"type": "array", "items": {}}
    return {}


def _schema_request_body(schema: type[Schema]) -> dict:
    hints = schema.typehints()
    defaults = schema.default_values()
    props = {}
    required = []
    for col in schema.column_names():
        spec = _openapi_type(hints.get(col))
        if col in defaults:
            try:
                _json.dumps(defaults[col])
                spec["default"] = defaults[col]
            except TypeError:
                pass
        else:
            required.append(col)
        props[col] = spec
    body: dict[str, Any] = {"type": "object", "properties": props}
    if required:
        body["required"] = required
    return body


def _schema_query_params(schema: type[Schema]) -> list[dict]:
    hints = schema.typehints()
    defaults = schema.default_values()
    return [
        {
            "name": col,
            "in": "query",
            "required": col not in defaults,
            "schema": _openapi_type(hints.get(col)),
        }
        for col in schema.column_names()
    ]


def _validate_payload_types(schema: type[Schema], payload: dict) -> str | None:
    """Schema-driven request validation: wrong-typed fields are rejected
    with 400 before they enter the dataflow."""
    hints = schema.typehints()
    for col, value in payload.items():
        t = hints.get(col)
        if value is None or t is None:
            continue
        if t is dt.INT and not (
            isinstance(value, int) and not isinstance(value, bool)
        ):
            return f"field {col!r} must be an integer"
        if t is dt.FLOAT and not (
            isinstance(value, (int, float)) and not isinstance(value, bool)
        ):
            return f"field {col!r} must be a number"
        if t is dt.BOOL and not isinstance(value, bool):
            return f"field {col!r} must be a boolean"
        if t is dt.STR and not isinstance(value, str):
            return f"field {col!r} must be a string"
    return None


class PathwayWebserver:
    """Shared aiohttp server; routes register before pw.run() starts it
    (reference: _server.py:329)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 8080,
                 with_cors: bool = False, with_schema_endpoint: bool = True):
        self.host = host
        self.port = port
        self.with_cors = with_cors
        self._routes: list[tuple[str, tuple[str, ...], Any, Any]] = []
        self._openapi: dict[str, Any] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._thread: threading.Thread | None = None
        self.with_schema_endpoint = with_schema_endpoint

    def _register_route(self, route, methods, handler, docs, schema=None) -> None:
        self._routes.append((route, methods, handler, docs))
        ops: dict[str, Any] = {}
        for m in methods:
            op: dict[str, Any] = {
                "summary": getattr(docs, "summary", None) or route,
                "responses": {
                    "200": {"description": "OK"},
                    "400": {"description": "Invalid request"},
                    "504": {"description": "Processing timeout"},
                },
            }
            desc = getattr(docs, "description", None)
            if desc:
                op["description"] = desc
            tags = list(getattr(docs, "tags", ()) or ())
            if tags:
                op["tags"] = tags
            if schema is not None:
                if m == "GET":
                    op["parameters"] = _schema_query_params(schema)
                else:
                    op["requestBody"] = {
                        "required": True,
                        "content": {
                            "application/json": {
                                "schema": _schema_request_body(schema)
                            }
                        },
                    }
            ops[m.lower()] = op
        self._openapi[route] = ops

    def openapi_document(self) -> dict:
        return {
            "openapi": "3.0.3",
            "info": {"title": "Pathway REST connector", "version": "1.0.0"},
            "servers": [{"url": f"http://{self.host}:{self.port}"}],
            "paths": self._openapi,
        }

    def _ensure_started(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)

    def _run(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        app = web.Application()
        for route, methods, handler, _docs in self._routes:
            for m in methods:
                app.router.add_route(m, route, handler)
        if self.with_schema_endpoint:
            async def schema_handler(request):
                return web.json_response(self.openapi_document())

            app.router.add_route("GET", "/_schema", schema_handler)
            app.router.add_route("GET", "/openapi.json", schema_handler)

        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        self._started.set()
        loop.run_forever()


class RestServerSubject(ConnectorSubject):
    def __init__(
        self,
        webserver: PathwayWebserver,
        route: str,
        methods: tuple[str, ...],
        schema: type[Schema],
        delete_completed_queries: bool,
        request_validator=None,
        documentation=None,
    ):
        super().__init__()
        self.webserver = webserver
        self.route = route
        self.schema = schema
        self.delete_completed_queries = delete_completed_queries
        self.request_validator = request_validator
        self._tasks: dict[Pointer, asyncio.Future] = {}
        self._seq = 0
        self._lock = threading.Lock()
        webserver._register_route(
            route, methods, self._handle, documentation, schema=schema
        )

    def run(self):
        self.webserver._ensure_started()
        # stays alive for the whole pipeline; requests drive next()/commit
        self._shutdown = threading.Event()
        self._shutdown.wait()

    def on_stop(self):
        if hasattr(self, "_shutdown"):
            self._shutdown.set()

    async def _handle(self, request):
        from aiohttp import web

        cols = self.schema.column_names()
        defaults = self.schema.default_values()
        if request.method == "GET":
            # query-string values are strings — coerce to the schema types
            hints = self.schema.typehints()
            payload = {}
            for key, value in request.query.items():
                t = hints.get(key)
                try:
                    if t is dt.INT:
                        value = int(value)
                    elif t is dt.FLOAT:
                        value = float(value)
                    elif t is dt.BOOL:
                        value = value.lower() in ("1", "true", "yes")
                except (TypeError, ValueError):
                    pass
                payload[key] = value
        else:
            try:
                payload = await request.json()
            except Exception:
                payload = {}
        if self.request_validator is not None:
            try:
                err = self.request_validator(payload)
                if err is not None:
                    return web.json_response({"error": str(err)}, status=400)
            except Exception as e:
                return web.json_response({"error": str(e)}, status=400)
        missing = [
            c for c in cols if c not in payload and c not in defaults
        ]
        if missing:
            return web.json_response(
                {"error": f"missing fields: {missing}"}, status=400
            )
        if request.method != "GET":
            type_err = _validate_payload_types(self.schema, payload)
            if type_err is not None:
                return web.json_response({"error": type_err}, status=400)
        values = {c: payload.get(c, defaults.get(c)) for c in cols}
        # JSON-typed columns wrap payload fragments
        for c, typ in self.schema.typehints().items():
            if typ is dt.JSON and values.get(c) is not None and not isinstance(values[c], Json):
                values[c] = Json(values[c])
        with self._lock:
            self._seq += 1
            key = ref_scalar("rest", self.route, self._seq)
        future: asyncio.Future = asyncio.get_event_loop().create_future()
        self._tasks[key] = future
        self._upsert(key, values)
        self.commit()
        try:
            result = await asyncio.wait_for(future, timeout=120)
        except asyncio.TimeoutError:
            return web.json_response({"error": "timeout"}, status=504)
        finally:
            self._tasks.pop(key, None)
            if self.delete_completed_queries:
                self._remove(key, values)
                self.commit()
        return web.json_response(result)

    def _resolve(self, key: Pointer, value: Any) -> None:
        future = self._tasks.get(key)
        loop = self.webserver._loop
        if future is not None and loop is not None:
            def _set():
                if not future.done():
                    future.set_result(value)

            loop.call_soon_threadsafe(_set)


def rest_connector(
    host: str | None = None,
    port: int | None = None,
    *,
    webserver: PathwayWebserver | None = None,
    route: str = "/",
    schema: type[Schema] | None = None,
    methods: Sequence[str] = ("POST",),
    autocommit_duration_ms: int | None = 1500,
    keep_queries: bool | None = None,
    delete_completed_queries: bool | None = None,
    request_validator=None,
    documentation: EndpointDocumentation | None = None,
):
    """Returns (queries_table, response_writer) (reference: _server.py:624).

    response_writer(table) — table keyed like queries with a `result`
    column; writing it resolves the matching pending HTTP request.
    """
    if webserver is None:
        webserver = PathwayWebserver(
            host=host or "0.0.0.0", port=port or 8080
        )
    if delete_completed_queries is None:
        delete_completed_queries = (
            not keep_queries if keep_queries is not None else False
        )
    if schema is None:
        raise ValueError("rest_connector requires a schema")

    subject = RestServerSubject(
        webserver,
        route,
        tuple(m.upper() for m in methods),
        schema,
        delete_completed_queries,
        request_validator,
        documentation,
    )
    queries = python_read(
        subject, schema=schema, autocommit_duration_ms=autocommit_duration_ms
    )

    def response_writer(response_table) -> None:
        cols = response_table.column_names()

        def on_change(key, row, time_, diff):
            if diff <= 0:
                return
            data = dict(zip(cols, row))
            result = data.get("result", data)
            if isinstance(result, Json):
                result = result.value
            subject._resolve(key, result)

        def lower(ctx):
            ctx.scope.output(
                ctx.engine_table(response_table), on_change=on_change
            )

        G.add_operator(
            [response_table], [], lower, "rest_response", is_output=True
        )

    return queries, response_writer
