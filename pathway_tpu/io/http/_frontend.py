"""Epoch-survivable serving frontend (ISSUE 9 tentpole).

PR 4's mesh rollback makes a rank failure exit the whole process
(``MESH_RESTART_EXIT_CODE``), so PR 6's in-rank gateway used to drop its
listener, its admission queue and every in-flight window mid-dispatch —
a single flaky rank became user-visible connection resets, exactly the
failure class coordinated rollback is supposed to hide from clients.

This module moves the HTTP listener and the admission queue OUT of the
epoch-scoped runtime into a supervisor-side frontend that survives the
rollback:

* the frontend owns the public ``host:port`` across epochs; the rank's
  gateway binds a loopback **backend port** instead
  (``PATHWAY_SERVE_BACKEND_PORT``, set by the supervisor) and the
  frontend proxies keep-alive HTTP/1.1 to it;
* on backend loss (``MeshPeerFailure`` → epoch abort → the rank's
  listener dies) every admitted, unresponded request is **parked** —
  its client connection and future are retained — and new arrivals park
  too, up to ``PATHWAY_SERVE_PARK_BUDGET``;
* when the supervisor's epoch+1 gateway re-binds the backend port, the
  parked set **replays** into its first batch windows with deadline
  accounting: requests whose ``PATHWAY_REST_TIMEOUT_S`` budget expired
  while parked get 503 + Retry-After sized by the OBSERVED restart
  time, never a dropped connection;
* readiness (serving / draining / recovering) is exposed on
  ``/healthz`` and park/replay/expiry counters plus an epoch-handoff
  latency histogram on ``/metrics``.

Every park/replay decision is a pure transition in
``parallel/protocol.py`` (``serve_frontend_state`` / ``serve_admit`` /
``serve_park`` / ``serve_replay_split`` / ``serve_retry_after``) that
``analysis/meshcheck.py check_serving`` exhaustively model-checks — no
admitted request is lost or answered twice across a rollback, by the
same anti-drift construction the mesh verifier uses.

Exactly-once boundary: a request whose response was fully received from
the backend is TERMINAL and never replays (``serve_park`` filters on
the responded set); a request cut mid-dispatch replays into epoch+1,
which is safe because the dead epoch's serving state was discarded at
the rollback cut — the gateway keys rows by the frontend's
``X-Pathway-Request-Id``, so even a surviving duplicate upsert is
idempotent at the dataflow level.

This module is deliberately **stdlib-only** (asyncio + http framing by
hand): the mesh supervisor loads it by file path exactly like
``protocol.py``, so stdlib-light drivers (``scripts/fault_matrix.py``,
``scripts/serve_chaos_smoke.py``) never touch the package __init__s.
"""

from __future__ import annotations

import asyncio
import json as _json
import os
import threading
import time as _time
from typing import Any

if __package__:
    from pathway_tpu.internals import faults as _faults
    from pathway_tpu.parallel import protocol as _proto
else:  # pragma: no cover - file-path load (supervisor / chaos drivers)
    import importlib.util as _ilu

    def _load_by_path(name, *parts):
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
            *parts,
        )
        spec = _ilu.spec_from_file_location(name, path)
        mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _proto = _load_by_path("_pw_mesh_protocol", "parallel", "protocol.py")
    _faults = _load_by_path("_pw_faults", "internals", "faults.py")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# epoch-handoff latency histogram edges (seconds): spans loopback
# respawns (sub-second) up to multi-host rollbacks. Kept here (not
# monitoring.py) because this module must stay stdlib-only.
HANDOFF_BUCKETS_S = (0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0)


class FrontendMetrics:
    """Minimal OpenMetrics surface for the frontend process — the
    serving-through-rollback counters named in ISSUE 9 plus the
    epoch-handoff histogram. Same family names the dashboards expect;
    this renders in the SUPERVISOR process, the gateway's ServeMetrics
    in the rank process."""

    def __init__(self):
        self.admitted = 0
        self.shed = 0
        self.parked = 0
        self.replayed = 0
        self.deadline_expired = 0
        self.responses = 0
        self.timeouts = 0
        self.backend_losses = 0
        self.handoff_counts = [0] * (len(HANDOFF_BUCKETS_S) + 1)
        self.handoff_sum = 0.0
        self.handoff_total = 0

    def on_handoff_s(self, s: float) -> None:
        self.handoff_total += 1
        self.handoff_sum += s
        for i, edge in enumerate(HANDOFF_BUCKETS_S):
            if s <= edge:
                self.handoff_counts[i] += 1
                return
        self.handoff_counts[-1] += 1

    def render(self) -> str:
        lines = []
        for metric, val in (
            ("serve_frontend_requests_total", self.admitted),
            ("serve_frontend_shed_total", self.shed),
            ("serve_parked_total", self.parked),
            ("serve_replayed_total", self.replayed),
            ("serve_deadline_expired_total", self.deadline_expired),
            ("serve_frontend_responses_total", self.responses),
            ("serve_frontend_timeouts_total", self.timeouts),
            ("serve_backend_losses_total", self.backend_losses),
        ):
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {val}")
        lines.append("# TYPE serve_epoch_handoff_seconds histogram")
        cum = 0
        for edge, n in zip(HANDOFF_BUCKETS_S, self.handoff_counts):
            cum += n
            lines.append(
                f'serve_epoch_handoff_seconds_bucket{{le="{edge:g}"}} {cum}'
            )
        cum += self.handoff_counts[-1]
        lines.append(
            f'serve_epoch_handoff_seconds_bucket{{le="+Inf"}} {cum}'
        )
        lines.append(
            f"serve_epoch_handoff_seconds_sum {self.handoff_sum:.6g}"
        )
        lines.append(
            f"serve_epoch_handoff_seconds_count {self.handoff_total}"
        )
        return "\n".join(lines) + "\n"


class _BackendDown(ConnectionError):
    """The backend epoch is gone mid-roundtrip: park and replay.
    ``stale`` marks a failure on a REUSED kept-alive socket before any
    response byte — the gateway's idle keep-alive close racing our
    request (the same provably-unprocessed race KeepAliveSession
    retries), NOT evidence the backend died: retry on a fresh
    connection before declaring a loss."""

    def __init__(self, message: str, stale: bool = False):
        super().__init__(message)
        self.stale = stale


class _Request:
    __slots__ = ("method", "path", "headers", "body")

    def __init__(self, method, path, headers, body):
        self.method = method
        self.path = path
        self.headers = headers
        self.body = body


# public-edge hardening: the frontend runs inside the SUPERVISOR
# process (which owns the mesh), so an unbounded request body would let
# one hostile POST OOM the whole deployment. Matches the order of the
# aiohttp edge it replaces (client_max_size); responses from the
# trusted loopback backend are not capped.
MAX_REQUEST_BODY = 16 * 1024 * 1024
MAX_HEADER_LINES = 256


async def _read_http(reader, *, request: bool, max_body: int | None = None):
    """One HTTP/1.1 message off ``reader``. Returns ``None`` on a clean
    EOF before the start line; raises ``ValueError`` on malformed or
    over-sized input and ``asyncio.IncompleteReadError`` on a torn
    message (callers close the connection for both)."""
    line = await reader.readline()
    if not line:
        return None
    parts = line.decode("latin-1").rstrip("\r\n").split(None, 2)
    if len(parts) < 2:
        # a scanner's garbage start line must close the connection
        # cleanly (callers catch ValueError), not kill the handler task
        raise ValueError(f"malformed HTTP start line: {line[:80]!r}")
    headers: dict[str, str] = {}
    for _ in range(MAX_HEADER_LINES):
        h = await reader.readline()
        if h in (b"\r\n", b"\n"):
            break
        if not h:
            raise asyncio.IncompleteReadError(b"", None)
        k, _, v = h.decode("latin-1").partition(":")
        headers[k.strip().lower()] = v.strip()
    else:
        raise ValueError("too many header lines")
    te = headers.get("transfer-encoding", "")
    if "chunked" in te.lower():
        chunks = []
        total = 0
        while True:
            size_line = await reader.readline()
            size = int(size_line.strip().split(b";")[0] or b"0", 16)
            if size == 0:
                await reader.readline()  # trailing CRLF
                break
            total += size
            if max_body is not None and total > max_body:
                raise ValueError("chunked body exceeds the request cap")
            chunks.append(await reader.readexactly(size))
            await reader.readexactly(2)  # chunk CRLF
        body = b"".join(chunks)
    else:
        n = int(headers.get("content-length", "0") or 0)
        if max_body is not None and n > max_body:
            raise ValueError(
                f"declared body of {n} bytes exceeds the request cap"
            )
        body = await reader.readexactly(n) if n > 0 else b""
    if request:
        return _Request(parts[0], parts[1], headers, body)
    return int(parts[1]), headers, body


# end-to-end headers the relay must NOT forward verbatim: hop-by-hop
# semantics, or recomputed by the frontend itself
_HOP_BY_HOP = frozenset(
    (
        "connection", "keep-alive", "transfer-encoding", "content-length",
        "te", "trailer", "upgrade", "proxy-authenticate",
        "proxy-authorization",
    )
)


class _BackendConn:
    """One kept-alive backend connection per client connection — the
    proxy preserves the closed-loop client's parallelism and its
    keep-alive amortization through to the gateway."""

    def __init__(self, frontend: "ServingFrontend"):
        self.fe = frontend
        self.reader = None
        self.writer = None
        # which backend ATTACHMENT this socket belongs to: a kept-alive
        # socket from the dead epoch failing AFTER epoch+1 attached is a
        # stale connection to retry, not a fresh backend loss
        self.gen = -1

    def close(self) -> None:
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:
                pass
        self.reader = self.writer = None

    async def roundtrip(self, req: _Request, rid: int):
        """Forward ``req`` (with the frontend's request id stamped) and
        read the full response; a transport failure raises
        ``_BackendDown`` (``stale=True`` when a reused kept-alive socket
        failed before any response byte — retry, don't declare a
        loss)."""
        reused = self.writer is not None
        try:
            if self.writer is None:
                # stamp the generation BEFORE connecting: a failing
                # CONNECT at the current attachment is a real loss
                self.gen = self.fe._attach_gen
                self.reader, self.writer = await asyncio.open_connection(
                    self.fe.backend_host, self.fe.backend_port
                )
            head = [
                f"{req.method} {req.path} HTTP/1.1",
                f"Host: {self.fe.backend_host}:{self.fe.backend_port}",
                f"Content-Length: {len(req.body)}",
                f"X-Pathway-Request-Id: {rid}",
                "Connection: keep-alive",
            ]
            # forward the client's end-to-end headers (Origin/CORS,
            # Authorization, custom validator inputs...) — only
            # hop-by-hop semantics, the recomputed framing, and any
            # client-supplied copy of the request-id header (ours is
            # authoritative) are rebuilt by the frontend
            for k, v in req.headers.items():
                if (
                    k not in _HOP_BY_HOP
                    and k not in ("host", "x-pathway-request-id")
                ):
                    head.append(f"{k.title()}: {v}")
            self.writer.write(
                ("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
                + req.body
            )
            await self.writer.drain()
            out = await _read_http(self.reader, request=False)
            if out is None:
                raise _BackendDown(
                    "backend closed the connection", stale=reused
                )
            status, headers, body = out
            if "close" in headers.get("connection", "").lower():
                self.close()
            return status, headers, body
        except _BackendDown:
            self.close()
            raise
        except (OSError, asyncio.IncompleteReadError, ValueError) as exc:
            self.close()
            raise _BackendDown(repr(exc), stale=reused) from exc


class ServingFrontend:
    """The supervisor-side (or standalone) serving frontend. Runs its
    own asyncio loop on a daemon thread; ``start()`` returns once the
    public listener is bound."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 8080,
        backend_port: int | None = None,
        backend_host: str = "127.0.0.1",
        timeout_s: float | None = None,
        park_budget: int | None = None,
        queue_cap: int | None = None,
        attach_poll_s: float = 0.1,
    ):
        self.host = host
        self.port = port
        self.backend_host = backend_host
        if backend_port is None:
            backend_port = int(
                os.environ.get("PATHWAY_SERVE_BACKEND_PORT", "0") or 0
            )
        if not backend_port:
            raise ValueError("ServingFrontend requires backend_port")
        self.backend_port = backend_port
        self.timeout_s = (
            timeout_s
            if timeout_s is not None
            else _env_float("PATHWAY_REST_TIMEOUT_S", 120.0)
        )
        self.park_budget = int(
            park_budget
            if park_budget is not None
            else _env_float("PATHWAY_SERVE_PARK_BUDGET", 1024)
        )
        self.queue_cap = int(
            queue_cap
            if queue_cap is not None
            else _env_float("PATHWAY_SERVE_QUEUE_CAP", 2048)
        )
        self.attach_poll_s = attach_poll_s
        self.metrics = FrontendMetrics()
        # -- state (touched only on the frontend's asyncio loop) --------
        self._backend_up = False
        self._draining = False
        self._stopped = False
        self._inflight: dict[int, float] = {}  # rid -> deadline (loop time)
        self._parked: dict[int, float] = {}    # rid -> deadline, arrival order
        self._responded: set[int] = set()
        self._expired: set[int] = set()        # decided by serve_replay_split
        self._seq = 0
        self._down_since: float | None = None
        self._had_attach = False
        self._attach_gen = 0  # bumped per successful attach
        self.observed_restart_s = 0.0
        # elastic mesh (ISSUE 11): a supervisor-initiated rescale
        # announces itself BEFORE reaping the backend, so the detached
        # window reads `rescaling` on /healthz and its duration feeds a
        # SEPARATE EWMA — a rescale restores a re-sharded world (more
        # state, different cost curve) and must not pollute the crash
        # recovery estimate that sizes Retry-After for real failures
        self._rescaling = False
        self._loss_was_rescale = False
        self.observed_rescale_s = 0.0
        self.rescales_seen = 0
        self._attach_event: asyncio.Event | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._server = None
        self._started = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ServingFrontend":
        self._thread = threading.Thread(
            target=self._run, name="pw-serve-frontend", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("serving frontend failed to start")
        return self

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        loop.run_until_complete(self._start_async())
        self._started.set()
        loop.run_forever()
        # cancel stragglers so the loop closes cleanly
        for task in asyncio.all_tasks(loop):
            task.cancel()
        try:
            loop.run_until_complete(
                asyncio.gather(*asyncio.all_tasks(loop), return_exceptions=True)
            )
        except Exception:
            pass
        loop.close()

    async def _start_async(self) -> None:
        self._attach_event = asyncio.Event()
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, reuse_address=True
        )
        asyncio.ensure_future(self._attach_loop())

    def state(self) -> str:
        return _proto.serve_frontend_state(
            self._backend_up, self._draining, self._rescaling
        )

    def note_rescale(self) -> None:
        """Called by the supervisor BEFORE it reaps the rank set for a
        rescale: the upcoming backend loss is planned, so readiness
        reads ``rescaling`` (not ``recovering``) and the outage duration
        lands on the rescale EWMA. Thread-safe."""
        loop = self._loop
        if loop is not None:
            loop.call_soon_threadsafe(self._set_rescaling)

    def _set_rescaling(self) -> None:
        self._rescaling = True

    def _retry_after_s(self) -> float:
        """The restart-time estimate behind Retry-After: the rescale
        EWMA while a rescale is in flight (or when it is all we have
        observed), the crash EWMA otherwise."""
        if self._rescaling and self.observed_rescale_s > 0:
            return self.observed_rescale_s
        if self.observed_restart_s > 0:
            return self.observed_restart_s
        return self.observed_rescale_s

    def drain(self) -> None:
        """Enter draining: new arrivals shed with Retry-After so a load
        balancer rotates away; in-flight requests finish."""
        loop = self._loop
        if loop is not None:
            loop.call_soon_threadsafe(self._set_draining)

    def _set_draining(self) -> None:
        self._draining = True

    def stop(self) -> None:
        loop = self._loop
        if loop is None:
            return
        self._stopped = True

        def _shutdown():
            if self._server is not None:
                self._server.close()
            loop.stop()

        loop.call_soon_threadsafe(_shutdown)
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- backend attach / loss (asyncio loop only) -------------------------
    async def _attach_loop(self) -> None:
        """Probe the backend port while detached; on success run the
        replay split and wake every parked coroutine."""
        while not self._stopped:
            if self._backend_up:
                await asyncio.sleep(self.attach_poll_s)
                continue
            try:
                r, w = await asyncio.open_connection(
                    self.backend_host, self.backend_port
                )
                w.close()
            except OSError:
                await asyncio.sleep(self.attach_poll_s)
                continue
            self._on_attach()

    def _on_attach(self) -> None:
        now = self._loop.time()
        if self._down_since is not None:
            # a previously-attached epoch was lost: this is a rollback
            # handoff — record how long serving was dark (the blip)
            handoff = now - self._down_since
            self.metrics.on_handoff_s(handoff)
            # EWMA of observed restart time sizes Retry-After for sheds
            # and deadline expiries — clients back off for as long as a
            # rollback actually takes here. Rescale handoffs feed their
            # OWN estimate (a re-sharded restore loads every old rank's
            # snapshot — different cost curve than a crash respawn)
            if self._loss_was_rescale:
                self.rescales_seen += 1
                self.observed_rescale_s = (
                    handoff
                    if self.observed_rescale_s <= 0
                    else 0.5 * self.observed_rescale_s + 0.5 * handoff
                )
            else:
                self.observed_restart_s = (
                    handoff
                    if self.observed_restart_s <= 0
                    else 0.5 * self.observed_restart_s + 0.5 * handoff
                )
            self._down_since = None
        self._rescaling = False
        self._loss_was_rescale = False
        self._had_attach = True
        self._attach_gen += 1
        self._backend_up = True
        # the replay-vs-expire verdict over the parked set is a protocol
        # decision (serve_replay_split) — parked coroutines consult the
        # expired set it computed instead of re-deciding per coroutine
        replay, expired = _proto.serve_replay_split(
            list(self._parked), now, self._parked
        )
        self._expired.update(expired)
        ev = self._attach_event
        if ev is not None:
            ev.set()

    def _note_backend_loss(self) -> None:
        if not self._backend_up and self._down_since is not None:
            return  # already noted
        first = self._backend_up or self._down_since is None
        # fresh event FIRST: coroutines that observe backend_up == False
        # after this point wait on the new event, which only the next
        # attach sets
        self._attach_event = asyncio.Event()
        self._backend_up = False
        if self._had_attach and first:
            self._down_since = self._loop.time()
            # classify the loss NOW: a note_rescale that arrives after
            # the links already dropped must not retroactively relabel
            # a crash window as a planned rescale
            self._loss_was_rescale = self._rescaling
            self.metrics.backend_losses += 1
            # the park set at loss: every admitted, unresponded request
            # (the exactly-once boundary — responded ids never replay)
            for rid in _proto.serve_park(self._inflight, self._responded):
                if rid not in self._parked:
                    self._parked[rid] = self._inflight[rid]
                    self.metrics.parked += 1
                    _faults.fault_point("serve.park")

    # -- request path (asyncio loop) ---------------------------------------
    async def _handle_conn(self, reader, writer) -> None:
        backend = _BackendConn(self)
        try:
            while True:
                try:
                    # bounded read: body cap (a hostile Content-Length
                    # must not buffer gigabytes inside the SUPERVISOR
                    # process) and an idle timeout so slow-loris clients
                    # cannot hold handler tasks forever
                    req = await asyncio.wait_for(
                        _read_http(
                            reader, request=True,
                            max_body=MAX_REQUEST_BODY,
                        ),
                        timeout=max(300.0, self.timeout_s),
                    )
                except (
                    asyncio.IncompleteReadError,
                    asyncio.TimeoutError,
                    ValueError,
                    OSError,
                ):
                    break
                if req is None:
                    break
                keep = "close" not in req.headers.get(
                    "connection", ""
                ).lower()
                path = req.path.split("?", 1)[0]
                if path == "/healthz":
                    await self._respond_local(writer, req, keep)
                elif path == "/metrics":
                    await self._write_response(
                        writer, 200, self.metrics.render().encode(),
                        keep, ctype="text/plain; version=0.0.4",
                    )
                else:
                    await self._serve(req, writer, backend, keep)
                if not keep:
                    break
        finally:
            backend.close()
            try:
                writer.close()
            except Exception:
                pass

    async def _respond_local(self, writer, req, keep) -> None:
        state = self.state()
        body = _json.dumps(
            {
                "state": state,
                "backend_port": self.backend_port,
                "parked": len(self._parked),
                "observed_restart_s": round(self.observed_restart_s, 3),
                "observed_rescale_s": round(self.observed_rescale_s, 3),
                "rescales_seen": self.rescales_seen,
            }
        ).encode()
        await self._write_response(
            writer, 200 if state == "serving" else 503, body, keep,
            ctype="application/json",
        )

    async def _serve(self, req, writer, backend, keep) -> None:
        m = self.metrics
        verdict = _proto.serve_admit(
            self.state(), len(self._inflight), self.queue_cap,
            len(self._parked), self.park_budget,
        )
        if verdict == "shed":
            m.shed += 1
            await self._write_response(
                writer, 503,
                b'{"error": "overloaded or draining, retry later"}',
                keep, ctype="application/json",
                extra={
                    "Retry-After": str(
                        _proto.serve_retry_after(self._retry_after_s())
                    )
                },
            )
            return
        m.admitted += 1
        self._seq += 1
        rid = self._seq
        deadline = self._loop.time() + self.timeout_s
        self._inflight[rid] = deadline
        if verdict == "park":
            self._parked[rid] = deadline
            m.parked += 1
            _faults.fault_point("serve.park")
        try:
            await self._serve_inflight(req, writer, backend, keep, rid)
        finally:
            self._inflight.pop(rid, None)
            self._parked.pop(rid, None)
            self._expired.discard(rid)
            self._responded.discard(rid)

    async def _serve_inflight(self, req, writer, backend, keep, rid) -> None:
        """Forward → (park → replay)* → terminal response. Every admitted
        request leaves through exactly one of: relayed backend response,
        deadline 503 + Retry-After, or frontend-timeout 504."""
        m = self.metrics
        deadline = self._inflight[rid]
        while True:
            if not self._backend_up:
                # -- parked: future retained, waiting for epoch+1 -------
                # (membership-checked on the shared dict, not a local
                # flag: _note_backend_loss may have parked this rid
                # already while its roundtrip was failing)
                if rid not in self._parked:
                    self._parked[rid] = deadline
                    m.parked += 1
                    _faults.fault_point("serve.park")
                ev = self._attach_event
                remaining = deadline - self._loop.time()
                if remaining <= 0 or rid in self._expired:
                    m.deadline_expired += 1
                    await self._write_deadline_503(writer, keep)
                    return
                try:
                    await asyncio.wait_for(ev.wait(), timeout=remaining)
                except asyncio.TimeoutError:
                    m.deadline_expired += 1
                    await self._write_deadline_503(writer, keep)
                    return
                if rid in self._expired:
                    # serve_replay_split put this id in the expired half
                    m.deadline_expired += 1
                    await self._write_deadline_503(writer, keep)
                    return
            if rid in self._parked and self._backend_up:
                # -- replay into the recovered epoch's first windows ----
                # (single accounting site: covers both a woken parked
                # coroutine and one whose roundtrip failure raced a
                # fast reattach)
                self._parked.pop(rid, None)
                m.replayed += 1
                _faults.fault_point("serve.replay")
            remaining = deadline - self._loop.time()
            if remaining <= 0:
                m.deadline_expired += 1
                await self._write_deadline_503(writer, keep)
                return
            try:
                status, headers, body = await asyncio.wait_for(
                    backend.roundtrip(req, rid), timeout=remaining + 0.5
                )
            except _BackendDown as exc:
                if not exc.stale and backend.gen == self._attach_gen:
                    # a FRESH connection failed at the current
                    # attachment: the backend epoch is genuinely gone
                    self._note_backend_loss()
                # else: a reused kept-alive socket went stale (gateway
                # idle-close race, or a socket from a previous
                # attachment) — retry on a fresh connection without
                # declaring (and mis-measuring) a backend loss; if the
                # backend really died, the fresh connect fails next
                # iteration with stale=False and the loss is declared
                continue
            except asyncio.TimeoutError:
                # backend alive but past the request deadline: the
                # gateway's own 504 raced us — answer and drop the
                # (mid-response) backend connection
                backend.close()
                m.timeouts += 1
                await self._write_response(
                    writer, 504, b'{"error": "timeout"}', keep,
                    ctype="application/json",
                )
                return
            # response fully received: the request is TERMINAL — it must
            # never replay (the park set filters on this)
            self._responded.add(rid)
            m.responses += 1
            # relay every end-to-end backend header (CORS, Retry-After,
            # Degraded, caching...) — only hop-by-hop semantics and the
            # recomputed framing headers are the frontend's own
            extra = {
                k.title(): v
                for k, v in headers.items()
                if k not in _HOP_BY_HOP and k != "content-type"
            }
            await self._write_response(
                writer, status, body, keep,
                ctype=headers.get("content-type", "application/json"),
                extra=extra,
            )
            return

    async def _write_deadline_503(self, writer, keep) -> None:
        """Deadline accounting for a parked request: its budget expired
        while serving was dark — a terminal 503 whose Retry-After is the
        observed restart time, NOT a dropped connection."""
        await self._write_response(
            writer, 503,
            b'{"error": "rolling back, deadline expired while parked"}',
            keep, ctype="application/json",
            extra={
                "Retry-After": str(
                    _proto.serve_retry_after(self._retry_after_s())
                )
            },
        )

    async def _write_response(
        self, writer, status, body, keep, ctype="application/json",
        extra=None,
    ) -> None:
        reason = {200: "OK", 503: "Service Unavailable", 504: "Gateway Timeout"}
        head = [
            f"HTTP/1.1 {status} {reason.get(status, 'Status')}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(body)}",
            f"Connection: {'keep-alive' if keep else 'close'}",
        ]
        for k, v in (extra or {}).items():
            head.append(f"{k}: {v}")
        try:
            writer.write(
                ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body
            )
            await writer.drain()
        except (OSError, ConnectionError):
            pass  # client went away; its request already terminated


def main(argv=None) -> int:  # pragma: no cover - thin CLI
    import argparse

    ap = argparse.ArgumentParser(
        description="standalone epoch-survivable serving frontend"
    )
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--backend-port", type=int, required=True)
    args = ap.parse_args(argv)
    fe = ServingFrontend(
        host=args.host, port=args.port, backend_port=args.backend_port
    ).start()
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        fe.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
