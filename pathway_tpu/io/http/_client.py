"""HTTP streaming client connector (reference:
python/pathway/io/http/__init__.py:28 — poll an endpoint into a table;
write: POST each row to an endpoint) + the keep-alive request session the
serving clients (VectorStoreClient, RAGClient) reuse so a closed-loop
client pays TCP setup once, not per query."""

from __future__ import annotations

import http.client
import json as _json
import threading
import time
import urllib.parse
import urllib.request
from typing import Any

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read


class HttpError(urllib.error.HTTPError):
    """Non-2xx response from a keep-alive session request. Subclasses
    ``urllib.error.HTTPError`` so callers that caught the old
    urllib-based clients' errors (``e.code``, ``e.read()``) keep
    working unchanged. ``headers`` carries the response headers (the
    backpressure contract rides them: ``Retry-After`` on 503 sheds,
    ``Degraded`` on brownout answers)."""

    def __init__(
        self, status: int, body: bytes, url: str = "", headers=None
    ):
        import email.message
        import io

        hdrs = email.message.Message()
        for k, v in (headers or {}).items():
            hdrs[k] = v
        # .status/.code come from HTTPError itself
        super().__init__(url, status, f"HTTP {status}", hdrs, io.BytesIO(body))
        self.body = body

    def json(self):
        return _json.loads(self.body.decode())


class KeepAliveSession:
    """Persistent-connection JSON client over ``http.client``.

    One kept-alive HTTP/1.1 connection PER THREAD (``threading.local``),
    re-established transparently when the server closes it — concurrent
    callers sharing one session keep their independent parallelism (no
    cross-thread lock held over a round trip) while each thread's
    request stream pays connection setup once. This is what lets a
    closed-loop client of the batching gateway ride the keep-alive path
    the server now serves."""

    def __init__(
        self,
        url: str,
        timeout: float = 90.0,
        retries: int = 0,
        max_retry_wait_s: float = 30.0,
    ):
        if "://" not in url:
            # scheme-less "host:port" would mis-parse as scheme=host
            url = "http://" + url
        parsed = urllib.parse.urlsplit(url)
        if parsed.scheme not in ("http", "https"):
            raise ValueError(
                f"KeepAliveSession supports http(s):// urls, got {url!r}"
            )
        self.tls = parsed.scheme == "https"
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or (443 if self.tls else 80)
        # a base path in the url (reverse-proxy prefix) prepends to
        # every route, matching the old `url + route` concatenation
        self.base_path = parsed.path.rstrip("/")
        self.timeout = timeout
        # opt-in bounded retry of the DOCUMENTED backpressure contract:
        # a 503 carrying Retry-After (admission shed, brownout breaker,
        # parked-deadline expiry during a rollback) is an explicit
        # "come back in N seconds" — with retries > 0 the session honors
        # it, sleeping min(Retry-After, max_retry_wait_s) between
        # attempts. 503s WITHOUT Retry-After and every other status
        # still raise immediately: only the server-invited retry is
        # safe to automate.
        self.retries = retries
        self.max_retry_wait_s = max_retry_wait_s
        self._local = threading.local()

    def _connect(self) -> http.client.HTTPConnection:
        cls = (
            http.client.HTTPSConnection
            if self.tls
            else http.client.HTTPConnection
        )
        conn = cls(self.host, self.port, timeout=self.timeout)
        conn.connect()
        return conn

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            finally:
                self._local.conn = None

    def request_json(self, method: str, route: str, payload=None):
        body = None
        headers = {}
        if payload is not None:
            body = _json.dumps(payload).encode()
            headers["Content-Type"] = "application/json"
        route = self.base_path + route
        attempts = 0
        while True:
            resp, data = self._roundtrip(method, route, body, headers)
            if (
                resp.status == 503
                and attempts < self.retries
                and resp.getheader("Retry-After") is not None
            ):
                try:
                    delay = float(resp.getheader("Retry-After"))
                except (TypeError, ValueError):
                    delay = 1.0
                attempts += 1
                time.sleep(max(0.0, min(delay, self.max_retry_wait_s)))
                continue
            break
        if resp.status >= 400:
            raise HttpError(
                resp.status, data, headers=dict(resp.getheaders())
            )
        if not data:
            return None
        return _json.loads(data.decode())

    def _roundtrip(self, method, route, body, headers):
        while True:
            reused = getattr(self._local, "conn", None) is not None
            conn = self._local.conn if reused else self._connect()
            self._local.conn = conn
            sent = False
            try:
                conn.request(method, route, body=body, headers=headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
                if resp.will_close:
                    conn.close()
                    self._local.conn = None
                break
            except (
                http.client.HTTPException, ConnectionError, OSError
            ) as exc:
                conn.close()
                self._local.conn = None
                # retry ONLY the stale keep-alive race, where the server
                # provably never processed the request: a send-phase
                # failure on a reused socket, or a zero-byte
                # "closed without response" on a reused socket (the
                # idle-timeout close raced our request). Anything after
                # response bytes began — or any fresh-connection failure
                # — may have been processed server-side, and re-sending
                # would duplicate a non-idempotent request: propagate.
                stale = reused and (
                    not sent
                    or isinstance(
                        exc,
                        (
                            http.client.RemoteDisconnected,
                            http.client.BadStatusLine,
                        ),
                    )
                )
                if not stale:
                    raise
        return resp, data

    def post(self, route: str, payload: dict):
        return self.request_json("POST", route, payload)

    def get(self, route: str):
        return self.request_json("GET", route)


class _HttpPollSubject(ConnectorSubject):
    def __init__(self, url, refresh_interval, headers, method="GET"):
        super().__init__()
        self.url = url
        self.refresh_interval = refresh_interval
        self.headers = headers or {}
        self.method = method
        self._stop = False
        self._seen_lines: set[str] = set()

    def run(self):
        while not self._stop:
            req = urllib.request.Request(
                self.url, headers=self.headers, method=self.method
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    body = resp.read().decode()
            except Exception:
                time.sleep(self.refresh_interval)
                continue
            emitted = False
            for line in body.splitlines():
                line = line.strip()
                if not line or line in self._seen_lines:
                    continue  # only NEW lines become rows across polls
                self._seen_lines.add(line)
                emitted = True
                try:
                    self.next(**_json.loads(line))
                except Exception:
                    self.next(data=line)
            if emitted:
                self.commit()
            time.sleep(self.refresh_interval)

    def on_stop(self):
        self._stop = True

    def snapshot_state(self):
        return {"seen_lines": set(self._seen_lines)}

    def seek(self, state):
        self._seen_lines = set(state.get("seen_lines", ()))


def read(
    url: str,
    *,
    schema: type[Schema] | None = None,
    method: str = "GET",
    refresh_interval: float = 5.0,
    headers: dict | None = None,
    format: str = "json",
    **kwargs,
):
    subject = _HttpPollSubject(url, refresh_interval, headers, method=method)
    return python_read(subject, schema=schema, name=f"http:{url}")


def write(
    table,
    url: str,
    *,
    method: str = "POST",
    headers: dict | None = None,
    format: str = "json",
    n_retries: int = 0,
    connect_timeout_ms: int | None = None,
    request_timeout_ms: int = 30_000,
    payload_fn=None,
    response_check=None,
    include_special_fields: bool = True,
    **kwargs,
) -> None:
    """POST every row CHANGE (inserts and retractions) to `url` with
    `time`/`diff` fields appended (reference: io/http write — the payload
    downstream needs to mirror table state). `payload_fn(row_dict) ->
    bytes | None` customizes the body (None skips the change);
    `response_check(body_bytes)` may log/raise on API-level failures."""
    import logging

    cols = table.column_names()
    hdrs = {"Content-Type": "application/json", **(headers or {})}
    timeout_s = request_timeout_ms / 1000.0
    log = logging.getLogger("pathway_tpu.io.http")

    def on_change(key, row, time_, diff):
        data = dict(zip(cols, row))
        if include_special_fields:
            data["time"] = time_
            data["diff"] = diff
        if payload_fn is not None:
            payload = payload_fn(data, diff)
            if payload is None:
                return
        else:
            payload = _json.dumps(data, default=str).encode()
        req = urllib.request.Request(
            url, data=payload, method=method, headers=hdrs
        )
        for attempt in range(n_retries + 1):
            try:
                with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                    body = resp.read()
                if response_check is not None:
                    response_check(body)
                return
            except Exception as exc:
                if attempt == n_retries:
                    log.warning("http write to %s failed: %r", url, exc)
                else:
                    time.sleep(min(0.1 * (2 ** attempt), 2.0))

    def lower(ctx):
        ctx.scope.output(ctx.engine_table(table), on_change=on_change)

    G.add_operator([table], [], lower, "http_write", is_output=True)
