"""HTTP streaming client connector (reference:
python/pathway/io/http/__init__.py:28 — poll an endpoint into a table;
write: POST each row to an endpoint)."""

from __future__ import annotations

import json as _json
import time
import urllib.request
from typing import Any

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read


class _HttpPollSubject(ConnectorSubject):
    def __init__(self, url, refresh_interval, headers, method="GET"):
        super().__init__()
        self.url = url
        self.refresh_interval = refresh_interval
        self.headers = headers or {}
        self.method = method
        self._stop = False
        self._seen_lines: set[str] = set()

    def run(self):
        while not self._stop:
            req = urllib.request.Request(
                self.url, headers=self.headers, method=self.method
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    body = resp.read().decode()
            except Exception:
                time.sleep(self.refresh_interval)
                continue
            emitted = False
            for line in body.splitlines():
                line = line.strip()
                if not line or line in self._seen_lines:
                    continue  # only NEW lines become rows across polls
                self._seen_lines.add(line)
                emitted = True
                try:
                    self.next(**_json.loads(line))
                except Exception:
                    self.next(data=line)
            if emitted:
                self.commit()
            time.sleep(self.refresh_interval)

    def on_stop(self):
        self._stop = True

    def snapshot_state(self):
        return {"seen_lines": set(self._seen_lines)}

    def seek(self, state):
        self._seen_lines = set(state.get("seen_lines", ()))


def read(
    url: str,
    *,
    schema: type[Schema] | None = None,
    method: str = "GET",
    refresh_interval: float = 5.0,
    headers: dict | None = None,
    format: str = "json",
    **kwargs,
):
    subject = _HttpPollSubject(url, refresh_interval, headers, method=method)
    return python_read(subject, schema=schema, name=f"http:{url}")


def write(table, url: str, *, method: str = "POST", headers: dict | None = None,
          format: str = "json", **kwargs) -> None:
    cols = table.column_names()
    hdrs = {"Content-Type": "application/json", **(headers or {})}

    def on_change(key, row, time_, diff):
        if diff <= 0:
            return
        payload = _json.dumps(dict(zip(cols, row)), default=str).encode()
        req = urllib.request.Request(
            url, data=payload, method=method, headers=hdrs
        )
        try:
            urllib.request.urlopen(req, timeout=30).read()
        except Exception:
            pass  # reference logs and continues

    def lower(ctx):
        ctx.scope.output(ctx.engine_table(table), on_change=on_change)

    G.add_operator([table], [], lower, "http_write", is_output=True)
