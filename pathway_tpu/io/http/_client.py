"""HTTP streaming client connector (reference:
python/pathway/io/http/__init__.py:28 — poll an endpoint into a table;
write: POST each row to an endpoint)."""

from __future__ import annotations

import json as _json
import time
import urllib.request
from typing import Any

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read


class _HttpPollSubject(ConnectorSubject):
    def __init__(self, url, refresh_interval, headers, method="GET"):
        super().__init__()
        self.url = url
        self.refresh_interval = refresh_interval
        self.headers = headers or {}
        self.method = method
        self._stop = False
        self._seen_lines: set[str] = set()

    def run(self):
        while not self._stop:
            req = urllib.request.Request(
                self.url, headers=self.headers, method=self.method
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    body = resp.read().decode()
            except Exception:
                time.sleep(self.refresh_interval)
                continue
            emitted = False
            for line in body.splitlines():
                line = line.strip()
                if not line or line in self._seen_lines:
                    continue  # only NEW lines become rows across polls
                self._seen_lines.add(line)
                emitted = True
                try:
                    self.next(**_json.loads(line))
                except Exception:
                    self.next(data=line)
            if emitted:
                self.commit()
            time.sleep(self.refresh_interval)

    def on_stop(self):
        self._stop = True

    def snapshot_state(self):
        return {"seen_lines": set(self._seen_lines)}

    def seek(self, state):
        self._seen_lines = set(state.get("seen_lines", ()))


def read(
    url: str,
    *,
    schema: type[Schema] | None = None,
    method: str = "GET",
    refresh_interval: float = 5.0,
    headers: dict | None = None,
    format: str = "json",
    **kwargs,
):
    subject = _HttpPollSubject(url, refresh_interval, headers, method=method)
    return python_read(subject, schema=schema, name=f"http:{url}")


def write(
    table,
    url: str,
    *,
    method: str = "POST",
    headers: dict | None = None,
    format: str = "json",
    n_retries: int = 0,
    connect_timeout_ms: int | None = None,
    request_timeout_ms: int = 30_000,
    payload_fn=None,
    response_check=None,
    include_special_fields: bool = True,
    **kwargs,
) -> None:
    """POST every row CHANGE (inserts and retractions) to `url` with
    `time`/`diff` fields appended (reference: io/http write — the payload
    downstream needs to mirror table state). `payload_fn(row_dict) ->
    bytes | None` customizes the body (None skips the change);
    `response_check(body_bytes)` may log/raise on API-level failures."""
    import logging

    cols = table.column_names()
    hdrs = {"Content-Type": "application/json", **(headers or {})}
    timeout_s = request_timeout_ms / 1000.0
    log = logging.getLogger("pathway_tpu.io.http")

    def on_change(key, row, time_, diff):
        data = dict(zip(cols, row))
        if include_special_fields:
            data["time"] = time_
            data["diff"] = diff
        if payload_fn is not None:
            payload = payload_fn(data, diff)
            if payload is None:
                return
        else:
            payload = _json.dumps(data, default=str).encode()
        req = urllib.request.Request(
            url, data=payload, method=method, headers=hdrs
        )
        for attempt in range(n_retries + 1):
            try:
                with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                    body = resp.read()
                if response_check is not None:
                    response_check(body)
                return
            except Exception as exc:
                if attempt == n_retries:
                    log.warning("http write to %s failed: %r", url, exc)
                else:
                    time.sleep(min(0.1 * (2 ** attempt), 2.0))

    def lower(ctx):
        ctx.scope.output(ctx.engine_table(table), on_change=on_change)

    G.add_operator([table], [], lower, "http_write", is_output=True)
