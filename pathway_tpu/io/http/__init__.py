"""pw.io.http — HTTP streaming client + REST server connector (reference:
python/pathway/io/http/__init__.py:28 client; _server.py:624
rest_connector + :329 PathwayWebserver)."""

from pathway_tpu.io.http._server import (
    EndpointDocumentation,
    PathwayWebserver,
    RestServerSubject,
    rest_connector,
)
from pathway_tpu.io.http._client import (
    HttpError,
    KeepAliveSession,
    read,
    write,
)
from pathway_tpu.io.http._frontend import FrontendMetrics, ServingFrontend

__all__ = [
    "PathwayWebserver",
    "EndpointDocumentation",
    "RestServerSubject",
    "rest_connector",
    "ServingFrontend",
    "FrontendMetrics",
    "KeepAliveSession",
    "HttpError",
    "read",
    "write",
]
