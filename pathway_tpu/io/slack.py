"""pw.io.slack — connector surface (reference: python/pathway/io/slack (webhook output)).

Client transport gated on its library; the configuration surface matches
the reference so templates parse and fail only at run time with a clear
dependency error."""

from __future__ import annotations

from pathway_tpu.io._gated import require


def write(table, *args, name=None, **kwargs):
    require('requests')
    raise NotImplementedError(
        "pw.io.slack.write: client library found, but no slack service "
        "transport is wired in this build"
    )
