"""pw.io.slack — Slack output connector (reference: python/pathway/io/slack
— posts one message per inserted row via chat.postMessage)."""

from __future__ import annotations

import json as _json
import logging

from pathway_tpu.io.http._client import write as _http_write

_log = logging.getLogger("pathway_tpu.io.slack")


def send_alerts(alerts, slack_alert_channel_id: str, slack_alert_token: str,
                *, name: str | None = None, **kwargs) -> None:
    """Post each inserted row as a Slack message (reference: io/slack
    send_alerts — accepts a ColumnReference or a single-column table)."""
    from pathway_tpu.internals.expression import ColumnReference

    if isinstance(alerts, ColumnReference):
        alerts = alerts.table.select(alerts)
    cols = alerts.column_names()

    def payload(data: dict, diff: int):
        if diff <= 0:
            return None  # alerts fire on insertion only
        values = {c: data[c] for c in cols}
        text = (
            str(values[cols[0]])
            if len(cols) == 1
            else _json.dumps(values, default=str)
        )
        return _json.dumps(
            {"channel": slack_alert_channel_id, "text": text}
        ).encode()

    def check(body: bytes) -> None:
        # Slack returns API failures as ok:false over HTTP 200
        try:
            out = _json.loads(body)
        except Exception:
            return
        if not out.get("ok", True):
            _log.warning("slack postMessage failed: %s", out.get("error"))

    _http_write(
        alerts,
        "https://slack.com/api/chat.postMessage",
        method="POST",
        headers={"Authorization": f"Bearer {slack_alert_token}"},
        payload_fn=payload,
        response_check=check,
        n_retries=2,
    )


write = send_alerts
