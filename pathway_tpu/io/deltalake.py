"""pw.io.deltalake — Delta Lake connector (reference:
python/pathway/io/deltalake over the native DeltaTableReader/Writer,
src/connectors/data_storage.rs:1902/:1611).

Redesigned transport: no delta-rs — the Delta Lake format IS an open
spec (parquet parts + a JSON transaction log under ``_delta_log/``), and
pyarrow is in the image, so this build implements the protocol directly:

* ``write`` appends one parquet part + one log version per non-empty
  commit window, with ``protocol``/``metaData`` actions minted at table
  creation (schema inferred from the table's dtypes);
* ``read`` polls ``_delta_log`` versions in order and ingests the
  ``add`` actions of each (append-only semantics, like the reference's
  reader at io/deltalake/__init__.py:38).

Storage rides a small store abstraction: local filesystem, or any
S3-compatible object store through the dependency-free SigV4 transport
(io/_s3.py) — ``s3://bucket/prefix`` lakes read and write directly on
object storage like the reference (data_storage.rs:1611,1902), with
log-commit exclusivity via conditional PUT (``If-None-Match: *``).
"""

from __future__ import annotations

import io as _io
import json as _json
import os
import time
import uuid
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read

__all__ = ["read", "write"]

_DELTA_TYPES = {
    dt.INT: "long",
    dt.FLOAT: "double",
    dt.STR: "string",
    dt.BOOL: "boolean",
    dt.BYTES: "binary",
}


class _LocalStore:
    """Lake storage on the local filesystem."""

    def __init__(self, root: str):
        self.root = root

    def read(self, rel: str) -> bytes | None:
        try:
            with open(os.path.join(self.root, rel), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def write(self, rel: str, data: bytes) -> None:
        # write-temp + atomic rename: object-store PUTs are atomic, and
        # the transactional writer's manifests (the durable pre-commit
        # record) must never be observable half-written on local disk
        path = os.path.join(self.root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp-{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def write_exclusive(self, rel: str, data: bytes) -> None:
        """Create-if-absent (Delta log commits must be mutually
        exclusive: two writers must never both claim version N).
        os.link from a private tmp file is atomic-exclusive; filesystems
        without hard links fall back to os.replace (single-writer safe)."""
        path = os.path.join(self.root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp-{uuid.uuid4().hex}"
        with open(tmp, "wb") as f:
            f.write(data)
        try:
            os.link(tmp, path)
        except OSError as exc:
            if isinstance(exc, FileExistsError):
                raise
            os.replace(tmp, path)
            tmp = None
        finally:
            if tmp is not None:
                os.unlink(tmp)

    def list_log_versions(self) -> list[int]:
        log = os.path.join(self.root, "_delta_log")
        try:
            names = os.listdir(log)
        except FileNotFoundError:
            return []
        return sorted(
            int(f.split(".")[0])
            for f in names
            if f.endswith(".json") and f.split(".")[0].isdigit()
        )

    def list(self, prefix: str) -> list[str]:
        """Relative keys under ``prefix`` (the staging/manifest scans of
        the transactional writer). Walks only the prefix's subtree — a
        whole-lake walk would put an O(committed parts) scan on every
        snapshot cut's finalize."""
        base = os.path.join(self.root, prefix)
        if os.path.isdir(base):
            roots = [base]
        else:
            # partial-name prefix: walk the containing directory
            parent = os.path.dirname(base)
            if not os.path.isdir(parent):
                return []
            roots = [parent]
        out = []
        for root in roots:
            for dirpath, _dirs, files in os.walk(root):
                for f in files:
                    rel = os.path.relpath(
                        os.path.join(dirpath, f), self.root
                    ).replace(os.sep, "/")
                    if rel.startswith(prefix):
                        out.append(rel)
        return sorted(out)

    def delete(self, rel: str) -> None:
        try:
            os.unlink(os.path.join(self.root, rel))
        except FileNotFoundError:
            pass


class _S3Store:
    """Lake storage on an S3-compatible object store via the SigV4
    transport (reference: the delta-rs S3 log store,
    data_storage.rs:1611)."""

    def __init__(self, uri: str, settings=None, opener=None):
        from pathway_tpu.io._s3 import AwsS3Settings, S3Client

        rest = uri.split("://", 1)[1]
        bucket, _, prefix = rest.partition("/")
        if settings is None:
            settings = AwsS3Settings.new_from_path(uri)
        self.client = S3Client(settings.with_bucket(bucket), opener=opener)
        self.prefix = prefix.strip("/")

    def _key(self, rel: str) -> str:
        rel = rel.replace(os.sep, "/")
        return f"{self.prefix}/{rel}" if self.prefix else rel

    def read(self, rel: str) -> bytes | None:
        import urllib.error

        try:
            return self.client.get_object(self._key(rel))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def write(self, rel: str, data: bytes) -> None:
        self.client.put_object(self._key(rel), data)

    def write_exclusive(self, rel: str, data: bytes) -> None:
        self.client.put_object_if_absent(self._key(rel), data)

    def list_log_versions(self) -> list[int]:
        log_prefix = self._key("_delta_log/")
        out = []
        for obj in self.client.list_objects(prefix=log_prefix):
            name = obj.key.rsplit("/", 1)[-1]
            if name.endswith(".json") and name.split(".")[0].isdigit():
                out.append(int(name.split(".")[0]))
        return sorted(out)

    def list(self, prefix: str) -> list[str]:
        strip = len(self.prefix) + 1 if self.prefix else 0
        return sorted(
            obj.key[strip:]
            for obj in self.client.list_objects(prefix=self._key(prefix))
        )

    def delete(self, rel: str) -> None:
        self.client.delete_object(self._key(rel))


def _make_store(uri, s3_connection_settings=None):
    uri = str(os.fspath(uri))
    if uri.startswith(("s3://", "s3a://")):
        return _S3Store(uri, settings=s3_connection_settings)
    return _LocalStore(uri)


def _delta_type(col_dtype) -> str:
    return _DELTA_TYPES.get(col_dtype, "string")


class _DeltaSubject(ConnectorSubject):
    _deletions_enabled = False  # append-only source (reference contract)

    def __init__(self, store, columns, mode, refresh_interval=1.0):
        super().__init__()
        self.store = store
        self.columns = columns
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._version = 0
        self._stop = False

    def _scan_versions(self) -> bool:
        import pyarrow.parquet as pq

        advanced = False
        while True:
            data = self.store.read(
                os.path.join("_delta_log", f"{self._version:020d}.json")
            )
            if data is None:
                return advanced
            actions = [
                _json.loads(line)
                for line in data.decode().splitlines()
                if line.strip()
            ]
            # read every referenced part BEFORE emitting any row: a part
            # not yet visible (eventually-consistent store, torn upload)
            # must not advance the version — the whole version retries on
            # the next poll; in static mode a missing part is data loss
            # and fails loudly
            parts: dict[str, bytes] = {}
            for action in actions:
                add = action.get("add")
                if add is None:
                    continue
                blob = self.store.read(add["path"])
                if blob is None:
                    if self.mode == "static":
                        raise FileNotFoundError(
                            f"delta part {add['path']!r} referenced by log "
                            f"version {self._version} is missing"
                        )
                    return advanced  # retry this version next refresh
                parts[add["path"]] = blob
            for action in actions:
                add = action.get("add")
                if add is None:
                    continue
                # use_threads=False: this runs on a connector thread, and
                # pyarrow's CPU pool first spawned from a non-main thread
                # aborts the process at exit ("terminate called without an
                # active exception", ~30% of runs on pyarrow 22); parts
                # are small, the pool buys nothing here
                table = pq.read_table(
                    _io.BytesIO(parts[add["path"]]), use_threads=False
                )
                cols = [
                    table.column(c).to_pylist()
                    if c in table.column_names
                    else [None] * table.num_rows
                    for c in self.columns
                ]
                for i in range(table.num_rows):
                    key = ref_scalar("delta", add["path"], i)
                    self._upsert(
                        key,
                        {
                            c: cols[j][i]
                            for j, c in enumerate(self.columns)
                        },
                    )
            self._version += 1
            advanced = True

    def run(self):
        self._scan_versions()
        self.commit()
        if self.mode == "static":
            return
        while not self._stop:
            time.sleep(self.refresh_interval)
            if self._scan_versions():
                self.commit()

    def on_stop(self):
        self._stop = True

    def snapshot_state(self):
        return {"version": self._version}

    def seek(self, state) -> None:
        self._version = int(state.get("version", 0))


def read(
    uri,
    schema: type[Schema],
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 1.0,
    s3_connection_settings=None,
    name: str | None = None,
    **kwargs,
):
    """Read an append-only table from a Delta Lake — local path or
    ``s3://bucket/prefix`` (reference: io/deltalake/__init__.py:38, with
    the same AwsS3Settings-or-path-derived credentials contract :25)."""
    store = _make_store(uri, s3_connection_settings)
    subject = _DeltaSubject(
        store, schema.column_names(), mode,
        refresh_interval=refresh_interval,
    )
    return python_read(
        subject,
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"deltalake:{uri}",
    )


class TxnDeltaSink:
    """Transactional Delta writer (io/txn.py protocol; ISSUE 12) — and
    ROADMAP item 3's per-rank partitioned distributed output, shipped
    robustness-first: each rank writes its OWN parquet data files (no
    gather-to-rank-0 leg), and one rank appends the log version through
    the existing ``write_exclusive`` conditional-PUT path.

    Epoch-aligned two-phase commit (OPERATOR_PERSISTING runs):

    * **stage** — each rank's buffered rows flush into staged parquet
      parts under ``_pw_txn/stage/r{rank}/`` (rate-limited by
      ``min_commit_frequency`` *within* the epoch — the satellite fix:
      wall-clock autocommit no longer commits log versions the engine's
      epochs know nothing about);
    * **pre-commit** — at the snapshot cut every rank writes ONE
      durable manifest ``_pw_txn/manifest/r{rank}/{tag}.json`` naming
      its staged parts, so the set the marker commits is frozen;
    * **finalize** — after the marker lands, the log-owner rank
      (``shard_owner(0, world)``) folds ALL ranks' manifests at each
      covered tag into one log version carrying a Delta ``txn`` action
      ``{appId, version=tag}`` — the idempotence record: a re-run of
      finalize (or a recovery) skips tags the log already committed;
    * **recover** — pending manifests at-or-below the committed cut are
      (re-)committed to the log; manifests above it are discarded with
      their parts, as are orphaned staged parts of dead incarnations.
      Manifest partitions are claimed through the shared
      ``shard_owner`` mint, so after an N→M rescale the pending
      partitions of dead ranks are re-owned deterministically.

    Without OPERATOR_PERSISTING the writer behaves exactly as before
    (one part + one log version per rate-limited commit window) —
    documented at-least-once, since there is no engine cut to align
    with."""

    TXN_APP_ID = "pathway_tpu-txn"

    def __init__(self, store, cols, dtypes, min_commit_frequency):
        self.store = store
        self.cols = list(cols)
        self.dtypes = list(dtypes)
        self.min_commit_frequency = min_commit_frequency
        self.name = "deltalake"
        self._buf: list[tuple] = []
        # columnar staging (ISSUE 14): Arrow record batches delivered by
        # the fused chain, kept AS BATCHES until the part flush — the
        # parquet part is written straight from the column buffers, no
        # row round-trip
        self._abuf: list[tuple] = []  # [(RecordBatch, time), ...]
        self._version: int | None = None
        self._last_commit = 0.0
        self._txn = False
        self._rank = 0
        self._world = 1
        self._epoch = 0
        self._stats = None
        self._open_parts: list[dict] = []  # staged, not yet manifested
        self._staged_tag = -1
        self._finalized_tag = -1
        self._committed_txn: set[int] | None = None
        self._log_paths: set[str] = set()
        self._scanned_upto = -1
        self._incarnation = uuid.uuid4().hex[:12]
        self._app_id = self.TXN_APP_ID

    # -- log machinery (shared by both modes) ------------------------------

    def _bootstrap_actions(self) -> list[dict]:
        fields = [
            {
                "name": c,
                "type": _delta_type(d),
                "nullable": True,
                "metadata": {},
            }
            for c, d in zip(self.cols, self.dtypes)
        ] + [
            {"name": "time", "type": "long", "nullable": False, "metadata": {}},
            {"name": "diff", "type": "long", "nullable": False, "metadata": {}},
        ]
        return [
            {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
            {
                "metaData": {
                    "id": str(uuid.uuid4()),
                    "format": {"provider": "parquet", "options": {}},
                    "schemaString": _json.dumps(
                        {"type": "struct", "fields": fields}
                    ),
                    "partitionColumns": [],
                    "configuration": {},
                    "createdTime": int(time.time() * 1000),
                }
            },
        ]

    def _write_version(self, v: int, actions: list[dict]) -> None:
        # The Delta protocol requires mutually-exclusive version
        # creation: two writers must never both claim version N. The
        # store's write_exclusive raises FileExistsError if a concurrent
        # writer — a peer rank, a second pipeline or an external
        # delta-rs client — committed N first (local: atomic os.link;
        # S3: conditional PUT).
        data = "".join(_json.dumps(a) + "\n" for a in actions).encode()
        self.store.write_exclusive(
            os.path.join("_delta_log", f"{v:020d}.json"), data
        )

    def _next_version(self) -> int:
        if self._version is None:
            existing = self.store.list_log_versions()
            self._version = (max(existing) + 1) if existing else 0
            if self._version == 0:
                try:
                    self._write_version(0, self._bootstrap_actions())
                except FileExistsError:
                    pass  # a concurrent writer bootstrapped the table
                self._version = 1
        v = self._version
        self._version += 1
        return v

    def _commit(self, actions: list[dict]) -> None:
        while True:
            v = self._next_version()
            try:
                self._write_version(v, actions)
                return
            except FileExistsError:
                self._version = None  # lost the race: re-list and retry

    def _read_log_actions(self, v: int) -> list[dict]:
        data = self.store.read(
            os.path.join("_delta_log", f"{v:020d}.json")
        )
        if data is None:
            return []
        return [
            _json.loads(line)
            for line in data.decode().splitlines()
            if line.strip()
        ]

    def _scan_log(self, refresh: bool = False) -> set[int]:
        """Incremental pass over the log: the tags whose egress it
        already committed (the Delta ``txn`` action is the durable
        dedup record that makes finalize and recovery idempotent) AND
        every data path it references (committed parts live at their
        staged paths — object stores have no rename, the log reference
        IS the finalization — so the recovery orphan sweep must never
        touch them). The log is append-only, so refreshes read only
        versions newer than the last scan — a long-lived lake's
        restore does not re-fetch its whole history."""
        if self._committed_txn is None:
            self._committed_txn = set()
            self._scanned_upto = -1
        elif not refresh:
            return self._committed_txn
        for v in self.store.list_log_versions():
            if v <= self._scanned_upto:
                continue
            for action in self._read_log_actions(v):
                txn = action.get("txn")
                if txn and txn.get("appId") == self._app_id:
                    self._committed_txn.add(int(txn.get("version", -1)))
                add = action.get("add")
                if add is not None:
                    self._log_paths.add(add["path"])
            self._scanned_upto = max(self._scanned_upto, v)
        return self._committed_txn

    def _committed_txn_versions(self, refresh: bool = False) -> set[int]:
        return self._scan_log(refresh)

    # -- encoding ----------------------------------------------------------

    def _rows_to_parquet(self, rows: list[tuple]) -> bytes:
        import pyarrow as pa
        import pyarrow.parquet as pq

        arrays = {
            c: [r[j] for r in rows] for j, c in enumerate(self.cols)
        }
        arrays["time"] = [r[len(self.cols)] for r in rows]
        arrays["diff"] = [r[len(self.cols) + 1] for r in rows]
        buf = _io.BytesIO()
        pq.write_table(pa.table(arrays), buf)
        return buf.getvalue()

    def _batches_to_parquet(self, chunks: list[tuple]) -> list[bytes]:
        """Columnar part images: each buffered record batch gains its
        commit-time column and the column buffers go straight into
        parquet — zero row materialization. Batches are grouped by
        schema (an all-null column types differently across chunks)
        and each group becomes one part."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        groups: dict[str, list] = {}
        for rb, t in chunks:
            n = rb.num_rows
            arrays = [
                rb.column(rb.schema.get_field_index(c)) for c in self.cols
            ]
            arrays.append(pa.array([t] * n, pa.int64()))
            arrays.append(rb.column(rb.schema.get_field_index("diff")))
            out = pa.RecordBatch.from_arrays(
                arrays, names=self.cols + ["time", "diff"]
            )
            groups.setdefault(str(out.schema), []).append(out)
        parts = []
        for batches in groups.values():
            buf = _io.BytesIO()
            pq.write_table(pa.Table.from_batches(batches), buf)
            parts.append(buf.getvalue())
        return parts

    def _drain_payloads(self) -> list[bytes]:
        """One parquet part image per buffered representation (rows /
        per-schema arrow groups), draining both buffers."""
        out = []
        if self._buf:
            rows, self._buf = self._buf, []
            out.append(self._rows_to_parquet(rows))
        if self._abuf:
            chunks, self._abuf = self._abuf, []
            out.extend(self._batches_to_parquet(chunks))
        return out

    def _note_egress(self, seconds: float) -> None:
        from pathway_tpu.io.txn import note_egress_seconds

        note_egress_seconds(self._stats, self.name, seconds)

    @staticmethod
    def _add_action(path: str, size: int) -> dict:
        return {
            "add": {
                "path": path,
                "partitionValues": {},
                "size": size,
                "modificationTime": int(time.time() * 1000),
                "dataChange": True,
            }
        }

    # -- engine callbacks --------------------------------------------------

    def on_batch(self, time_, deltas) -> None:
        t0 = time.perf_counter()
        for _k, row, d in deltas:
            self._buf.append(tuple(row) + (time_, d))
        self._note_egress(time.perf_counter() - t0)

    def on_batch_arrow(self, time_, rb) -> None:
        """Columnar delivery (ISSUE 14): buffer the record batch as-is;
        the part flush writes its column buffers directly."""
        t0 = time.perf_counter()
        if rb is not None and rb.num_rows:
            self._abuf.append((rb, time_))
        self._note_egress(time.perf_counter() - t0)

    def on_time_end(self, time_) -> None:
        if self._txn:
            self._stage_part()
        else:
            self._flush()

    def on_end(self) -> None:
        if not self._txn:
            self._flush(force=True)
        # txn mode: the runtime's final cut already pre-committed and
        # finalized the tail before on_end fires

    # -- plain (non-epoch-aligned) path ------------------------------------

    def _flush(self, force: bool = False) -> None:
        if not (self._buf or self._abuf):
            return
        if (
            not force
            and self.min_commit_frequency is not None
            and (time.monotonic() - self._last_commit) * 1000.0
            < self.min_commit_frequency
        ):
            return
        self._last_commit = time.monotonic()
        actions = []
        for data in self._drain_payloads():
            part = f"part-{uuid.uuid4().hex}.parquet"
            self.store.write(part, data)
            actions.append(self._add_action(part, len(data)))
        self._commit(actions)

    # -- the 2PC verbs -----------------------------------------------------

    def arm(
        self, *, stats=None, txn=False, rank=0, world=1, epoch=0,
        lineage=None,
    ):
        from pathway_tpu.io.txn import txn_enabled

        self._stats = stats
        self._txn = txn and txn_enabled()
        self._rank = rank
        self._world = world
        self._epoch = epoch
        # the txn dedup appId is scoped to the PERSISTENCE LINEAGE
        # (a marker minted on the store's first run): snapshot tags
        # restart at 1 whenever the persistence directory is cleared,
        # and an unscoped appId would let a kept lake's OLD txn actions
        # mask the new lineage's first tags — finalize would then skip
        # the commit but still delete the manifests, silently losing
        # every row of the new run's first cuts
        if lineage:
            new_id = f"{self.TXN_APP_ID}-{lineage}"
            if new_id != self._app_id:
                self._app_id = new_id
                # any cached log scan keyed the old appId
                self._committed_txn = None
                self._scanned_upto = -1

    def _stage_dir(self, rank: int) -> str:
        return f"_pw_txn/stage/r{rank}"

    def _manifest_dir(self, rank: int) -> str:
        return f"_pw_txn/manifest/r{rank}"

    def _stage_part(self, force: bool = False) -> None:
        """Flush buffered output into staged parquet parts — invisible
        to readers (no log reference) until a finalized log version
        adds them. Row and arrow buffers stage as separate parts (one
        per representation/schema). Rate-limited within the epoch by
        min_commit_frequency; pre-commit always forces."""
        if not (self._buf or self._abuf):
            return
        if (
            not force
            and self.min_commit_frequency is not None
            and (time.monotonic() - self._last_commit) * 1000.0
            < self.min_commit_frequency
        ):
            return
        from pathway_tpu.internals import faults as _faults

        _faults.fault_point("sink.stage")
        self._last_commit = time.monotonic()
        staged = 0
        for data in self._drain_payloads():
            path = (
                f"{self._stage_dir(self._rank)}/"
                f"part-{self._incarnation}-{uuid.uuid4().hex}.parquet"
            )
            self.store.write(path, data)
            self._open_parts.append({"path": path, "size": len(data)})
            staged += 1
        if staged and self._stats is not None:
            self._stats.on_sink_staged(self.name, staged)
            self._note_lag()

    def precommit(self, tag: int) -> None:
        if not self._txn:
            return
        self._stage_part(force=True)
        self._staged_tag = max(self._staged_tag, tag)
        if not self._open_parts:
            return
        manifest = {
            "tag": tag,
            "rank": self._rank,
            "parts": self._open_parts,
        }
        self.store.write(
            f"{self._manifest_dir(self._rank)}/{tag:020d}.json",
            _json.dumps(manifest).encode(),
        )
        self._open_parts = []
        self._note_lag()

    def _log_owner(self) -> bool:
        from pathway_tpu.io.txn import SHARD_OWNER

        return SHARD_OWNER(0, self._world) == self._rank

    def _pending_manifests(self) -> dict[int, list[dict]]:
        """tag -> [manifest, ...] across ALL rank partitions. A
        manifest that fails to parse is a torn pre-commit leftover from
        a store without atomic writes — its cut can never have
        committed (the marker moves only after precommit completed), so
        skipping it is the discard verdict, not data loss; it must not
        turn every later recovery into a crash loop."""
        out: dict[int, list[dict]] = {}
        for key in self.store.list("_pw_txn/manifest/"):
            if ".tmp-" in key:
                continue
            raw = self.store.read(key)
            if raw is None:
                continue
            try:
                m = _json.loads(raw.decode())
                tag = int(m["tag"])
            except (ValueError, KeyError, UnicodeDecodeError):
                continue
            m["_key"] = key
            out.setdefault(tag, []).append(m)
        return out

    def _commit_tag(self, tag: int, manifests: list[dict]) -> None:
        from pathway_tpu.internals import faults as _faults

        _faults.fault_point("sink.finalize")
        adds = [
            self._add_action(p["path"], p["size"])
            for m in sorted(manifests, key=lambda m: m["rank"])
            for p in m["parts"]
        ]
        self._commit(
            adds
            + [
                {
                    "txn": {
                        "appId": self._app_id,
                        "version": tag,
                        "lastUpdated": int(time.time() * 1000),
                    }
                }
            ]
        )
        self._committed_txn_versions().add(tag)
        # the just-committed parts are log-referenced data now — the
        # recovery orphan sweep must never see them as orphans
        self._log_paths.update(a["add"]["path"] for a in adds)
        if self._stats is not None:
            self._stats.on_sink_finalized(self.name, len(adds))

    def finalize(self, tag: int) -> None:
        """The marker landed at ``tag``: the log owner folds every
        covered pending manifest set into one log version per tag,
        through the shared ``sink_may_finalize`` transition."""
        if not self._txn:
            return
        self._finalized_tag = max(self._finalized_tag, tag)
        if not self._log_owner():
            self._note_lag()
            return
        from pathway_tpu.io.txn import SINK_MAY_FINALIZE

        committed = self._committed_txn_versions()
        for u, manifests in sorted(self._pending_manifests().items()):
            if not SINK_MAY_FINALIZE(u, tag):
                continue
            if u not in committed:
                self._commit_tag(u, manifests)
            for m in manifests:
                self.store.delete(m["_key"])
        self._note_lag()

    def recover(self, marker_tag, world: int) -> None:
        """Restore-time scan: one shared ``sink_recover`` verdict per
        pending manifest — (re-)commit everything the cut covers,
        discard the rest with its parts. Partition claims route through
        ``shard_owner``, so a dead world's pending partitions are
        re-owned after a rescale; the log's ``txn`` actions make double
        recovery idempotent.

        Scan ORDER is load-bearing: manifests are read BEFORE the log.
        A committed part's lifecycle is manifest → log commit → manifest
        delete, so a sweeper that misses the manifest (deleted) is
        guaranteed to see the commit in its LATER log scan — reading
        the log first would open a window where a peer's concurrent
        recovery commit makes a committed part look orphaned."""
        from pathway_tpu.internals import faults as _faults
        from pathway_tpu.io.txn import SHARD_OWNER, SINK_RECOVER

        self._world = world
        _faults.fault_point("sink.recover")
        pending = self._pending_manifests()
        committed = self._committed_txn_versions(refresh=True)
        recovered = aborted = 0
        if marker_tag is not None and self._open_parts:
            # pre-restore staging under a committed marker: the only
            # rows staged before recovery are re-injected static rows,
            # which the restored cut already committed — keeping them
            # would re-commit them at the next cut, once per restart
            for p in self._open_parts:
                self.store.delete(p["path"])
                aborted += 1
            self._open_parts = []
        for u, manifests in sorted(pending.items()):
            verdict = SINK_RECOVER(u, marker_tag)
            if verdict == "finalize":
                # the whole tag's manifest set commits as one version:
                # the log owner claims it (every other rank leaves the
                # manifests for the owner's scan)
                if self._log_owner():
                    if u not in committed:
                        self._commit_tag(u, manifests)
                        recovered += sum(len(m["parts"]) for m in manifests)
                    for m in manifests:
                        self.store.delete(m["_key"])
                continue
            # discard: per-partition, claimed through the shard mint
            for m in manifests:
                if SHARD_OWNER(int(m["rank"]), world) != self._rank:
                    continue
                for p in m["parts"]:
                    self.store.delete(p["path"])
                    aborted += 1
                self.store.delete(m["_key"])
        # orphaned staged parts (un-manifested leftovers of dead
        # incarnations). Each rank sweeps only partitions with NO live
        # writer it could race: its OWN partition (it knows its own
        # incarnation token) and dead partitions beyond the current
        # world (claimed through the shard mint; a rank id >= world has
        # no process). Live peer partitions are left to their own
        # ranks' recoveries. Parts referenced by a pending manifest or
        # by the log are never orphans — safe under the manifest-then-
        # log scan order above.
        needed = frozenset(
            pp["path"]
            for u, ms in pending.items()
            if SINK_RECOVER(u, marker_tag) == "finalize"
            for m in ms
            for pp in m["parts"]
        )
        for key in self.store.list("_pw_txn/stage/"):
            if key in needed or key in self._log_paths:
                continue
            try:
                p = int(key.split("/r", 1)[1].split("/", 1)[0])
            except (IndexError, ValueError):
                continue
            if p == self._rank:
                if (
                    f"-{self._incarnation}-" in key
                    and marker_tag is None
                ):
                    continue  # live from-scratch staging (static rows)
            elif p < world or SHARD_OWNER(p, world) != self._rank:
                continue  # a live peer's partition, or not our claim
            self.store.delete(key)
            aborted += 1
        if self._stats is not None:
            if recovered:
                self._stats.on_sink_recovered(self.name, recovered)
            if aborted:
                self._stats.on_sink_aborted(self.name, aborted)
        if marker_tag is not None:
            self._staged_tag = max(self._staged_tag, marker_tag)
            self._finalized_tag = max(self._finalized_tag, marker_tag)
        self._note_lag()

    def abort_for_rollback(self) -> None:
        n = len(self._open_parts)
        for p in self._open_parts:
            try:
                self.store.delete(p["path"])
            except Exception:
                pass
        self._open_parts = []
        self._buf = []
        self._abuf = []
        if n and self._stats is not None:
            self._stats.on_sink_aborted(self.name, n)

    def _note_lag(self) -> None:
        if self._stats is not None and self._txn:
            self._stats.on_sink_epoch_lag(
                self.name,
                max(0, self._staged_tag - self._finalized_tag),
            )


def write(
    table,
    uri,
    *,
    min_commit_frequency: int | None = 60_000,
    s3_connection_settings=None,
    name: str | None = None,
    **kwargs,
) -> None:
    """Write the table's change stream into a Delta Lake — local path or
    ``s3://bucket/prefix`` (reference: io/deltalake/__init__.py:170 —
    output rows carry ``time`` and ``diff`` columns). Multi-rank runs
    write PARTITIONED: each rank commits its own parquet data files and
    one rank appends the log version (no gather leg). Under
    ``OPERATOR_PERSISTING`` the writer is a transactional sink: log
    commits are tied to the engine's epoch commit markers
    (``min_commit_frequency`` then rate-limits staged part writes
    *within* an epoch only), so committed lake contents are
    bit-identical across rollback and rescale (io/txn.py; ISSUE 12)."""
    store = _make_store(uri, s3_connection_settings)
    cols = table.column_names()
    schema_dtypes = table._schema_cls._dtypes()
    dtypes = [schema_dtypes.get(c) for c in cols]
    sink = TxnDeltaSink(store, cols, dtypes, min_commit_frequency)
    # per-output metrics label (two lakes in one program must not merge
    # their 2PC counters under one name)
    base = os.path.basename(str(os.fspath(uri)).rstrip("/"))
    sink.name = name or f"deltalake:{base or uri}"

    def lower(ctx):
        # per-rank partitioned egress (no gather exchange) — except in
        # the emulated-rank CI lane, where thread-ranks share one
        # process and a single writer must own the side effects
        partitioned = not getattr(
            ctx.scope.runtime, "_lane_emulated", False
        )
        ctx.scope.output(
            ctx.engine_table(table),
            on_batch=sink.on_batch,
            on_batch_arrow=sink.on_batch_arrow,
            arrow_cols=cols,
            on_time_end=sink.on_time_end,
            on_end=sink.on_end,
            txn_sink=sink,
            partitioned=partitioned,
        )

    G.add_operator([table], [], lower, "deltalake_write", is_output=True)
