"""pw.io.deltalake — Delta Lake connector (reference:
python/pathway/io/deltalake over the native DeltaTableReader/Writer,
src/connectors/data_storage.rs:1902/:1611).

Redesigned transport: no delta-rs — the Delta Lake format IS an open
spec (parquet parts + a JSON transaction log under ``_delta_log/``), and
pyarrow is in the image, so this build implements the protocol directly:

* ``write`` appends one parquet part + one log version per non-empty
  commit window, with ``protocol``/``metaData`` actions minted at table
  creation (schema inferred from the table's dtypes);
* ``read`` polls ``_delta_log`` versions in order and ingests the
  ``add`` actions of each (append-only semantics, like the reference's
  reader at io/deltalake/__init__.py:38).

Local filesystem lakes are supported; S3 lakes raise with a clear
message (the object-store transport exists in io/_s3.py — wiring the
log store onto it is future work).
"""

from __future__ import annotations

import json as _json
import os
import time
import uuid
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read

__all__ = ["read", "write"]

_DELTA_TYPES = {
    dt.INT: "long",
    dt.FLOAT: "double",
    dt.STR: "string",
    dt.BOOL: "boolean",
    dt.BYTES: "binary",
}


def _require_local(uri) -> str:
    uri = os.fspath(uri)
    if str(uri).startswith(("s3://", "s3a://")):
        raise NotImplementedError(
            "pw.io.deltalake: S3-backed lakes are not wired yet in this "
            "build — use a local path (the reference supports both, "
            "io/deltalake/__init__.py:52)"
        )
    return str(uri)


def _log_dir(uri: str) -> str:
    return os.path.join(uri, "_delta_log")


def _delta_type(col_dtype) -> str:
    return _DELTA_TYPES.get(col_dtype, "string")


class _DeltaSubject(ConnectorSubject):
    _deletions_enabled = False  # append-only source (reference contract)

    def __init__(self, uri, columns, mode, refresh_interval=1.0):
        super().__init__()
        self.uri = uri
        self.columns = columns
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._version = 0
        self._stop = False

    def _scan_versions(self) -> bool:
        import pyarrow.parquet as pq

        log = _log_dir(self.uri)
        advanced = False
        while True:
            path = os.path.join(log, f"{self._version:020d}.json")
            if not os.path.exists(path):
                return advanced
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    action = _json.loads(line)
                    add = action.get("add")
                    if add is None:
                        continue
                    part = os.path.join(self.uri, add["path"])
                    table = pq.read_table(part)
                    cols = [
                        table.column(c).to_pylist()
                        if c in table.column_names
                        else [None] * table.num_rows
                        for c in self.columns
                    ]
                    for i in range(table.num_rows):
                        key = ref_scalar("delta", add["path"], i)
                        self._upsert(
                            key,
                            {
                                c: cols[j][i]
                                for j, c in enumerate(self.columns)
                            },
                        )
            self._version += 1
            advanced = True

    def run(self):
        self._scan_versions()
        self.commit()
        if self.mode == "static":
            return
        while not self._stop:
            time.sleep(self.refresh_interval)
            if self._scan_versions():
                self.commit()

    def on_stop(self):
        self._stop = True

    def snapshot_state(self):
        return {"version": self._version}

    def seek(self, state) -> None:
        self._version = int(state.get("version", 0))


def read(
    uri,
    schema: type[Schema],
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 1.0,
    name: str | None = None,
    **kwargs,
):
    """Read an append-only table from a Delta Lake (reference:
    io/deltalake/__init__.py:38)."""
    uri = _require_local(uri)
    subject = _DeltaSubject(
        uri, schema.column_names(), mode, refresh_interval=refresh_interval
    )
    return python_read(
        subject,
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"deltalake:{uri}",
    )


def write(
    table,
    uri,
    *,
    min_commit_frequency: int | None = 60_000,
    name: str | None = None,
    **kwargs,
) -> None:
    """Write the table's change stream into a Delta Lake (reference:
    io/deltalake/__init__.py:170 — output rows carry ``time`` and
    ``diff`` columns; one parquet part + log version per commit window,
    rate-limited by min_commit_frequency)."""
    uri = _require_local(uri)
    cols = table.column_names()
    schema_dtypes = table._schema_cls._dtypes()
    dtypes = [schema_dtypes.get(c) for c in cols]
    state: dict[str, Any] = {
        "buf": [], "version": None, "last_commit": 0.0,
    }

    def _next_version() -> int:
        log = _log_dir(uri)
        os.makedirs(log, exist_ok=True)
        if state["version"] is None:
            existing = [
                int(f.split(".")[0])
                for f in os.listdir(log)
                if f.endswith(".json") and f.split(".")[0].isdigit()
            ]
            state["version"] = (max(existing) + 1) if existing else 0
            if state["version"] == 0:
                try:
                    _write_version(0, _bootstrap_actions())
                except FileExistsError:
                    pass  # a concurrent writer bootstrapped the table
                state["version"] = 1
        v = state["version"]
        state["version"] += 1
        return v

    def _bootstrap_actions() -> list[dict]:
        fields = [
            {
                "name": c,
                "type": _delta_type(d),
                "nullable": True,
                "metadata": {},
            }
            for c, d in zip(cols, dtypes)
        ] + [
            {"name": "time", "type": "long", "nullable": False, "metadata": {}},
            {"name": "diff", "type": "long", "nullable": False, "metadata": {}},
        ]
        return [
            {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
            {
                "metaData": {
                    "id": str(uuid.uuid4()),
                    "format": {"provider": "parquet", "options": {}},
                    "schemaString": _json.dumps(
                        {"type": "struct", "fields": fields}
                    ),
                    "partitionColumns": [],
                    "configuration": {},
                    "createdTime": int(time.time() * 1000),
                }
            },
        ]

    def _write_version(v: int, actions: list[dict]) -> None:
        # The Delta protocol requires mutually-exclusive version creation:
        # two writers must never both claim version N. os.link from a
        # private tmp file is atomic-exclusive (raises FileExistsError if
        # a concurrent writer — a second pipeline or an external delta-rs
        # client — committed N first), unlike os.replace which would
        # silently clobber the other commit's log entry.
        path = os.path.join(_log_dir(uri), f"{v:020d}.json")
        tmp = path + f".tmp-{uuid.uuid4().hex}"
        with open(tmp, "w") as f:
            for a in actions:
                f.write(_json.dumps(a) + "\n")
        try:
            os.link(tmp, path)
        except OSError as exc:
            if isinstance(exc, FileExistsError):
                raise
            # filesystem without hard links (exFAT, some FUSE/NFS mounts):
            # fall back to os.replace — single-writer still safe, only the
            # multi-writer exclusivity guarantee is lost there
            os.replace(tmp, path)
            tmp = None
        finally:
            if tmp is not None:
                os.unlink(tmp)

    def _commit(actions: list[dict]) -> None:
        while True:
            v = _next_version()
            try:
                _write_version(v, actions)
                return
            except FileExistsError:
                state["version"] = None  # lost the race: re-list and retry

    def _flush(force: bool = False):
        if not state["buf"]:
            return
        if (
            not force
            and min_commit_frequency is not None
            and (time.monotonic() - state["last_commit"]) * 1000.0
            < min_commit_frequency
        ):
            return
        import pyarrow as pa
        import pyarrow.parquet as pq

        rows = state["buf"]
        state["buf"] = []
        state["last_commit"] = time.monotonic()
        arrays = {
            c: [r[j] for r in rows] for j, c in enumerate(cols)
        }
        arrays["time"] = [r[len(cols)] for r in rows]
        arrays["diff"] = [r[len(cols) + 1] for r in rows]
        part = f"part-{uuid.uuid4().hex}.parquet"
        os.makedirs(uri, exist_ok=True)
        path = os.path.join(uri, part)
        pq.write_table(pa.table(arrays), path)
        _commit(
            [
                {
                    "add": {
                        "path": part,
                        "partitionValues": {},
                        "size": os.path.getsize(path),
                        "modificationTime": int(time.time() * 1000),
                        "dataChange": True,
                    }
                }
            ],
        )

    def on_change(key, row, time_, diff):
        state["buf"].append(tuple(row) + (time_, diff))

    def on_time_end(time_):
        _flush()

    def on_end():
        _flush(force=True)

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table), on_change=on_change,
            on_time_end=on_time_end, on_end=on_end,
        )

    G.add_operator([table], [], lower, "deltalake_write", is_output=True)
