"""pw.io.deltalake — connector surface (reference: python/pathway/io/deltalake (native DeltaTableReader/Writer data_storage.rs:1902/:1611)).

Client transport gated on its library; the configuration surface matches
the reference so templates parse and fail only at run time with a clear
dependency error."""

from __future__ import annotations

from pathway_tpu.io._gated import require


def read(*args, schema=None, mode="streaming", autocommit_duration_ms=1500,
         name=None, **kwargs):
    require('deltalake')
    raise NotImplementedError(
        "pw.io.deltalake.read: client library found, but no deltalake service "
        "transport is wired in this build"
    )


def write(table, *args, name=None, **kwargs):
    require('deltalake')
    raise NotImplementedError(
        "pw.io.deltalake.write: client library found, but no deltalake service "
        "transport is wired in this build"
    )
