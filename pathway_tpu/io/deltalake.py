"""pw.io.deltalake — Delta Lake connector (reference:
python/pathway/io/deltalake over the native DeltaTableReader/Writer,
src/connectors/data_storage.rs:1902/:1611).

Redesigned transport: no delta-rs — the Delta Lake format IS an open
spec (parquet parts + a JSON transaction log under ``_delta_log/``), and
pyarrow is in the image, so this build implements the protocol directly:

* ``write`` appends one parquet part + one log version per non-empty
  commit window, with ``protocol``/``metaData`` actions minted at table
  creation (schema inferred from the table's dtypes);
* ``read`` polls ``_delta_log`` versions in order and ingests the
  ``add`` actions of each (append-only semantics, like the reference's
  reader at io/deltalake/__init__.py:38).

Storage rides a small store abstraction: local filesystem, or any
S3-compatible object store through the dependency-free SigV4 transport
(io/_s3.py) — ``s3://bucket/prefix`` lakes read and write directly on
object storage like the reference (data_storage.rs:1611,1902), with
log-commit exclusivity via conditional PUT (``If-None-Match: *``).
"""

from __future__ import annotations

import io as _io
import json as _json
import os
import time
import uuid
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read

__all__ = ["read", "write"]

_DELTA_TYPES = {
    dt.INT: "long",
    dt.FLOAT: "double",
    dt.STR: "string",
    dt.BOOL: "boolean",
    dt.BYTES: "binary",
}


class _LocalStore:
    """Lake storage on the local filesystem."""

    def __init__(self, root: str):
        self.root = root

    def read(self, rel: str) -> bytes | None:
        try:
            with open(os.path.join(self.root, rel), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def write(self, rel: str, data: bytes) -> None:
        path = os.path.join(self.root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)

    def write_exclusive(self, rel: str, data: bytes) -> None:
        """Create-if-absent (Delta log commits must be mutually
        exclusive: two writers must never both claim version N).
        os.link from a private tmp file is atomic-exclusive; filesystems
        without hard links fall back to os.replace (single-writer safe)."""
        path = os.path.join(self.root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp-{uuid.uuid4().hex}"
        with open(tmp, "wb") as f:
            f.write(data)
        try:
            os.link(tmp, path)
        except OSError as exc:
            if isinstance(exc, FileExistsError):
                raise
            os.replace(tmp, path)
            tmp = None
        finally:
            if tmp is not None:
                os.unlink(tmp)

    def list_log_versions(self) -> list[int]:
        log = os.path.join(self.root, "_delta_log")
        try:
            names = os.listdir(log)
        except FileNotFoundError:
            return []
        return sorted(
            int(f.split(".")[0])
            for f in names
            if f.endswith(".json") and f.split(".")[0].isdigit()
        )


class _S3Store:
    """Lake storage on an S3-compatible object store via the SigV4
    transport (reference: the delta-rs S3 log store,
    data_storage.rs:1611)."""

    def __init__(self, uri: str, settings=None, opener=None):
        from pathway_tpu.io._s3 import AwsS3Settings, S3Client

        rest = uri.split("://", 1)[1]
        bucket, _, prefix = rest.partition("/")
        if settings is None:
            settings = AwsS3Settings.new_from_path(uri)
        self.client = S3Client(settings.with_bucket(bucket), opener=opener)
        self.prefix = prefix.strip("/")

    def _key(self, rel: str) -> str:
        rel = rel.replace(os.sep, "/")
        return f"{self.prefix}/{rel}" if self.prefix else rel

    def read(self, rel: str) -> bytes | None:
        import urllib.error

        try:
            return self.client.get_object(self._key(rel))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def write(self, rel: str, data: bytes) -> None:
        self.client.put_object(self._key(rel), data)

    def write_exclusive(self, rel: str, data: bytes) -> None:
        self.client.put_object_if_absent(self._key(rel), data)

    def list_log_versions(self) -> list[int]:
        log_prefix = self._key("_delta_log/")
        out = []
        for obj in self.client.list_objects(prefix=log_prefix):
            name = obj.key.rsplit("/", 1)[-1]
            if name.endswith(".json") and name.split(".")[0].isdigit():
                out.append(int(name.split(".")[0]))
        return sorted(out)


def _make_store(uri, s3_connection_settings=None):
    uri = str(os.fspath(uri))
    if uri.startswith(("s3://", "s3a://")):
        return _S3Store(uri, settings=s3_connection_settings)
    return _LocalStore(uri)


def _delta_type(col_dtype) -> str:
    return _DELTA_TYPES.get(col_dtype, "string")


class _DeltaSubject(ConnectorSubject):
    _deletions_enabled = False  # append-only source (reference contract)

    def __init__(self, store, columns, mode, refresh_interval=1.0):
        super().__init__()
        self.store = store
        self.columns = columns
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._version = 0
        self._stop = False

    def _scan_versions(self) -> bool:
        import pyarrow.parquet as pq

        advanced = False
        while True:
            data = self.store.read(
                os.path.join("_delta_log", f"{self._version:020d}.json")
            )
            if data is None:
                return advanced
            actions = [
                _json.loads(line)
                for line in data.decode().splitlines()
                if line.strip()
            ]
            # read every referenced part BEFORE emitting any row: a part
            # not yet visible (eventually-consistent store, torn upload)
            # must not advance the version — the whole version retries on
            # the next poll; in static mode a missing part is data loss
            # and fails loudly
            parts: dict[str, bytes] = {}
            for action in actions:
                add = action.get("add")
                if add is None:
                    continue
                blob = self.store.read(add["path"])
                if blob is None:
                    if self.mode == "static":
                        raise FileNotFoundError(
                            f"delta part {add['path']!r} referenced by log "
                            f"version {self._version} is missing"
                        )
                    return advanced  # retry this version next refresh
                parts[add["path"]] = blob
            for action in actions:
                add = action.get("add")
                if add is None:
                    continue
                # use_threads=False: this runs on a connector thread, and
                # pyarrow's CPU pool first spawned from a non-main thread
                # aborts the process at exit ("terminate called without an
                # active exception", ~30% of runs on pyarrow 22); parts
                # are small, the pool buys nothing here
                table = pq.read_table(
                    _io.BytesIO(parts[add["path"]]), use_threads=False
                )
                cols = [
                    table.column(c).to_pylist()
                    if c in table.column_names
                    else [None] * table.num_rows
                    for c in self.columns
                ]
                for i in range(table.num_rows):
                    key = ref_scalar("delta", add["path"], i)
                    self._upsert(
                        key,
                        {
                            c: cols[j][i]
                            for j, c in enumerate(self.columns)
                        },
                    )
            self._version += 1
            advanced = True

    def run(self):
        self._scan_versions()
        self.commit()
        if self.mode == "static":
            return
        while not self._stop:
            time.sleep(self.refresh_interval)
            if self._scan_versions():
                self.commit()

    def on_stop(self):
        self._stop = True

    def snapshot_state(self):
        return {"version": self._version}

    def seek(self, state) -> None:
        self._version = int(state.get("version", 0))


def read(
    uri,
    schema: type[Schema],
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 1.0,
    s3_connection_settings=None,
    name: str | None = None,
    **kwargs,
):
    """Read an append-only table from a Delta Lake — local path or
    ``s3://bucket/prefix`` (reference: io/deltalake/__init__.py:38, with
    the same AwsS3Settings-or-path-derived credentials contract :25)."""
    store = _make_store(uri, s3_connection_settings)
    subject = _DeltaSubject(
        store, schema.column_names(), mode,
        refresh_interval=refresh_interval,
    )
    return python_read(
        subject,
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"deltalake:{uri}",
    )


def write(
    table,
    uri,
    *,
    min_commit_frequency: int | None = 60_000,
    s3_connection_settings=None,
    name: str | None = None,
    **kwargs,
) -> None:
    """Write the table's change stream into a Delta Lake — local path or
    ``s3://bucket/prefix`` (reference: io/deltalake/__init__.py:170 —
    output rows carry ``time`` and ``diff`` columns; one parquet part +
    log version per commit window, rate-limited by
    min_commit_frequency)."""
    store = _make_store(uri, s3_connection_settings)
    cols = table.column_names()
    schema_dtypes = table._schema_cls._dtypes()
    dtypes = [schema_dtypes.get(c) for c in cols]
    state: dict[str, Any] = {
        "buf": [], "version": None, "last_commit": 0.0,
    }

    def _next_version() -> int:
        if state["version"] is None:
            existing = store.list_log_versions()
            state["version"] = (max(existing) + 1) if existing else 0
            if state["version"] == 0:
                try:
                    _write_version(0, _bootstrap_actions())
                except FileExistsError:
                    pass  # a concurrent writer bootstrapped the table
                state["version"] = 1
        v = state["version"]
        state["version"] += 1
        return v

    def _bootstrap_actions() -> list[dict]:
        fields = [
            {
                "name": c,
                "type": _delta_type(d),
                "nullable": True,
                "metadata": {},
            }
            for c, d in zip(cols, dtypes)
        ] + [
            {"name": "time", "type": "long", "nullable": False, "metadata": {}},
            {"name": "diff", "type": "long", "nullable": False, "metadata": {}},
        ]
        return [
            {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
            {
                "metaData": {
                    "id": str(uuid.uuid4()),
                    "format": {"provider": "parquet", "options": {}},
                    "schemaString": _json.dumps(
                        {"type": "struct", "fields": fields}
                    ),
                    "partitionColumns": [],
                    "configuration": {},
                    "createdTime": int(time.time() * 1000),
                }
            },
        ]

    def _write_version(v: int, actions: list[dict]) -> None:
        # The Delta protocol requires mutually-exclusive version creation:
        # two writers must never both claim version N. The store's
        # write_exclusive raises FileExistsError if a concurrent writer —
        # a second pipeline or an external delta-rs client — committed N
        # first (local: atomic os.link; S3: conditional PUT).
        data = "".join(_json.dumps(a) + "\n" for a in actions).encode()
        store.write_exclusive(
            os.path.join("_delta_log", f"{v:020d}.json"), data
        )

    def _commit(actions: list[dict]) -> None:
        while True:
            v = _next_version()
            try:
                _write_version(v, actions)
                return
            except FileExistsError:
                state["version"] = None  # lost the race: re-list and retry

    def _flush(force: bool = False):
        if not state["buf"]:
            return
        if (
            not force
            and min_commit_frequency is not None
            and (time.monotonic() - state["last_commit"]) * 1000.0
            < min_commit_frequency
        ):
            return
        import pyarrow as pa
        import pyarrow.parquet as pq

        rows = state["buf"]
        state["buf"] = []
        state["last_commit"] = time.monotonic()
        arrays = {
            c: [r[j] for r in rows] for j, c in enumerate(cols)
        }
        arrays["time"] = [r[len(cols)] for r in rows]
        arrays["diff"] = [r[len(cols) + 1] for r in rows]
        part = f"part-{uuid.uuid4().hex}.parquet"
        buf = _io.BytesIO()
        pq.write_table(pa.table(arrays), buf)
        data = buf.getvalue()
        store.write(part, data)
        _commit(
            [
                {
                    "add": {
                        "path": part,
                        "partitionValues": {},
                        "size": len(data),
                        "modificationTime": int(time.time() * 1000),
                        "dataChange": True,
                    }
                }
            ],
        )

    def on_change(key, row, time_, diff):
        state["buf"].append(tuple(row) + (time_, diff))

    def on_time_end(time_):
        _flush()

    def on_end():
        _flush(force=True)

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table), on_change=on_change,
            on_time_end=on_time_end, on_end=on_end,
        )

    G.add_operator([table], [], lower, "deltalake_write", is_output=True)
