"""pw.io.minio — MinIO connector (reference: python/pathway/io/minio —
the S3 protocol against a path-style MinIO endpoint)."""

from __future__ import annotations

from pathway_tpu.io._s3 import AwsS3Settings
from pathway_tpu.io.s3 import read as _s3_read, write as _s3_write

__all__ = ["MinIOSettings", "read", "write"]


class MinIOSettings:
    """MinIO connection settings (reference: io/minio/__init__.py:15 —
    same constructor surface; path-style access defaults on)."""

    def __init__(
        self,
        endpoint,
        bucket_name,
        access_key,
        secret_access_key,
        *,
        with_path_style=True,
        region=None,
    ):
        self.endpoint = endpoint
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style
        self.region = region

    def create_aws_settings(self) -> AwsS3Settings:
        return AwsS3Settings(
            endpoint=self.endpoint,
            bucket_name=self.bucket_name,
            access_key=self.access_key,
            secret_access_key=self.secret_access_key,
            with_path_style=self.with_path_style,
            region=self.region,
        )


def read(
    path: str,
    minio_settings: MinIOSettings,
    format: str = "csv",
    **kwargs,
):
    return _s3_read(
        path, format, aws_s3_settings=minio_settings.create_aws_settings(),
        **kwargs,
    )


def write(table, path: str, minio_settings: MinIOSettings, **kwargs) -> None:
    return _s3_write(
        table, path, aws_s3_settings=minio_settings.create_aws_settings(),
        **kwargs,
    )
