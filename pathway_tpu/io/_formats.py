"""Output formatter suite (reference: src/connectors/data_format.rs —
trait Formatter :452; DsvFormatter :941, SingleColumn :1014, PsqlUpdates
:1632, PsqlSnapshot :1691, JsonLines :1829, Bson :1982, Null :1869).

A formatter turns one output delta ``(key, values, time, diff)`` into the
wire payload(s) for a writer. Formatters are transport-independent and
fully testable offline; gated connectors (postgres/mongodb) use them once
their client libraries exist, and `pw.io.subscribe`-style sinks can use
them directly.
"""

from __future__ import annotations

import datetime as _dt
import json as _json
import struct
from typing import Any, Sequence

from pathway_tpu.internals.api import Json, Pointer


class FormatterContext:
    """One formatted output event (reference: FormatterContext,
    data_format.rs:328): payloads + key + time + diff."""

    __slots__ = ("payloads", "key", "time", "diff")

    def __init__(self, payloads, key, time, diff):
        self.payloads = payloads
        self.key = key
        self.time = time
        self.diff = diff


def _plain(v: Any) -> Any:
    if isinstance(v, Json):
        return v.value
    if isinstance(v, Pointer):
        return repr(v)
    return v


class JsonLinesFormatter:
    """reference: data_format.rs:1829 — one JSON object per delta with
    time/diff fields."""

    def __init__(self, value_fields: Sequence[str]):
        self.value_fields = list(value_fields)

    def format(self, key, values, time, diff) -> FormatterContext:
        payload = {
            f: _plain(v) for f, v in zip(self.value_fields, values)
        }
        payload["time"] = time
        payload["diff"] = diff
        line = _json.dumps(payload, default=str).encode() + b"\n"
        return FormatterContext([line], key, time, diff)


class DsvFormatter:
    """reference: data_format.rs:941 — delimiter-separated values plus
    time/diff columns."""

    def __init__(self, value_fields: Sequence[str], separator: str = ","):
        self.value_fields = list(value_fields)
        self.separator = separator

    def header(self) -> bytes:
        return (
            self.separator.join([*self.value_fields, "time", "diff"]) + "\n"
        ).encode()

    def _cell(self, v: Any) -> str:
        s = "" if v is None else str(_plain(v))
        if self.separator in s or '"' in s or "\n" in s:
            s = '"' + s.replace('"', '""') + '"'
        return s

    def format(self, key, values, time, diff) -> FormatterContext:
        cells = [self._cell(v) for v in values] + [str(time), str(diff)]
        return FormatterContext(
            [(self.separator.join(cells) + "\n").encode()], key, time, diff
        )


class SingleColumnFormatter:
    """reference: data_format.rs:1014 — the raw value of one column."""

    def __init__(self, value_index: int = 0):
        self.value_index = value_index

    def format(self, key, values, time, diff) -> FormatterContext:
        v = values[self.value_index]
        if isinstance(v, bytes):
            payload = v
        else:
            payload = str(_plain(v)).encode()
        return FormatterContext([payload], key, time, diff)


def _sql_literal(v: Any) -> str:
    import math

    v = _plain(v)
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, float) and not math.isfinite(v):
        # bare nan/inf are not SQL literals; PostgreSQL wants quoted forms
        if math.isnan(v):
            return "'NaN'::float8"
        return "'Infinity'::float8" if v > 0 else "'-Infinity'::float8"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, bytes):
        return "'\\x" + v.hex() + "'"
    if isinstance(v, (dict, list)):
        v = _json.dumps(v, default=str)
    return "'" + str(v).replace("'", "''") + "'"


def _sql_ident(name: str) -> str:
    """Double-quote an identifier (each dotted part) so reserved words
    (user, order, ...) and mixed-case names survive a real PostgreSQL
    parser — the mock-free failure mode is an upsert keyed on the
    SESSION user instead of the column."""
    return ".".join(
        '"' + part.replace('"', '""') + '"' for part in name.split(".")
    )


class PsqlUpdatesFormatter:
    """reference: data_format.rs:1632 — INSERT per delta carrying time and
    diff columns; consumers reconstruct the update stream."""

    def __init__(self, table_name: str, value_fields: Sequence[str]):
        self.table_name = table_name
        self.value_fields = list(value_fields)

    def format(self, key, values, time, diff) -> FormatterContext:
        cols = ",".join(
            _sql_ident(c) for c in [*self.value_fields, "time", "diff"]
        )
        vals = ",".join(
            [_sql_literal(v) for v in values] + [str(time), str(diff)]
        )
        stmt = (
            f"INSERT INTO {_sql_ident(self.table_name)} ({cols}) "
            f"VALUES ({vals});\n"
        )
        return FormatterContext([stmt.encode()], key, time, diff)


class PsqlSnapshotFormatter:
    """reference: data_format.rs:1691 — maintain the CURRENT snapshot:
    upsert on the primary key for insertions, DELETE for retractions."""

    def __init__(
        self,
        table_name: str,
        primary_key_fields: Sequence[str],
        value_fields: Sequence[str],
    ):
        self.table_name = table_name
        self.primary_key_fields = list(primary_key_fields)
        self.value_fields = list(value_fields)
        missing = set(primary_key_fields) - set(value_fields)
        if missing:
            raise ValueError(
                f"primary key fields {sorted(missing)} not in value fields"
            )

    def format(self, key, values, time, diff) -> FormatterContext:
        by_name = dict(zip(self.value_fields, values))
        if diff < 0:
            cond = " AND ".join(
                f"{_sql_ident(f)}={_sql_literal(by_name[f])}"
                for f in self.primary_key_fields
            )
            stmt = f"DELETE FROM {_sql_ident(self.table_name)} WHERE {cond};\n"
        else:
            cols = ",".join(_sql_ident(c) for c in self.value_fields)
            vals = ",".join(_sql_literal(v) for v in values)
            pk = ",".join(_sql_ident(f) for f in self.primary_key_fields)
            non_pk = [
                f for f in self.value_fields
                if f not in self.primary_key_fields
            ]
            if non_pk:
                update = ",".join(
                    f"{_sql_ident(f)}={_sql_literal(by_name[f])}"
                    for f in non_pk
                )
                conflict = f"DO UPDATE SET {update}"
            else:
                conflict = "DO NOTHING"
            stmt = (
                f"INSERT INTO {_sql_ident(self.table_name)} ({cols}) "
                f"VALUES ({vals}) ON CONFLICT ({pk}) {conflict};\n"
            )
        return FormatterContext([stmt.encode()], key, time, diff)


# -- BSON (hand-rolled: no bson client lib in this image) -------------------

def _bson_cstring(s: str) -> bytes:
    return s.encode("utf-8") + b"\x00"


def _bson_string(s: str) -> bytes:
    raw = s.encode("utf-8") + b"\x00"
    return struct.pack("<i", len(raw)) + raw


def _bson_element(name: str, v: Any) -> bytes:
    v = _plain(v)
    n = _bson_cstring(name)
    if v is None:
        return b"\x0a" + n
    if isinstance(v, bool):
        return b"\x08" + n + (b"\x01" if v else b"\x00")
    if isinstance(v, int):
        if -(2**31) <= v < 2**31:
            return b"\x10" + n + struct.pack("<i", v)
        if -(2**63) <= v < 2**63:
            return b"\x12" + n + struct.pack("<q", v)
        raise ValueError(f"integer {v} exceeds BSON int64 range")
    if isinstance(v, float):
        return b"\x01" + n + struct.pack("<d", v)
    if isinstance(v, str):
        return b"\x02" + n + _bson_string(v)
    if isinstance(v, bytes):
        return b"\x05" + n + struct.pack("<i", len(v)) + b"\x00" + v
    if isinstance(v, _dt.datetime):
        millis = int(v.timestamp() * 1000)
        return b"\x09" + n + struct.pack("<q", millis)
    if isinstance(v, dict):
        return b"\x03" + n + bson_document(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + n + bson_document(
            {str(i): x for i, x in enumerate(v)}
        )
    return b"\x02" + n + _bson_string(str(v))


def bson_document(doc: dict) -> bytes:
    """Serialize a dict as a BSON document (spec: bsonspec.org, the format
    the reference's Bson formatter emits via the bson crate)."""
    body = b"".join(_bson_element(str(k), v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


class BsonFormatter:
    """reference: data_format.rs:1982 — one BSON document per delta with
    time/diff fields (MongoWriter's wire format)."""

    def __init__(self, value_fields: Sequence[str]):
        self.value_fields = list(value_fields)

    def format(self, key, values, time, diff) -> FormatterContext:
        doc = {f: _plain(v) for f, v in zip(self.value_fields, values)}
        doc["time"] = time
        doc["diff"] = diff
        return FormatterContext([bson_document(doc)], key, time, diff)


class NullFormatter:
    """reference: data_format.rs:1869."""

    def format(self, key, values, time, diff) -> FormatterContext:
        return FormatterContext([], key, time, diff)
