"""pw.io.logstash — Logstash HTTP-input output connector (reference:
python/pathway/io/logstash — rows POSTed to the logstash http plugin
endpoint with time/diff fields, configurable retries/timeouts)."""

from __future__ import annotations

from pathway_tpu.io.http._client import write as _http_write


def write(
    table,
    endpoint: str,
    n_retries: int = 0,
    retry_policy=None,
    connect_timeout_ms: int | None = None,
    request_timeout_ms: int = 30_000,
    *,
    name: str | None = None,
    **kwargs,
) -> None:
    """POST each row change (inserts AND retractions) to the Logstash HTTP
    input as JSON with `time` and `diff` fields appended (reference
    payload contract)."""
    _http_write(
        table,
        endpoint,
        method="POST",
        n_retries=n_retries,
        connect_timeout_ms=connect_timeout_ms,
        request_timeout_ms=request_timeout_ms,
    )
