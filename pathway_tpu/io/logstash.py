"""pw.io.logstash — connector surface (reference: python/pathway/io/logstash (HTTP transport over pw.io.http.write)).

Client transport gated on its library; the configuration surface matches
the reference so templates parse and fail only at run time with a clear
dependency error."""

from __future__ import annotations

from pathway_tpu.io._gated import require


def write(table, *args, name=None, **kwargs):
    require('requests')
    raise NotImplementedError(
        "pw.io.logstash.write: client library found, but no logstash service "
        "transport is wired in this build"
    )
