"""pw.io.debezium — CDC message parsing (reference: python/pathway/io/
debezium + native DebeziumMessageParser, data_format.rs:1056 with
MongoDB and Postgres dialects :1051).

The parser logic is real and pure: Debezium envelopes ({'payload':
{'before', 'after', 'op'}}) become upserts/deletions. Transport is Kafka
(gated on a client lib) or any jsonlines stream of envelopes for testing.
"""

from __future__ import annotations

import json as _json
from typing import Any

from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read


def parse_debezium_message(message: str | bytes | dict, cols, pkeys):
    """-> list of ('upsert'|'remove', values_dict, key). Handles both the
    Postgres dialect (before/after/op) and the MongoDB dialect (stringified
    'after' payload) — reference data_format.rs:1051-1200."""
    if isinstance(message, (str, bytes)):
        message = _json.loads(message)
    payload = message.get("payload", message)
    op = payload.get("op", "r")
    after = payload.get("after")
    before = payload.get("before")
    if isinstance(after, str):  # MongoDB dialect stringifies the document
        after = _json.loads(after)
    if isinstance(before, str):
        before = _json.loads(before)

    def key_of(values):
        if pkeys:
            return ref_scalar(*(values.get(c) for c in pkeys))
        return ref_scalar(*(values.get(c) for c in cols))

    out = []
    if op in ("c", "r", "u") and after is not None:
        values = {c: after.get(c) for c in cols}
        if op == "u" and before is not None:
            old = {c: before.get(c) for c in cols}
            out.append(("remove", old, key_of(old)))
        out.append(("upsert", values, key_of(values)))
    elif op == "d" and before is not None:
        old = {c: before.get(c) for c in cols}
        out.append(("remove", old, key_of(old)))
    return out


class _DebeziumFileSubject(ConnectorSubject):
    """Replay a jsonlines file of Debezium envelopes (testing transport)."""

    def __init__(self, path, schema):
        super().__init__()
        self.path = path
        self.schema = schema

    def run(self):
        cols = self.schema.column_names()
        pkeys = self.schema.primary_key_columns()
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                for kind, values, key in parse_debezium_message(
                    line, cols, pkeys
                ):
                    if kind == "upsert":
                        self._upsert(key, values)
                    else:
                        self._remove(key, values)
        self.commit()


def read(
    rdkafka_settings: dict | None = None,
    topic_name: str | None = None,
    *,
    schema: type[Schema],
    db_type: str = "postgres",
    autocommit_duration_ms: int | None = 1500,
    input_file: str | None = None,
    name: str | None = None,
    **kwargs,
):
    """Kafka transport requires `confluent_kafka`; `input_file` replays a
    jsonlines capture instead (test/dev path)."""
    if input_file is not None:
        subject = _DebeziumFileSubject(input_file, schema)
        return python_read(
            subject,
            schema=schema,
            autocommit_duration_ms=autocommit_duration_ms,
            name=name or f"debezium:{input_file}",
        )
    try:
        import confluent_kafka  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "pw.io.debezium.read over Kafka requires `confluent-kafka`; "
            "for files pass input_file="
        ) from e
    from pathway_tpu.io.kafka import _KafkaSubject

    subject = _KafkaSubject(
        rdkafka_settings, [topic_name], message_parser=(
            lambda subj, raw: [
                (subj._upsert(key, values) if kind == "upsert" else subj._remove(key, values))
                for kind, values, key in parse_debezium_message(
                    raw, schema.column_names(), schema.primary_key_columns()
                )
            ]
        ),
    )
    return python_read(
        subject,
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"debezium:{topic_name}",
    )
