"""pw.io.mongodb — MongoDB sink (reference: python/pathway/io/mongodb
over the native MongoWriter, src/connectors/data_storage.rs:2187, BSON
payloads data_format.rs:1982).

Redesigned transport: no pymongo — a dependency-free OP_MSG client
(`pathway_tpu/io/_mongo.py`) inserts the documents the existing Bson
formatter shape defines (row fields + ``time`` + ``diff``).
"""

from __future__ import annotations

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._formats import _plain
from pathway_tpu.io._mongo import MongoConnection

__all__ = ["write"]


def write(
    table,
    *,
    connection_string: str,
    database: str,
    collection: str,
    max_batch_size: int | None = None,
    _connection=None,
) -> None:
    """Write the table's update stream into a MongoDB collection
    (reference: io/mongodb/__init__.py:14 — docs carry ``time`` and
    ``diff`` fields; batches bounded by max_batch_size)."""
    cols = table.column_names()
    state = {"conn": _connection, "buf": []}

    def _conn():
        if state["conn"] is None:
            state["conn"] = MongoConnection(connection_string)
        return state["conn"]

    def _flush():
        if not state["buf"]:
            return
        docs = state["buf"]
        state["buf"] = []
        _conn().insert_many(database, collection, docs)

    def on_change(key, row, time_, diff):
        doc = {c: _plain(v) for c, v in zip(cols, row)}
        doc["time"] = time_
        doc["diff"] = diff
        state["buf"].append(doc)
        if max_batch_size is not None and len(state["buf"]) >= max_batch_size:
            _flush()

    def on_time_end(time_):
        _flush()

    def on_end():
        _flush()
        if state["conn"] is not None:
            state["conn"].close()
            state["conn"] = None

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table), on_change=on_change,
            on_time_end=on_time_end, on_end=on_end,
        )

    G.add_operator([table], [], lower, "mongodb_write", is_output=True)
