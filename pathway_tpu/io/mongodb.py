"""pw.io.mongodb — connector surface (reference: python/pathway/io/mongodb (native MongoWriter data_storage.rs:2187, Bson formatter data_format.rs:1982)).

Client transport gated on its library; the configuration surface matches
the reference so templates parse and fail only at run time with a clear
dependency error."""

from __future__ import annotations

from pathway_tpu.io._gated import require


def write(table, *args, name=None, **kwargs):
    require('pymongo')
    raise NotImplementedError(
        "pw.io.mongodb.write: client library found, but no mongodb service "
        "transport is wired in this build"
    )
