"""pw.io.plaintext (reference: python/pathway/io/plaintext)."""

from __future__ import annotations

from pathway_tpu.io import fs


def read(path, *, mode="streaming", **kwargs):
    return fs.read(path, format="plaintext", mode=mode, **kwargs)
