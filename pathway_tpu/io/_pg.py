"""Minimal PostgreSQL wire-protocol (v3) client — dependency-free.

The reference's Postgres writer drives the postgres crate over the same
protocol (reference: src/connectors/data_storage.rs PsqlWriter). This
build implements the subset the sink needs: startup, cleartext/MD5
password auth, and the Simple Query flow (``Q`` → CommandComplete* →
ReadyForQuery). Statements are produced by the Psql formatters
(io/_formats.py), which quote all values as SQL literals.
"""

from __future__ import annotations

import hashlib
import socket
import struct


class PgError(RuntimeError):
    pass


class PgConnection:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 5432,
        user: str = "postgres",
        password: str = "",
        dbname: str = "postgres",
        timeout: float = 30.0,
        **_extra,
    ):
        self.sock = socket.create_connection((host, int(port)), timeout=timeout)
        self._buf = b""
        params = {"user": user, "database": dbname}
        body = b"".join(
            k.encode() + b"\x00" + v.encode() + b"\x00"
            for k, v in params.items()
        ) + b"\x00"
        payload = struct.pack("!i", 196608) + body  # protocol 3.0
        self.sock.sendall(struct.pack("!i", len(payload) + 4) + payload)
        self._auth(user, password)

    # -- framing -----------------------------------------------------------
    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise EOFError("postgres connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    # backend messages are small (errors, command tags); a length beyond
    # this is a corrupt/desynced stream, not a real frame
    _MAX_FRAME = 64 * 1024 * 1024

    def _read_msg(self) -> tuple[bytes, bytes]:
        kind = self._read_exact(1)
        (length,) = struct.unpack("!i", self._read_exact(4))
        if length < 4 or length - 4 > self._MAX_FRAME:
            raise PgError(
                f"malformed postgres frame: kind={kind!r} length={length} "
                "(stream corrupt or not a postgres server)"
            )
        return kind, self._read_exact(length - 4)

    def _send_msg(self, kind: bytes, payload: bytes) -> None:
        self.sock.sendall(kind + struct.pack("!i", len(payload) + 4) + payload)

    # -- startup -----------------------------------------------------------
    def _auth(self, user: str, password: str) -> None:
        while True:
            kind, payload = self._read_msg()
            if kind == b"R":
                (code,) = struct.unpack("!i", payload[:4])
                if code == 0:  # AuthenticationOk
                    continue
                if code == 3:  # cleartext password
                    self._send_msg(b"p", password.encode() + b"\x00")
                elif code == 5:  # MD5: md5(md5(password+user)+salt)
                    salt = payload[4:8]
                    inner = hashlib.md5(
                        password.encode() + user.encode()
                    ).hexdigest()
                    digest = hashlib.md5(
                        inner.encode() + salt
                    ).hexdigest()
                    self._send_msg(
                        b"p", b"md5" + digest.encode() + b"\x00"
                    )
                else:
                    raise PgError(
                        f"unsupported postgres auth method code {code} "
                        "(supported: trust, password, md5)"
                    )
            elif kind == b"E":
                raise PgError(self._error_text(payload))
            elif kind == b"Z":  # ReadyForQuery
                return
            # S (ParameterStatus), K (BackendKeyData), N (Notice): skip

    @staticmethod
    def _error_text(payload: bytes) -> str:
        fields = {}
        for part in payload.split(b"\x00"):
            if part:
                fields[chr(part[0])] = part[1:].decode(errors="replace")
        return fields.get("M", "postgres error")

    # -- simple query ------------------------------------------------------
    def execute(self, sql: str) -> None:
        """Run statements via the Simple Query protocol; raises on error."""
        self._send_msg(b"Q", sql.encode() + b"\x00")
        error = None
        while True:
            kind, payload = self._read_msg()
            if kind == b"E":
                error = PgError(self._error_text(payload))
            elif kind == b"Z":
                if error is not None:
                    raise error
                return
            # C (CommandComplete), T/D (row data), N (notices): skip

    def close(self) -> None:
        try:
            self._send_msg(b"X", b"")
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
