"""pw.io.sqlite — SQLite input connector (reference:
python/pathway/io/sqlite + native SqliteReader, data_storage.rs:1407 —
snapshot + change polling keyed on rowid/data_version)."""

from __future__ import annotations

import sqlite3
import time

from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject, read as python_read


class _SqliteSubject(ConnectorSubject):
    def __init__(self, path, table_name, schema, mode, refresh_interval):
        super().__init__()
        self.path = path
        self.table_name = table_name
        self.schema = schema
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._stop = False
        self._live: dict = {}  # key -> row values

    def _scan(self):
        cols = self.schema.column_names()
        pkeys = self.schema.primary_key_columns()
        con = sqlite3.connect(self.path)
        try:
            cur = con.execute(
                f"SELECT {', '.join(cols)} FROM {self.table_name}"
            )
            current = {}
            for rec in cur.fetchall():
                values = dict(zip(cols, rec))
                if pkeys:
                    key = ref_scalar(*(values[c] for c in pkeys))
                else:
                    key = ref_scalar("sqlite", *rec)
                current[key] = values
        finally:
            con.close()
        # diff against previous snapshot: upserts + deletions
        for key, values in current.items():
            prev = self._live.get(key)
            if prev != values:
                if prev is not None:
                    self._remove(key, prev)
                self._upsert(key, values)
        for key in list(self._live):
            if key not in current:
                self._remove(key, self._live[key])
        self._live = current
        self.commit()

    def run(self):
        self._scan()
        if self.mode == "static":
            return
        while not self._stop:
            time.sleep(self.refresh_interval)
            self._scan()

    def on_stop(self):
        self._stop = True

    def snapshot_state(self):
        return {"live": dict(self._live)}

    def seek(self, state):
        self._live = dict(state.get("live", {}))


def read(
    path: str,
    table_name: str,
    schema: type[Schema],
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 1.0,
    name: str | None = None,
    **kwargs,
):
    subject = _SqliteSubject(path, table_name, schema, mode, refresh_interval)
    return python_read(
        subject,
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"sqlite:{path}:{table_name}",
    )
