"""pw.io.pyfilesystem — PyFilesystem source (reference:
python/pathway/io/pyfilesystem — walks any `fs.base.FS` object: local,
zip, tar, ftp, s3fs, memoryfs, ...).

Redesigned transport: DUCK-TYPED against the (small) PyFilesystem
surface the scanner needs — ``walk.files(path)`` (or ``listdir`` +
``isdir`` recursion), ``getmodified``/``getinfo``, ``open``/
``readbytes``. Any object implementing those works, including the real
``fs`` library's objects when installed; the connector itself carries no
dependency on it.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import schema_from_types
from pathway_tpu.io._objstore import ObjectStoreSubject
from pathway_tpu.io.python import read as python_read

__all__ = ["read"]


def _iter_files(source, path: str):
    """All file paths under `path`, recursively. Prefers the PyFilesystem
    walker; falls back to listdir/isdir recursion for minimal fakes."""
    walk = getattr(source, "walk", None)
    if walk is not None and hasattr(walk, "files"):
        yield from walk.files(path=path or "/")
        return
    base = (path or "/").rstrip("/")
    stack = [base or "/"]
    while stack:
        cur = stack.pop()
        for name in source.listdir(cur):
            full = f"{cur.rstrip('/')}/{name}"
            if source.isdir(full):
                stack.append(full)
            else:
                yield full


def _read_bytes(source, path: str) -> bytes:
    if hasattr(source, "readbytes"):
        return source.readbytes(path)
    with source.open(path, "rb") as f:
        data = f.read()
    if isinstance(data, str):
        data = data.encode("utf-8")
    return data


class _PyFsSubject(ObjectStoreSubject):
    """fmt='binary' object-store scan over a PyFilesystem-like source:
    the shared scanner owns modified-diffing, RETRACTION of previous
    rows on change, deletion detection, and snapshot bookkeeping."""

    _scheme = "pyfs"

    def __init__(self, source, path, mode, refresh_interval, with_metadata):
        super().__init__("binary", with_metadata, mode, refresh_interval)
        self.source = source
        self.path = path

    def _stat(self, path):
        """(stamp, metadata extras); files vanished between walk and
        stat are skipped (the scanner's deletion pass retracts them)."""
        try:
            info = self.source.getinfo(
                path, namespaces=["basic", "details", "access"]
            )
        except Exception:
            return None
        extras: dict[str, Any] = {}
        for field, attr in (
            ("created_at", "created"),
            ("modified_at", "modified"),
            ("accessed_at", "accessed"),
        ):
            ts = getattr(info, attr, None)
            extras[field] = int(ts.timestamp()) if ts is not None else None
        extras["owner"] = getattr(info, "user", None)
        extras["name"] = getattr(info, "name", None)
        if hasattr(self.source, "getmodified"):
            try:
                stamp = self.source.getmodified(path)
            except Exception:
                return None
        else:
            stamp = extras["modified_at"]
        return stamp, extras

    def _list(self):
        for p in _iter_files(self.source, self.path):
            stat = self._stat(p)
            if stat is None:
                continue
            stamp, extras = stat
            yield p, stamp, extras

    def _get(self, name: str) -> bytes:
        return _read_bytes(self.source, name)

    def _uri(self, name: str) -> str:
        return name


def read(
    source,
    *,
    path: str = "",
    refresh_interval: float = 30,
    mode: str = "streaming",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
):
    """Read every file under `path` of a PyFilesystem-like source as a
    binary `data` column (reference: io/pyfilesystem/__init__.py:142 —
    streaming mode re-scans every refresh_interval with upserts and
    deletion detection)."""
    if mode not in ("streaming", "static"):
        raise ValueError(f"Unrecognized connector mode: {mode}")
    cols: dict[str, Any] = {"data": dt.BYTES}
    if with_metadata:
        cols["_metadata"] = dt.JSON
    subject = _PyFsSubject(source, path, mode, refresh_interval, with_metadata)
    return python_read(
        subject,
        schema=schema_from_types(**cols),
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or "pyfilesystem",
    )
