"""pw.io.python — custom Python connectors (reference:
python/pathway/io/python/__init__.py:49 ConnectorSubject with
next()/commit()/close() protocol and *COMMIT*/*FINISH* literals :43-46)."""

from __future__ import annotations

import json as _json
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import _KEY_MASK, Pointer, ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe

COMMIT_LITERAL = "*COMMIT*"
FINISH_LITERAL = "*FINISH*"


class ConnectorSubject:
    """Subclass and implement run(); push rows with next()/next_json()/...

    The runtime runs ``run()`` on a dedicated thread per source (reference:
    connector thread, src/connectors/mod.rs:91) and stamps a commit timestamp
    per flush.
    """

    _deletions_enabled: bool = True

    def __init__(self, datasource_name: str = "python"):
        self._emit = None
        self._flush = None
        self._finished = False

    # wired by the engine runtime
    def _attach(self, emit, flush) -> None:
        self._emit = emit
        self._flush = flush

    def run(self) -> None:
        raise NotImplementedError

    def on_stop(self) -> None:
        pass

    def start(self) -> None:
        self.run()

    # -- producer API ------------------------------------------------------
    def next(self, **kwargs) -> None:
        if self._finished:
            return
        self._emit(("upsert", kwargs))

    def next_json(self, message: dict) -> None:
        self.next(**message)

    def next_batch(self, rows: list[dict]) -> None:
        """Push many rows in one producer call. The whole list reaches the
        flush as a single message and (for keyless append-only subjects)
        is parsed by one C call — the engine-bound ingestion door for
        sources that already hold rows in memory."""
        if self._finished or not rows:
            return
        # copy list AND row dicts: parsing is deferred to flush time on
        # the connector thread, so neither a caller-reused list buffer nor
        # a caller-reused row dict may alias the queued message
        self._emit(("upsert_batch", [dict(r) for r in rows]))

    def next_str(self, message: str) -> None:
        if message == COMMIT_LITERAL:
            self.commit()
            return
        if message == FINISH_LITERAL:
            # end-of-stream sentinel (reference: io/python/__init__.py:43-46):
            # later messages are dropped and the final batch is flushed.
            self._finished = True
            self.commit()
            return
        self.next(data=message)

    def next_bytes(self, message: bytes) -> None:
        self.next(data=message)

    def _upsert(self, key: Pointer, values: dict) -> None:
        """Insert/update with an explicit stable key (used by connectors
        that track object identity themselves, e.g. fs path+line)."""
        if self._finished:
            return
        self._emit(("upsert", values, key))

    def _remove(self, key: Pointer, values: dict) -> None:
        self._emit(("remove", values, key))

    def remove(self, **kwargs) -> None:
        self._emit(("remove", kwargs, None))

    def commit(self) -> None:
        if self._flush is not None:
            self._flush()

    def close(self) -> None:
        self.commit()


_parser_seq = [0]


def _make_parser(schema: type[Schema], subject=None):
    from pathway_tpu.engine.stream import freeze_row

    cols = schema.column_names()
    pkeys = schema.primary_key_columns()
    defaults = schema.default_values()
    seq = [0]
    # keyless rows mint salt+counter pointers: deterministic given arrival
    # order (restart replay preserves it via the journal) and two orders of
    # magnitude cheaper than hashing row content per row. The salt includes
    # a per-parser ordinal so same-schema sources in one program never
    # collide (graph construction order is deterministic per program).
    _parser_seq[0] += 1
    key_base = int(
        ref_scalar("py-connector", _parser_seq[0], *sorted(cols))
    )
    col_defaults = [(c, defaults.get(c)) for c in cols]
    # content->key stacks exist only to serve remove()-by-content; subjects
    # that declare they never remove skip the bookkeeping entirely
    track_removals = getattr(subject, "_deletions_enabled", True)
    # primary-keyed sources are upsert sessions (reference: SessionType::
    # Upsert, connectors/adaptors.rs:176): re-inserting a live key must
    # retract the previous row first, or multiset operators double-count
    live_rows: dict[Pointer, tuple] = {}
    # content -> stack of keys minted for it, so remove() retracts the row
    # actually inserted (schemas without primary keys mint per-row keys).
    live_keys: dict[tuple, list] = {}

    def parse(message) -> list[tuple]:
        kind, values = message[0], message[1]
        if kind == "upsert_batch":
            out: list[tuple] = []
            for row_values in values:
                out.extend(parse(("upsert", row_values)))
            return out
        explicit_key = message[2] if len(message) > 2 else None
        row = tuple(values.get(c, d) for c, d in col_defaults)
        if pkeys:
            key = ref_scalar(*(values[c] for c in pkeys))
            if kind == "remove":
                prev = live_rows.pop(key, None)
                return [(key, prev if prev is not None else row, -1)]
            out = []
            prev = live_rows.get(key)
            if prev is not None:
                out.append((key, prev, -1))
            live_rows[key] = row
            out.append((key, row, 1))
            return out
        if kind == "remove":
            if explicit_key is not None:
                key = explicit_key
            else:
                stack = live_keys.get(freeze_row(row))
                if not stack:
                    return []  # nothing to retract
                key = stack.pop()
        elif explicit_key is not None:
            # explicit-key rows are removed by key, never by content — they
            # must not enter the content->key stacks (leak + mis-retraction)
            key = explicit_key
        else:
            seq[0] += 1
            key = Pointer((key_base + seq[0]) & _KEY_MASK)
            if track_removals:
                live_keys.setdefault(freeze_row(row), []).append(key)
        diff = -1 if kind == "remove" else 1
        return [(key, row, diff)]

    # batch parsing: runs of keyless simple upserts (the append-only
    # streaming hot path) are parsed by one C call per run — row tuples,
    # defaults and minted keys all built without the per-row closure
    from pathway_tpu.engine.stream import get_fp

    fp = get_fp()
    simple = fp is not None and not pkeys and not track_removals
    # columnar fast path: a flush that is entirely simple upserts parses
    # into a C-owned NativeBatch (exec.cpp) that the group-by executor
    # consumes with zero per-row Python objects (the fused-chain door)
    nb_parse = None
    if simple:
        try:
            from pathway_tpu.native import get_pwexec

            nb_parse = getattr(get_pwexec(), "parse_upserts_nb", None)
        except Exception:
            nb_parse = None
    # primary-keyed upsert sessions take their own C pass (key mint from
    # pk values + retract-previous against the shared live_rows session
    # dict) — the CDC/connector hot path
    pk_fast = (
        fp is not None and bool(pkeys) and hasattr(fp, "parse_pk_upserts")
    )
    # columnar pk fast path: deletions-disabled pk sources own their
    # upsert session in C (exec.cpp PkStore) and emit NativeBatches while
    # every key is fresh — the fused parse→join/groupby chain for
    # CDC-shaped sources. The first retraction-needing or non-columnar
    # batch dumps the C session into live_rows and permanently falls back
    # to the tuple pk path (one-way demotion, state never splits).
    pk_nb = None
    pk_nb_state = None
    pk_nb_dump = None
    if pk_fast and not track_removals:
        try:
            from pathway_tpu.native import get_pwexec

            _ex = get_pwexec()
            if _ex is not None and hasattr(_ex, "parse_pk_upserts_nb"):
                pk_nb_state = _ex.pk_session_new()
                pk_nb_dump = _ex.pk_session_dump
                pk_nb = _ex.parse_pk_upserts_nb
        except Exception:
            pk_nb = None
    cols_t = tuple(cols)
    pkeys_t = tuple(pkeys or ())
    defaults_t = tuple(defaults.get(c) for c in cols)

    def _all_upsert_dicts(messages: list):
        """The flush's row dicts when EVERY message is an upsert (single
        rows or any number of upsert_batch runs, in order) — the shapes
        the columnar parsers ingest whole; None otherwise."""
        if len(messages) == 1 and messages[0][0] == "upsert_batch":
            return messages[0][1]
        dicts: list = []
        for m in messages:
            if m[0] == "upsert_batch":
                dicts.extend(m[1])
            elif m[0] == "upsert" and len(m) == 2:
                dicts.append(m[1])
            else:
                return None
        return dicts

    def parse_batch(messages: list) -> list[tuple]:
        nonlocal pk_nb
        from pathway_tpu.engine.stream import ConsolidatedList

        if nb_parse is not None and messages:
            dicts = _all_upsert_dicts(messages)
            if dicts is not None:
                res = nb_parse(
                    dicts, 0, cols_t, defaults_t, key_base, seq[0], Pointer
                )
                if res is not None:  # None: value outside the columnar set
                    nb, seq[0] = res
                    return nb
        if pk_nb is not None and messages:
            dicts = _all_upsert_dicts(messages)
            if dicts is not None:
                nb = pk_nb(
                    dicts, cols_t, defaults_t, pkeys_t, pk_nb_state,
                    live_rows, Pointer,
                )
                if nb is not None:
                    return nb
                # demoted: session state now lives in live_rows; the
                # tuple pk path below re-parses this batch against it
                pk_nb = None
            else:
                # a flush carrying non-upsert messages consults live_rows
                # — move the C session there first, then stay demoted
                pk_nb_dump(pk_nb_state, live_rows, Pointer, len(cols_t))
                pk_nb = None
        out: list[tuple] = []
        i, n = 0, len(messages)
        pure = simple
        while i < n:
            m = messages[i]
            if (simple or pk_fast) and m[0] == "upsert_batch":
                # pre-batched rows: one C call for the whole list
                if simple:
                    deltas, seq[0] = fp.parse_upserts(
                        m[1], 0, cols_t, defaults_t, key_base, seq[0],
                        _KEY_MASK, Pointer,
                    )
                else:
                    deltas = fp.parse_pk_upserts(
                        m[1], cols_t, defaults_t, pkeys_t, live_rows
                    )
                out.extend(deltas)
                i += 1
            elif (simple or pk_fast) and m[0] == "upsert" and len(m) == 2:
                j = i + 1
                while j < n:
                    mj = messages[j]
                    if mj[0] != "upsert" or len(mj) != 2:
                        break
                    j += 1
                dicts = [messages[t][1] for t in range(i, j)]
                if simple:
                    deltas, seq[0] = fp.parse_upserts(
                        dicts, 0, cols_t, defaults_t, key_base, seq[0],
                        _KEY_MASK, Pointer,
                    )
                else:
                    deltas = fp.parse_pk_upserts(
                        dicts, cols_t, defaults_t, pkeys_t, live_rows
                    )
                out.extend(deltas)
                i = j
            else:
                pure = False
                out.extend(parse(m))
                i += 1
        if pure:
            # every row minted a fresh key with diff +1: already net form,
            # the source node's consolidate passes it through untouched
            return ConsolidatedList(out)
        return out

    parse.parse_batch = parse_batch
    # primary-keyed sources are upsert sessions. Rescans after a
    # supervised restart are idempotent ONLY while the subject never
    # removes (re-inserting a live key retracts the previous row; a
    # re-scanned remove would retract twice), and the session state makes
    # ledger compensation unsound either way — the supervisor keys its
    # restart strategy off both flags.
    parse.is_pk = bool(pkeys)
    parse.is_upsert = bool(pkeys) and not track_removals
    # static fused-chain capability for pw.analyze + the runtime's
    # fallback accounting (analysis/eligibility.py source_nb_capability):
    # can this source emit columnar NativeBatches, and if not, why —
    # the door exists only for upsert flushes over columnar value types
    from pathway_tpu.analysis.eligibility import schema_nb_blame

    nb_blame: list[str] = []
    if nb_parse is None and pk_nb is None:
        if track_removals:
            nb_blame.append(
                "subject allows remove()-by-content (set "
                "_deletions_enabled = False for the columnar parser)"
            )
        elif pkeys and not pk_fast:
            nb_blame.append("no native toolchain (C parser unavailable)")
        elif not simple and not pkeys:
            nb_blame.append("no native toolchain (C parser unavailable)")
        else:
            nb_blame.append("columnar parser door unavailable")
    nb_blame.extend(schema_nb_blame(schema))
    parse.nb_capable = not nb_blame
    parse.nb_blame = tuple(nb_blame)
    return parse


def read(
    subject: ConnectorSubject,
    *,
    schema: type[Schema] | None = None,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs,
) -> Table:
    if schema is None:
        raise ValueError("pw.io.python.read requires a schema")
    subject._autocommit_duration_ms = autocommit_duration_ms
    out = Table(schema, Universe())
    parser = _make_parser(schema, subject)
    width = len(schema.column_names())
    persistent_name = name or kwargs.get("persistent_id")

    def lower(ctx):
        ctx.set_engine_table(
            out,
            ctx.scope.connector_table(
                subject, parser, width, name=persistent_name
            ),
        )

    G.add_operator([], [out], lower, "python_connector")
    return out
