"""Connector thread driver (reference: src/connectors/mod.rs:91 Connector —
per-source thread reading into an mpsc channel drained by the main loop).

Queue protocol: each entry is ``(conn, deltas, state, journal_rows)``.
``deltas`` are the rows the engine should accept this cycle (None = source
finished). ``journal_rows`` are the rows persistence should append to the
input journal with this entry, and ``state`` the subject scan state to save
alongside. For stateful (rescannable) subjects these are only populated at
subject-driven commit boundaries, where the subject's bookkeeping is up to
date on its own thread — so the saved state claims exactly the journaled
prefix. Mid-scan timer flushes forward rows for latency but defer journaling
to the next boundary; a crash in between is recovered by rescan from the
last consistent state (same stable keys), never by double-replay.
Stateless subjects (no ``snapshot_state``) cannot rescan, so their rows are
journaled write-ahead at every flush, exactly as before.
"""

from __future__ import annotations

import queue
import threading
import time as _time
from typing import Any

# uncommitted-row backlog above which a stateful subject's rows are
# journaled without a scan state (degrading recovery to at-least-once)
# rather than growing host memory without bound
_BACKLOG_CAP = 1_000_000


def run_connector_thread(conn, out_queue: "queue.Queue") -> None:
    subject = conn.subject
    parser = conn.parser
    # parse_batch defers per-message parsing to flush time so runs of
    # simple upserts go through one C call instead of a Python closure per
    # row (io/python.py attaches it; other parsers fall back to a loop)
    parse_batch = getattr(parser, "parse_batch", None)
    if parse_batch is None:

        def parse_batch(msgs):
            out: list = []
            for m in msgs:
                out.extend(parser(m))
            return out

    from pathway_tpu.engine.stream import is_native_batch

    pending: list = []  # raw messages, parsed at flush under `lock`
    # rows forwarded to the engine but not yet covered by a journal entry
    # (stateful subjects only; tracked only when persistence is configured)
    unjournaled: list = []
    lock = threading.Lock()
    has_state = hasattr(subject, "snapshot_state")
    runtime = getattr(getattr(conn, "node", None), "scope", None)
    runtime = getattr(runtime, "runtime", None)
    persisting = getattr(runtime, "persistence", None) is not None
    warned_backlog = False
    forwarded_since_boundary = 0
    # timer-based autocommit (reference: commit_duration cadence in the
    # worker poller, connectors/mod.rs): rows accumulate into one commit
    # until `autocommit_duration_ms` elapses or the subject commits
    # explicitly — this is what gives downstream batched UDFs whole
    # logical-time batches instead of row-at-a-time dribbles. The runtime's
    # main loop calls `conn.force_flush` on its own cadence so rows are not
    # stranded while the subject blocks waiting for input.
    duration_ms = getattr(subject, "_autocommit_duration_ms", None)
    last_flush = _time.monotonic()

    def jrows_of(batch):
        """Journal view of a parsed batch: empty when nothing journals
        (no persistence configured), materialized (key, row, diff) rows
        when the batch is a columnar NativeBatch (which carries no
        picklable rows); the engine always receives the batch itself."""
        if not persisting:
            return []
        return list(batch) if is_native_batch(batch) else batch

    def take_batch() -> list:
        """Parse and claim the currently queued messages. Caller holds
        `lock`. Appends from the subject thread are GIL-atomic, so the
        snapshot + del-prefix pair never drops a message that lands
        mid-flush — it simply stays queued for the next flush."""
        msgs = pending[:]
        if not msgs:
            return []
        del pending[: len(msgs)]
        return parse_batch(msgs)

    def timer_flush() -> None:
        nonlocal last_flush, warned_backlog, forwarded_since_boundary
        last_flush = _time.monotonic()
        with lock:
            batch = take_batch()
            if not batch:
                return
            forwarded_since_boundary += len(batch)
            if has_state and persisting:
                # the subject may be mid-scan on its own thread, so its
                # bookkeeping can lag these rows — journaling them now with
                # a concurrently captured state double-counts on restore
                # (journal replay + rescan re-emitting the same keys)
                unjournaled.extend(jrows_of(batch))
                if len(unjournaled) > _BACKLOG_CAP:
                    # subject never commits: journal stateless (at-least-once
                    # for this span) rather than grow host memory unboundedly
                    if not warned_backlog:
                        warned_backlog = True
                        import logging

                        logging.getLogger(__name__).warning(
                            "connector %s emitted %d rows without a "
                            "commit() boundary; journaling them without a "
                            "scan state (recovery degrades to "
                            "at-least-once for this span). Stateful "
                            "subjects should call commit() regularly.",
                            getattr(conn, "name", "?"),
                            len(unjournaled),
                        )
                    out_queue.put((conn, batch, None, unjournaled.copy()))
                    unjournaled.clear()
                else:
                    out_queue.put((conn, batch, None, []))
            elif has_state:
                # no persistence configured: nothing to journal
                out_queue.put((conn, batch, None, []))
            else:
                out_queue.put((conn, batch, None, jrows_of(batch)))

    def commit_flush() -> None:
        # subject-driven boundary (subject.commit() / end of run()): runs on
        # the subject thread after its bookkeeping was updated, so the
        # captured state claims exactly journal ∪ backlog ∪ this batch
        nonlocal last_flush, forwarded_since_boundary
        last_flush = _time.monotonic()
        with lock:
            batch = take_batch()
            if has_state:
                journal_rows = unjournaled + jrows_of(batch)
                unjournaled.clear()
                # publish a state even with an empty journal batch when rows
                # were forwarded since the last boundary (operator-snapshot
                # mode needs the state to cover them). `batch` enters the
                # condition directly: without persistence journal_rows is
                # always empty, but a committed batch must still reach the
                # engine
                dirty = (
                    bool(journal_rows)
                    or bool(batch)
                    or forwarded_since_boundary > 0
                )
                forwarded_since_boundary = 0
                if dirty:
                    state = subject.snapshot_state()
                    out_queue.put((conn, batch, state, journal_rows))
            elif batch:
                out_queue.put((conn, batch, None, jrows_of(batch)))

    def emit(message: Any) -> None:
        # list.append is GIL-atomic: no lock on the per-row producer path.
        # duration_ms None disables autocommit entirely (reference:
        # io/python/__init__.py autocommit_duration_ms=None) — rows then
        # move only at explicit subject.commit() boundaries.
        pending.append(message)
        if (
            duration_ms is not None
            and (_time.monotonic() - last_flush) * 1000.0 >= duration_ms
        ):
            timer_flush()

    def force_flush() -> None:
        # called from the runtime loop's cadence; respects the autocommit
        # window so steady sources still batch up to duration_ms
        if duration_ms is None or (
            (_time.monotonic() - last_flush) * 1000.0 < duration_ms
        ):
            return
        timer_flush()

    conn.force_flush = force_flush

    subject._attach(emit, commit_flush)
    try:
        subject.run()
    except Exception as exc:  # surfaced by the main loop
        conn.node.scope.runtime.error = exc
    finally:
        try:
            subject.on_stop()
        except Exception:
            pass
        commit_flush()
        out_queue.put((conn, None, None, []))
