"""Connector thread driver (reference: src/connectors/mod.rs:91 Connector —
per-source thread reading into an mpsc channel drained by the main loop)."""

from __future__ import annotations

import queue
from typing import Any, Callable


def run_connector_thread(conn, out_queue: "queue.Queue") -> None:
    subject = conn.subject
    parser = conn.parser
    pending: list = []

    def emit(message: Any) -> None:
        deltas = parser(message)
        if deltas:
            pending.extend(deltas)
            if getattr(subject, "_autocommit", True):
                flush()

    def flush() -> None:
        if pending:
            out_queue.put((conn, pending.copy()))
            pending.clear()

    subject._attach(emit, flush)
    try:
        subject.run()
    except Exception as exc:  # surfaced by the main loop
        conn.node.scope.runtime.error = exc
    finally:
        try:
            subject.on_stop()
        except Exception:
            pass
        flush()
        out_queue.put((conn, None))
