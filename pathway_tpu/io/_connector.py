"""Connector thread driver (reference: src/connectors/mod.rs:91 Connector —
per-source thread reading into an mpsc channel drained by the main loop).

Queue protocol: each entry is ``(conn, deltas, state, journal_rows)``.
``deltas`` are the rows the engine should accept this cycle (None = source
finished). ``journal_rows`` are the rows persistence should append to the
input journal with this entry, and ``state`` the subject scan state to save
alongside. For stateful (rescannable) subjects these are only populated at
subject-driven commit boundaries, where the subject's bookkeeping is up to
date on its own thread — so the saved state claims exactly the journaled
prefix. Mid-scan timer flushes forward rows for latency but defer journaling
to the next boundary; a crash in between is recovered by rescan from the
last consistent state (same stable keys), never by double-replay.
Stateless subjects (no ``snapshot_state``) cannot rescan, so their rows are
journaled write-ahead at every flush, exactly as before.

Supervision: ``run_connector_thread`` wraps the subject in a supervisor
loop. Failures escaping ``subject.run()`` (including faults injected via
internals/faults.py) are classified by the connector's
:class:`SupervisorPolicy` — retryable ones restart the subject in place
under an exponential-backoff budget with per-connector seeded jitter:

* rescannable subjects (``snapshot_state``/``seek``) roll back to the
  last scan state published on the queue (or the state the runtime
  restored at startup). Pure-upsert subjects (``parser.is_upsert``:
  primary-keyed with deletions disabled) simply rescan — re-emitted
  primary keys retract their previous rows, so the net effect is
  exactly-once. Non-pk subjects first retract the rows they forwarded
  beyond that state (the batch-granular backlog ledger) and then rescan
  with the same stable keys, which is also net exactly-once. pk subjects
  that may see removes are rescan-unsafe both ways and restart as
  continuations. If the backlog overflowed ``_BACKLOG_CAP``, recovery
  for that span degrades to at-least-once (reported through the
  runtime).
* stateless subjects just re-run; whether re-reads duplicate is up to the
  subject (documented at-least-once). Because that is not provably
  duplicate-free, non-rescannable non-upsert subjects are NOT restarted
  by the default policy — they fail fast exactly as before unless an
  explicit ``_supervisor_policy`` opts them in.

A permanently-failed connector (budget exhausted or classified fatal)
routes its failure through ``runtime.report_connector_error()``: the
pipeline aborts when ``terminate_on_error`` is set, otherwise the
connector demotes to finished and the failure lands in the global
error-log table. The runtime's watchdog (``_watchdog_timeout_s`` on the
subject or ``heartbeat_timeout_s`` on the policy) detects stalled — not
crashed — subjects from the heartbeat every emit/flush refreshes.

Mesh rollback interplay (engine/runtime.py supervised abort path): when
a multi-rank run detects a peer crash and this rank exits to request a
rollback restart, subjects are NOT rewound in place — they are arbitrary
user code blocked in ``run()``. Instead the whole rank set restarts at
the next mesh epoch and the normal startup restore path seeks every
subject to the scan state saved in the last committed distributed
snapshot (exactly the rollback target PR 2's in-place restart uses).
:func:`close_subjects_for_rollback` gives subjects holding external
resources (consumers, file locks) one bounded ``on_stop()`` chance
before the process exits — a courtesy a hard crash does not extend.
"""

from __future__ import annotations

import os
import queue
import random
import threading
import time as _time
import zlib
from typing import Any, Callable

from pathway_tpu.internals import faults as _faults

# uncommitted-row backlog above which a stateful subject's rows are
# journaled without a scan state (degrading recovery to at-least-once)
# rather than growing host memory without bound. With memory governance
# enabled (PATHWAY_MEM_BUDGET_MB; internals/memory.py) a PAUSABLE
# subject never reaches this degradation: the runtime's pacing pass
# stops the reader at the byte watermarks first (ISSUE 19), so the cap
# only fires for non-pausable subjects — and is error-logged + counted
# when it does.
_BACKLOG_CAP = 1_000_000


def _governed() -> bool:
    """Whether the memory-governance ladder is active for this runtime
    (an accountant is installed AND a budget is configured)."""
    from pathway_tpu.internals import memory as _memory

    acct = _memory.current()
    return acct is not None and acct.enabled


def _batch_nbytes(batch) -> int:
    """Cheap byte estimate for one forwarded batch: sample a few rows
    (``internals/memory.py approx_nbytes``) and extrapolate — the
    accountant steps watermarks off this, it does not bill."""
    from pathway_tpu.internals import memory as _memory

    try:
        n = len(batch)
    except TypeError:
        return 1024
    if n == 0:
        return 0
    sampled = 0
    taken = 0
    for row in batch:
        sampled += _memory.approx_nbytes(row)
        taken += 1
        if taken >= 8:
            break
    return (sampled // max(1, taken)) * n


class SupervisorPolicy:
    """Restart policy for a supervised connector thread.

    ``max_restarts=0`` disables in-place restart entirely (every failure
    is immediately permanent). ``retry_on`` classifies exceptions — False
    fails fast; the default honors an exception's ``retryable`` attribute
    (True when absent). ``backoff`` is a sync
    :class:`~pathway_tpu.udfs.retries.RetryPolicy`; when omitted, one is
    built from ``PATHWAY_CONNECTOR_BACKOFF_MS`` (default 500) with jitter
    seeded per connector name so restart schedules replay
    deterministically. ``heartbeat_timeout_s`` arms the runtime watchdog.
    Attach to a subject as ``subject._supervisor_policy``; the default
    budget comes from ``PATHWAY_CONNECTOR_MAX_RESTARTS`` (default 3).
    """

    def __init__(
        self,
        max_restarts: int | None = None,
        backoff=None,
        retry_on: Callable[[Exception], bool] | None = None,
        heartbeat_timeout_s: float | None = None,
    ):
        if max_restarts is None:
            max_restarts = int(
                os.environ.get("PATHWAY_CONNECTOR_MAX_RESTARTS", "3") or 3
            )
        self.max_restarts = max_restarts
        self.backoff = backoff
        self.retry_on = retry_on
        self.heartbeat_timeout_s = heartbeat_timeout_s

    @classmethod
    def for_connector(cls, conn) -> "SupervisorPolicy":
        pol = getattr(conn.subject, "_supervisor_policy", None)
        return pol if pol is not None else cls()

    def retryable(self, exc: Exception) -> bool:
        from pathway_tpu.udfs.retries import is_retryable

        return is_retryable(exc, self.retry_on)

    def resolved_backoff(self, name: str):
        if self.backoff is not None:
            return self.backoff
        from pathway_tpu.udfs.retries import RetryPolicy

        base = float(os.environ.get("PATHWAY_CONNECTOR_BACKOFF_MS", "500") or 500)
        return RetryPolicy(
            max_retries=self.max_restarts,
            initial_delay_ms=base,
            backoff_factor=2.0,
            jitter_ms=base * 0.25,
            max_delay_ms=30_000,
            rng=random.Random(zlib.crc32(name.encode("utf-8", "replace"))),
        )


def _runtime_of(conn):
    runtime = getattr(getattr(conn, "node", None), "scope", None)
    return getattr(runtime, "runtime", None)


def close_subjects_for_rollback(conns, deadline_s: float = 1.0) -> None:
    """Best-effort ``subject.on_stop()`` fan-out before a mesh rollback
    exit. Each on_stop runs on its own daemon thread (a subject wedged in
    teardown must not stall the rollback) and the TOTAL wait is bounded
    by ``deadline_s`` — stragglers are simply abandoned to the process
    exit, exactly as a hard crash would."""
    threads: list[threading.Thread] = []
    for conn in conns:
        on_stop = getattr(getattr(conn, "subject", None), "on_stop", None)
        if on_stop is None or getattr(conn, "finished", False):
            continue

        def _stop(fn=on_stop):
            try:
                fn()
            except Exception:
                pass  # the rank is exiting; failures here are moot

        t = threading.Thread(target=_stop, daemon=True)
        t.start()
        threads.append(t)
    deadline = _time.monotonic() + deadline_s
    for t in threads:
        t.join(max(0.0, deadline - _time.monotonic()))


def abort_sinks_for_rollback(sinks, deadline_s: float = 1.0) -> None:
    """Best-effort ``TransactionalSink.abort_for_rollback()`` fan-out
    before a mesh rollback exit — the egress sibling of
    :func:`close_subjects_for_rollback`: the dying epoch's
    un-pre-committed staged output is discarded. Recovery would discard
    it anyway (no committed cut claims it); doing it here reclaims the
    disk early and makes the abort observable on
    ``sink_aborted_total``. Same bounded-daemon-thread contract: a sink
    wedged in teardown must not stall the rollback."""
    threads: list[threading.Thread] = []
    for sink in sinks:
        abort = getattr(sink, "abort_for_rollback", None)
        if abort is None:
            continue

        def _abort(fn=abort):
            try:
                fn()
            except Exception:
                pass  # the rank is exiting; failures here are moot

        t = threading.Thread(target=_abort, daemon=True)
        t.start()
        threads.append(t)
    deadline = _time.monotonic() + deadline_s
    for t in threads:
        t.join(max(0.0, deadline - _time.monotonic()))


def _report_permanent(conn, failure: Exception) -> None:
    """Record a permanent connector failure and route it to the runtime
    (single door shared by the supervisor epilogue and the last-resort
    BaseException shell)."""
    conn.failure = failure
    report = getattr(_runtime_of(conn), "report_connector_error", None)
    if report is not None:
        report(conn, failure)


def run_connector_thread(conn, out_queue: "queue.Queue") -> None:
    """Thin shell around the supervised driver: whatever happens — even a
    failure in the supervisor prologue itself — the finish sentinel MUST
    reach the queue, or the main loop waits on this connector forever."""
    try:
        _run_supervised(conn, out_queue)
    except BaseException as exc:
        if getattr(conn, "failure", None) is None:
            _report_permanent(
                conn,
                exc
                if isinstance(exc, Exception)
                # SystemExit/KeyboardInterrupt on a connector thread is
                # still truncated input — record it, then let it propagate
                else RuntimeError(f"connector thread aborted: {exc!r}"),
            )
        if not isinstance(exc, Exception):
            raise
    finally:
        out_queue.put((conn, None, None, []))


def _stamp(conn) -> None:
    """Event-time lag watermark, connector half: stamp ingest time once
    per forwarded queue entry (perf_counter_ns, the engine's trace
    timebase). The runtime pops stamps FIFO as it drains entries and
    keys commit→emit freshness off them (engine/runtime.py
    ``_note_ingest``/``note_output_emit``); appends are GIL-atomic, so
    the subject thread needs no lock."""
    q = getattr(conn, "_ingest_ns", None)
    if q is None:
        import collections

        q = conn._ingest_ns = collections.deque()
    q.append(_time.perf_counter_ns())


def _run_supervised(conn, out_queue: "queue.Queue") -> None:
    subject = conn.subject
    parser = conn.parser
    # parse_batch defers per-message parsing to flush time so runs of
    # simple upserts go through one C call instead of a Python closure per
    # row (io/python.py attaches it; other parsers fall back to a loop)
    parse_batch = getattr(parser, "parse_batch", None)
    if parse_batch is None:

        def parse_batch(msgs):
            out: list = []
            for m in msgs:
                out.extend(parser(m))
            return out

    from pathway_tpu.engine.stream import is_native_batch

    policy = SupervisorPolicy.for_connector(conn)
    conn_name = getattr(conn, "name", "?")
    pending: list = []  # raw messages, parsed at flush under `lock`
    # batches forwarded to the engine but not yet covered by a journal
    # entry (stateful subjects only); doubles as the restart-compensation
    # ledger. Holds whole batches; backlog_rows counts their rows.
    unjournaled: list = []
    backlog_rows = 0
    lock = threading.Lock()
    has_state = hasattr(subject, "snapshot_state")
    can_seek = has_state and hasattr(subject, "seek")
    runtime = _runtime_of(conn)
    # _ephemeral subjects (the REST serving gateway) opt out of input
    # journaling entirely: their rows are live requests whose futures the
    # serving frontend owns — replaying a dead epoch's journaled queries
    # at epoch+1 would double-dispatch the requests the frontend is
    # already replaying
    persisting = (
        getattr(runtime, "persistence", None) is not None
        and not getattr(subject, "_ephemeral", False)
    )
    # pure-upsert parsers (primary-keyed, deletions disabled) make rescans
    # idempotent at the engine: re-inserting a live key retracts the
    # previous row, so restart needs no compensation ledger. pk parsers
    # that may also see removes are rescan-UNSAFE both ways (a re-scanned
    # remove retracts twice; ledger negation fights the session dict), so
    # they restart as continuations only.
    is_pk = getattr(parser, "is_pk", False)
    is_upsert = getattr(parser, "is_upsert", False)
    rescan_safe = can_seek and (is_upsert or not is_pk)
    # default supervision restarts only subjects whose restart is provably
    # duplicate-free (rescannable with compensation, or upsert-idempotent);
    # anything else re-running from scratch would push duplicate rows into
    # live outputs, so it must opt in with an explicit policy
    supervised = policy.max_restarts > 0 and (
        getattr(subject, "_supervisor_policy", None) is not None
        or rescan_safe
    )
    # heartbeats exist for the runtime watchdog only: skip the per-row
    # monotonic()+store on the emit hot path when nobody is watching
    watching = (
        getattr(conn, "watchdog_timeout", None) is not None
        or policy.heartbeat_timeout_s is not None
    )
    # -- source pacing (ISSUE 19) -----------------------------------------
    # Pausable subjects stop READING under memory pressure instead of
    # degrading journal guarantees: the runtime's pacing pass
    # (engine/runtime.py _service_connector_health) clears/sets the gate
    # off the pure protocol transitions pace_decide/pace_resume, and
    # emit() blocks on it BEFORE queueing the row. The REST gateway's
    # _ephemeral subject is never paused (its rows are live requests the
    # serving frontend already governs with admission + Retry-After);
    # subjects may opt out explicitly with ``_pausable = False``.
    pausable = not getattr(subject, "_ephemeral", False) and getattr(
        subject, "_pausable", True
    )
    conn.pausable = pausable
    gate = getattr(conn, "pace_gate", None)
    if gate is None:
        gate = conn.pace_gate = threading.Event()
        gate.set()  # running; the pacing pass clears it to pause
    governed = _governed()
    # put-side self-pacing: the engine's pacing pass runs once per loop
    # iteration, and one iteration can step for seconds — an unthrottled
    # in-process source could queue tens of MB between two verdicts. So
    # the SUBJECT thread also consults the same bound transitions on its
    # own emit path: once its queued-but-undrained bytes cross the high
    # watermark it parks until the main loop drains back under the low
    # one (the transitions compare magnitudes and are unit-agnostic —
    # bytes here, rows in the engine pass). Same deadlock-freedom
    # argument: the signal shrinks on the main loop only.
    _acct = None
    if governed and pausable:
        from pathway_tpu.internals import memory as _memory

        _acct = _memory.current()

    def account_put(batch) -> None:
        # ENGINE-DRAINABLE backlog accounting (the pacing signal): rows/
        # bytes put on the out queue, matched by rows/bytes_drained on
        # the runtime side as the main loop accepts the entries. Two
        # monotonic single-writer counters per axis — no lock, no race —
        # and both sides estimate from the SAME batch object, so the
        # difference is exactly the queued entries. The journal ledger
        # is deliberately NOT a pacing input: it only drains at subject
        # commit boundaries, and a paused subject can never reach one —
        # pacing on it would be the self-deadlock check_pacing rules out.
        if governed and batch:
            conn.rows_put = getattr(conn, "rows_put", 0) + len(batch)
            conn.bytes_put = (
                getattr(conn, "bytes_put", 0) + _batch_nbytes(batch)
            )

    # track the forwarded-but-unclaimed backlog whenever anyone needs it:
    # persistence (journal it at the next boundary) or the supervisor
    # (negate it before a non-upsert rescan). Kept at BATCH granularity —
    # columnar NativeBatches stay columnar until a boundary journals them
    # or a restart actually needs compensation rows.
    track_backlog = has_state and (
        persisting or (supervised and rescan_safe and not is_upsert)
    )
    warned_backlog = False
    forwarded_since_boundary = 0
    # commit boundaries published so far; the supervisor uses it to reset
    # the restart budget once a restarted subject proves recovery by
    # reaching a new boundary
    boundary_seq = 0
    # the scan state restart rolls back to: the subject's own pre-run
    # position (captured before any row is forwarded, so a failure before
    # the first commit boundary still rescans exactly), refreshed by
    # every published commit state
    last_published_state = getattr(conn, "restored_state", None)
    if can_seek and last_published_state is None:
        try:
            last_published_state = subject.snapshot_state()
        except Exception as exc:
            # restart degrades to continuation for this subject: surface
            # it — the exactly-once rescan guarantee is weakened
            last_published_state = None
            report = getattr(runtime, "report_connector_degraded", None)
            if report is not None:
                report(
                    conn_name,
                    "initial snapshot_state() failed; restarts degrade "
                    f"to at-least-once continuation: {exc!r}",
                )
    # timer-based autocommit (reference: commit_duration cadence in the
    # worker poller, connectors/mod.rs): rows accumulate into one commit
    # until `autocommit_duration_ms` elapses or the subject commits
    # explicitly — this is what gives downstream batched UDFs whole
    # logical-time batches instead of row-at-a-time dribbles. The runtime's
    # main loop calls `conn.force_flush` on its own cadence so rows are not
    # stranded while the subject blocks waiting for input.
    duration_ms = getattr(subject, "_autocommit_duration_ms", None)
    last_flush = _time.monotonic()
    # hot-path fault hook, resolved once per thread (plans are installed
    # before the run starts); None keeps emit() at zero overhead
    _fp = _faults.fault_point if _faults.active_plan() is not None else None

    def heartbeat() -> None:
        if watching:
            conn.last_activity = _time.monotonic()

    def rows_of(batch):
        """Materialized (key, row, diff) view of a parsed batch — the
        journal and the restart compensation need real tuples (a columnar
        NativeBatch carries no picklable rows)."""
        return list(batch) if is_native_batch(batch) else batch

    def jrows_of(batch):
        """Journal view: empty when nothing journals (no persistence
        configured); the engine always receives the batch itself."""
        return rows_of(batch) if persisting else []

    def ledger_rows():
        """Flatten the batch-granular ledger into rows (only called at a
        journaling boundary or an actual restart — steady-state flushes
        never materialize columnar batches)."""
        return [row for b in unjournaled for row in rows_of(b)]

    def take_batch() -> list:
        """Parse and claim the currently queued messages. Caller holds
        `lock`. Appends from the subject thread are GIL-atomic, so the
        snapshot + del-prefix pair never drops a message that lands
        mid-flush — it simply stays queued for the next flush."""
        msgs = pending[:]
        if not msgs:
            return []
        del pending[: len(msgs)]
        try:
            return parse_batch(msgs)
        except Exception as exc:
            # a failing flush must not drop the claimed messages: restore
            # them (prepend — later emits kept appending). But a parse
            # failure is deterministic data poison AND may have half-
            # applied stateful parser sessions (pk live_rows) — a rescan
            # would emit retractions for rows the engine never received —
            # so classify it non-retryable: fail fast, never restart.
            pending[:0] = msgs
            try:
                exc.retryable = False
                # hard marker the supervisor honors even when a user
                # retry_on says "retry everything": rescanning after a
                # half-applied parser session corrupts multiplicities
                exc.pw_parse_poison = True
            except Exception:
                pass
            raise

    def timer_flush() -> None:
        nonlocal last_flush, warned_backlog, forwarded_since_boundary
        nonlocal backlog_rows
        # resolved dynamically (flushes are not per-row hot) so plans
        # installed mid-run still cover this point
        _faults.fault_point("connector.flush")  # pre-take_batch: loses nothing
        last_flush = _time.monotonic()
        with lock:
            batch = take_batch()
            if not batch:
                return
            # heartbeat only on real progress: the runtime's wall-clock
            # force_flush cadence would otherwise refresh last_activity
            # for a dead-blocked subject and defeat the stall watchdog
            heartbeat()
            _stamp(conn)  # one ingest stamp per forwarded entry
            forwarded_since_boundary += len(batch)
            if track_backlog:
                # the subject may be mid-scan on its own thread, so its
                # bookkeeping can lag these rows — journaling them now with
                # a concurrently captured state double-counts on restore
                # (journal replay + rescan re-emitting the same keys)
                unjournaled.append(batch)
                backlog_rows += len(batch)
                # Overload routes through pacing FIRST (ISSUE 19): with
                # memory governance active, a pausable subject that has
                # shown a commit boundary never takes the at-least-once
                # escape — its ledger is bounded by its commit cadence
                # and its byte pressure by the pacing watermarks. A
                # subject that never commits is non-pausable in the only
                # sense that matters here (pausing it could never
                # resume), so the cap remains its bounded-memory escape
                # — error-logged and counted, no longer silent.
                paceable = pausable and governed and boundary_seq > 0
                if backlog_rows > _BACKLOG_CAP and not paceable:
                    # journal stateless (at-least-once for this span)
                    # rather than grow host memory without bound
                    msg = (
                        f"connector {conn_name} emitted "
                        f"{backlog_rows} rows without a commit() "
                        "boundary; recovery degrades to at-least-once for "
                        "this span. Stateful subjects should call commit() "
                        "regularly."
                    )
                    if not warned_backlog:
                        warned_backlog = True
                        import logging

                        logging.getLogger(__name__).error(msg)
                    if runtime is not None:
                        report = getattr(
                            runtime, "report_connector_degraded", None
                        )
                        if report is not None:
                            report(conn_name, msg)
                    account_put(batch)
                    if persisting:
                        out_queue.put((conn, batch, None, ledger_rows()))
                    else:
                        out_queue.put((conn, batch, None, []))
                    unjournaled.clear()
                    backlog_rows = 0
                else:
                    account_put(batch)
                    out_queue.put((conn, batch, None, []))
            elif has_state:
                # nothing journals and restart needs no ledger (no
                # persistence + upsert-idempotent or unseekable subject)
                account_put(batch)
                out_queue.put((conn, batch, None, []))
            else:
                account_put(batch)
                out_queue.put((conn, batch, None, jrows_of(batch)))

    def commit_flush() -> None:
        # subject-driven boundary (subject.commit() / end of run()): runs on
        # the subject thread after its bookkeeping was updated, so the
        # captured state claims exactly journal ∪ backlog ∪ this batch
        nonlocal last_flush, forwarded_since_boundary, last_published_state
        nonlocal boundary_seq, backlog_rows
        _faults.fault_point("connector.flush")
        last_flush = _time.monotonic()
        heartbeat()
        with lock:
            batch = take_batch()
            if batch:
                # stamps pair 1:1 with entries that carry rows — a
                # state-only boundary ships no stamp (the runtime pops
                # one per non-empty entry, FIFO)
                _stamp(conn)
            if has_state:
                journal_rows = (
                    ledger_rows() + jrows_of(batch) if persisting else []
                )
                # publish a state even with an empty journal batch when rows
                # were forwarded since the last boundary (operator-snapshot
                # mode needs the state to cover them). `batch` enters the
                # condition directly: without persistence journal_rows is
                # always empty, but a committed batch must still reach the
                # engine
                dirty = (
                    bool(journal_rows)
                    or bool(batch)
                    or forwarded_since_boundary > 0
                )
                if not dirty:
                    return
                try:
                    state = subject.snapshot_state()
                except BaseException:
                    # snapshot failed mid-boundary: forward the parsed
                    # batch like a timer flush (no state, no journal) so
                    # its rows are neither stranded nor missing from the
                    # compensation ledger, then surface the failure — the
                    # ledger is only cleared on a successful snapshot
                    if batch:
                        forwarded_since_boundary += len(batch)
                        if track_backlog:
                            unjournaled.append(batch)
                            backlog_rows += len(batch)
                        account_put(batch)
                        out_queue.put((conn, batch, None, []))
                    raise
                last_published_state = state
                boundary_seq += 1
                unjournaled.clear()
                backlog_rows = 0
                forwarded_since_boundary = 0
                account_put(batch)
                out_queue.put((conn, batch, state, journal_rows))
            elif batch:
                account_put(batch)
                out_queue.put((conn, batch, None, jrows_of(batch)))

    def emit(message: Any) -> None:
        # list.append is GIL-atomic: no lock on the per-row producer path.
        # duration_ms None disables autocommit entirely (reference:
        # io/python/__init__.py autocommit_duration_ms=None) — rows then
        # move only at explicit subject.commit() boundaries.
        if _fp:
            _fp("connector.read")
        if pausable and not gate.is_set():
            # paced (ISSUE 19): stop READING here, before the row is
            # queued, until the runtime's pacing pass releases the gate
            # off pace_resume. Heartbeats keep flowing so the paced wait
            # is visibly alive; the watchdog additionally exempts paused
            # connectors from the stall verdict (conn.paused).
            while not gate.wait(0.2):
                heartbeat()
        if _acct is not None and _acct._pace_decide(
            _acct.state,
            conn.bytes_put - conn.bytes_drained,
            _acct.high_bytes,
        ):
            # self-paced: own out-queue bytes crossed the high watermark
            # (or the ladder already left "ok") — park before reading
            # more, resume under the low watermark for hysteresis
            while not _acct._pace_resume(
                _acct.state,
                conn.bytes_put - conn.bytes_drained,
                _acct.low_bytes,
            ):
                if _memory.current() is not _acct:
                    break  # run over — the accountant was retired
                heartbeat()
                _time.sleep(0.05)
        pending.append(message)
        if duration_ms is not None:
            now = _time.monotonic()
            if watching:
                conn.last_activity = now
            if (now - last_flush) * 1000.0 >= duration_ms:
                timer_flush()
        elif watching:
            conn.last_activity = _time.monotonic()

    def force_flush() -> None:
        # called from the runtime loop's cadence; respects the autocommit
        # window so steady sources still batch up to duration_ms
        if duration_ms is None or (
            (_time.monotonic() - last_flush) * 1000.0 < duration_ms
        ):
            return
        timer_flush()

    conn.force_flush = force_flush

    def restart_reset() -> None:
        """Roll the session back to the last published scan state before
        re-running the subject (non-upsert rescannable subjects get their
        forwarded-but-unclaimed rows retracted first — rescan then re-
        emits the same stable keys, netting exactly-once). Rescan-unsafe
        subjects (pk sessions with removes, unseekable, no rollback
        state) restart as continuations instead: pending and forwarded
        rows stay, the subject re-runs from wherever it is."""
        nonlocal forwarded_since_boundary, backlog_rows
        if not rescan_safe or last_published_state is None:
            return
        with lock:
            if not is_upsert:
                comp = [
                    (k, r, -d) for (k, r, d) in ledger_rows()
                ]
                if comp:
                    _stamp(conn)
                    account_put(comp)
                    out_queue.put((conn, comp, None, []))
                # engine rolled back to the boundary: the ledger restarts
                # empty, matching it
                unjournaled.clear()
                backlog_rows = 0
                forwarded_since_boundary = 0
            # upsert path: the engine KEEPS the forwarded rows (the rescan
            # retracts/re-inserts through the live session), so the ledger
            # must keep them too — clearing it would journal only the
            # rescan's retract/insert pair at the next boundary, which
            # consolidates to nothing on replay (silent loss)
            pending.clear()
        subject.seek(last_published_state)

    # -- supervisor loop ---------------------------------------------------
    attempt = 0
    budget_boundary = -1  # boundary_seq at the last restart
    failure: Exception | None = None
    try:
        backoff = policy.resolved_backoff(conn_name)
        while True:
            heartbeat()
            subject._attach(emit, commit_flush)
            try:
                subject.run()
                break
            except Exception as exc:
                # a restart that reached a fresh durable boundary counts
                # as recovered: the budget is per failure episode, so a
                # long-lived source surviving one transient failure per
                # day is not killed on day max_restarts+1
                if boundary_seq != budget_boundary and attempt:
                    attempt = 0
                if (
                    not supervised
                    or attempt >= policy.max_restarts
                    or getattr(exc, "pw_parse_poison", False)
                    or not policy.retryable(exc)
                ):
                    failure = exc
                    break
                attempt += 1
                budget_boundary = boundary_seq
                if runtime is not None:
                    report = getattr(
                        runtime, "report_connector_restart", None
                    )
                    if report is not None:
                        report(conn, exc, attempt)
                restart_reset()  # a broken seek falls through as permanent
                # sliced backoff sleep with heartbeats: a connector
                # deliberately backing off must not trip the watchdog
                deadline = _time.monotonic() + backoff.delay_s(attempt - 1)
                while True:
                    heartbeat()
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        break
                    _time.sleep(min(0.2, remaining))
    except Exception as sup_exc:
        # the supervisor machinery itself failed (user retry_on/backoff
        # callbacks, seek, ...): permanent
        failure = sup_exc
    finally:
        # epilogue runs even for BaseException (SystemExit on the subject
        # thread): on_stop cleanup + the final boundary flush, exactly as
        # the pre-supervision driver guaranteed
        try:
            subject.on_stop()
        except Exception:
            pass
        try:
            commit_flush()
        except Exception as exc:
            if failure is None:
                failure = exc
        if failure is not None:
            _report_permanent(conn, failure)
    # the finish sentinel is enqueued by run_connector_thread's finally
