"""Connector thread driver (reference: src/connectors/mod.rs:91 Connector —
per-source thread reading into an mpsc channel drained by the main loop)."""

from __future__ import annotations

import queue
import threading
import time as _time
from typing import Any, Callable


def run_connector_thread(conn, out_queue: "queue.Queue") -> None:
    subject = conn.subject
    parser = conn.parser
    pending: list = []
    lock = threading.Lock()
    # timer-based autocommit (reference: commit_duration cadence in the
    # worker poller, connectors/mod.rs): rows accumulate into one commit
    # until `autocommit_duration_ms` elapses or the subject commits
    # explicitly — this is what gives downstream batched UDFs whole
    # logical-time batches instead of row-at-a-time dribbles. The runtime's
    # main loop calls `conn.force_flush` on its own cadence so rows are not
    # stranded while the subject blocks waiting for input.
    duration_ms = getattr(subject, "_autocommit_duration_ms", None)
    last_flush = _time.monotonic()

    def emit(message: Any) -> None:
        deltas = parser(message)
        if deltas:
            with lock:
                pending.extend(deltas)
            if duration_ms is None:
                flush()
            elif (_time.monotonic() - last_flush) * 1000.0 >= duration_ms:
                flush()

    def flush() -> None:
        nonlocal last_flush
        last_flush = _time.monotonic()
        with lock:
            if pending:
                # subject scan state captured WITH the batch: on restore,
                # the journaled prefix and the seek state agree (a snapshot
                # taken later could claim rows the journal never got)
                state = (
                    subject.snapshot_state()
                    if hasattr(subject, "snapshot_state")
                    else None
                )
                out_queue.put((conn, pending.copy(), state))
                pending.clear()

    def force_flush() -> None:
        # called from the runtime loop's cadence; respects the autocommit
        # window so steady sources still batch up to duration_ms
        if (
            duration_ms is not None
            and (_time.monotonic() - last_flush) * 1000.0 < duration_ms
        ):
            return
        flush()

    conn.force_flush = force_flush

    subject._attach(emit, flush)
    try:
        subject.run()
    except Exception as exc:  # surfaced by the main loop
        conn.node.scope.runtime.error = exc
    finally:
        try:
            subject.on_stop()
        except Exception:
            pass
        flush()
        out_queue.put((conn, None, None))
