"""pw.io.gdrive — Google Drive source (reference:
python/pathway/io/gdrive — recursive directory scan over the Drive v3
API with modifiedTime diffing and deletion detection).

Redesigned transport: no google-api-python-client — the Drive v3 REST
API is driven directly over urllib (files.list with a parent query,
files/{id}?alt=media downloads), authenticated by the installed
google-auth service-account credentials (or any object with a
``token``/``refresh`` interface, or a raw bearer token for tests).
"""

from __future__ import annotations

import fnmatch
import json as _json
import urllib.parse
import urllib.request
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import schema_from_types
from pathway_tpu.io._gauth import bearer_token
from pathway_tpu.io._objstore import ObjectStoreSubject
from pathway_tpu.io.python import read as python_read

__all__ = ["read"]

_FIELDS = "id,name,mimeType,parents,modifiedTime,size,trashed"
_FOLDER = "application/vnd.google-apps.folder"


class _DriveClient:
    def __init__(self, credentials, endpoint=None, opener=None):
        self.credentials = credentials
        self.endpoint = (endpoint or "https://www.googleapis.com/drive/v3").rstrip("/")
        self._opener = opener or urllib.request.build_opener()

    def _token(self) -> str:
        return bearer_token(self.credentials)

    def _get(self, path: str, query: dict | None = None) -> bytes:
        qs = f"?{urllib.parse.urlencode(query)}" if query else ""
        req = urllib.request.Request(
            f"{self.endpoint}{path}{qs}",
            headers={"Authorization": f"Bearer {self._token()}"},
        )
        with self._opener.open(req, timeout=60) as resp:
            return resp.read()

    def list_children(self, folder_id: str) -> list[dict]:
        items, token = [], None
        while True:
            query = {
                "q": f"'{folder_id}' in parents and trashed = false",
                "fields": f"nextPageToken, files({_FIELDS})",
                "pageSize": "1000",
            }
            if token:
                query["pageToken"] = token
            payload = _json.loads(self._get("/files", query))
            items.extend(payload.get("files", []))
            token = payload.get("nextPageToken")
            if not token:
                return items

    def get_file(self, file_id: str) -> dict:
        return _json.loads(
            self._get(f"/files/{file_id}", {"fields": _FIELDS})
        )

    def download(self, file_id: str) -> bytes:
        return self._get(f"/files/{file_id}", {"alt": "media"})


class _GDriveSubject(ObjectStoreSubject):
    """fmt='binary' object-store scan over Drive file ids: the shared
    scanner owns modified-diffing, RETRACTION of previous rows on
    change, deletion detection, and snapshot bookkeeping."""

    _scheme = "gdrive"

    def __init__(self, client, object_id, mode, refresh_interval,
                 with_metadata, object_size_limit, patterns):
        super().__init__("binary", with_metadata, mode, refresh_interval)
        self.client = client
        self.object_id = object_id
        self.object_size_limit = object_size_limit
        self.patterns = patterns

    def _walk(self):
        """Yield file entries under object_id (dirs recursed)."""
        root = self.client.get_file(self.object_id)
        if root.get("mimeType") != _FOLDER:
            yield root
            return
        stack = [self.object_id]
        while stack:
            for entry in self.client.list_children(stack.pop()):
                if entry.get("mimeType") == _FOLDER:
                    stack.append(entry["id"])
                else:
                    yield entry

    def _accepts(self, entry: dict) -> bool:
        if self.object_size_limit is not None:
            size = int(entry.get("size", 0) or 0)
            if size > self.object_size_limit:
                return False
        if self.patterns:
            return any(
                fnmatch.fnmatch(entry.get("name", ""), p)
                for p in self.patterns
            )
        return True

    def _list(self):
        for entry in self._walk():
            if not self._accepts(entry):
                continue
            extras = {
                k: entry.get(k)
                for k in ("id", "name", "mimeType", "parents", "modifiedTime")
            }
            yield entry["id"], entry.get("modifiedTime", ""), extras

    def _get(self, name: str) -> bytes:
        return self.client.download(name)

    def _uri(self, name: str) -> str:
        return f"gdrive:{name}"


def read(
    object_id: str,
    *,
    mode: str = "streaming",
    object_size_limit: int | None = None,
    refresh_interval: int = 30,
    service_user_credentials_file: str | None = None,
    with_metadata: bool = False,
    file_name_pattern: list | str | None = None,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    _credentials=None,
    _endpoint=None,
    _opener=None,
):
    """Read a Google Drive file or directory (recursively) as binary
    rows (reference: io/gdrive/__init__.py:336 — streaming re-scans
    every refresh_interval with upserts and deletion detection)."""
    if mode not in ("streaming", "static"):
        raise ValueError(f"Unrecognized connector mode: {mode}")
    credentials = _credentials
    if credentials is None:
        if service_user_credentials_file is None:
            raise ValueError(
                "pw.io.gdrive.read needs service_user_credentials_file"
            )
        from google.oauth2 import service_account

        credentials = service_account.Credentials.from_service_account_file(
            service_user_credentials_file,
            scopes=["https://www.googleapis.com/auth/drive.readonly"],
        )
    patterns = (
        [file_name_pattern]
        if isinstance(file_name_pattern, str)
        else list(file_name_pattern or [])
    )
    client = _DriveClient(credentials, endpoint=_endpoint, opener=_opener)
    cols: dict[str, Any] = {"data": dt.BYTES}
    if with_metadata:
        cols["_metadata"] = dt.JSON
    subject = _GDriveSubject(
        client, object_id, mode, refresh_interval, with_metadata,
        object_size_limit, patterns,
    )
    return python_read(
        subject,
        schema=schema_from_types(**cols),
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"gdrive:{object_id}",
    )
