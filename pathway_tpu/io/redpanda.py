"""pw.io.redpanda — Kafka-protocol alias (reference:
python/pathway/io/redpanda re-exports the kafka connector)."""

from pathway_tpu.io.kafka import read, write

__all__ = ["read", "write"]
