"""Shared Google-auth bearer token resolution for REST transports
(gdrive, pubsub): accepts a raw token string (tests) or any
google-auth credentials object (refreshes when missing/expired)."""

from __future__ import annotations


def bearer_token(credentials) -> str:
    if isinstance(credentials, str):
        return credentials
    token = getattr(credentials, "token", None)
    if token is None or getattr(credentials, "expired", False):
        import google.auth.transport.requests

        credentials.refresh(google.auth.transport.requests.Request())
        token = credentials.token
    return token
