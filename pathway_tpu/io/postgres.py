"""pw.io.postgres — PostgreSQL sink (reference: python/pathway/io/postgres
over the native PsqlWriter, src/connectors/data_storage.rs:1072, with the
updates/snapshot formatters data_format.rs:1632/1691).

Redesigned transport: no psycopg2 — a dependency-free wire-protocol (v3)
client (`pathway_tpu/io/_pg.py`) executes the statements produced by the
existing Psql formatters (io/_formats.py). ``write`` streams the update
log (INSERT rows carrying time/diff); ``write_snapshot`` maintains the
current state via upsert-on-primary-key / DELETE.
"""

from __future__ import annotations

from typing import Sequence

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._formats import PsqlSnapshotFormatter, PsqlUpdatesFormatter
from pathway_tpu.io._pg import PgConnection

__all__ = ["write", "write_snapshot"]


def _writer(table, postgres_settings, formatter, op_name, max_batch_size,
            _connection):
    cols = table.column_names()
    state = {"conn": _connection, "buf": []}

    def _conn():
        if state["conn"] is None:
            state["conn"] = PgConnection(**postgres_settings)
        return state["conn"]

    def _flush():
        if not state["buf"]:
            return
        stmts = "".join(state["buf"])
        state["buf"] = []
        _conn().execute("BEGIN;\n" + stmts + "COMMIT;")

    def on_change(key, row, time_, diff):
        ctx = formatter.format(key, list(row), time_, diff)
        for payload in ctx.payloads:
            state["buf"].append(payload.decode())
        if max_batch_size is not None and len(state["buf"]) >= max_batch_size:
            _flush()

    def on_time_end(time_):
        _flush()

    def on_end():
        _flush()
        if state["conn"] is not None:
            state["conn"].close()
            state["conn"] = None

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table), on_change=on_change,
            on_time_end=on_time_end, on_end=on_end,
        )

    G.add_operator([table], [], lower, op_name, is_output=True)


def write(
    table,
    postgres_settings: dict,
    table_name: str,
    max_batch_size: int | None = None,
    *,
    _connection=None,
) -> None:
    """Stream the table's update log into a Postgres table (reference:
    io/postgres/__init__.py:18 — target table needs integer ``time`` and
    ``diff`` columns)."""
    _writer(
        table,
        postgres_settings,
        PsqlUpdatesFormatter(table_name, table.column_names()),
        "postgres_write",
        max_batch_size,
        _connection,
    )


def write_snapshot(
    table,
    postgres_settings: dict,
    table_name: str,
    primary_key: Sequence[str],
    max_batch_size: int | None = None,
    *,
    _connection=None,
) -> None:
    """Maintain the CURRENT snapshot of the table in Postgres (reference:
    io/postgres/__init__.py:113 — upsert on the primary key, DELETE on
    retraction)."""
    _writer(
        table,
        postgres_settings,
        PsqlSnapshotFormatter(
            table_name, list(primary_key), table.column_names()
        ),
        "postgres_write_snapshot",
        max_batch_size,
        _connection,
    )
