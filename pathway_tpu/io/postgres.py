"""pw.io.postgres — connector surface (reference: python/pathway/io/postgres (native PsqlWriter data_storage.rs:1072; snapshot/updates formatters data_format.rs:1632,1691)).

Client transport gated on its library; the configuration surface matches
the reference so templates parse and fail only at run time with a clear
dependency error."""

from __future__ import annotations

from pathway_tpu.io._gated import require


def write(table, *args, name=None, **kwargs):
    require('psycopg2')
    raise NotImplementedError(
        "pw.io.postgres.write: client library found, but no postgres service "
        "transport is wired in this build"
    )
