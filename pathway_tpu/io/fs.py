"""pw.io.fs — filesystem connector (reference: python/pathway/io/fs +
src/connectors/scanner/filesystem.rs:139 — glob polling with metadata and
deletion detection).

Static mode materialises matching files once; streaming mode polls the glob
for new/modified files on a connector thread.
"""

from __future__ import annotations

import csv as _csv
import glob as _glob
import json as _json
import os
import time
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import Json, ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema, schema_from_types
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io.python import ConnectorSubject, read as python_read


def _iter_paths(path: str) -> list[str]:
    if os.path.isdir(path):
        out = []
        for root, _, files in os.walk(path):
            for f in sorted(files):
                out.append(os.path.join(root, f))
        return sorted(out)
    return sorted(_glob.glob(path))


def _parse_file(path: str, fmt: str, value_columns, schema_cols, with_metadata):
    rows: list[dict] = []
    if fmt in ("csv", "dsv"):
        with open(path, newline="") as f:
            for rec in _csv.DictReader(f):
                rows.append({k: _coerce(v) for k, v in rec.items()})
    elif fmt in ("json", "jsonlines"):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(_json.loads(line))
    elif fmt == "plaintext":
        with open(path) as f:
            for line in f:
                rows.append({"data": line.rstrip("\n")})
    elif fmt == "plaintext_by_file":
        with open(path) as f:
            rows.append({"data": f.read()})
    elif fmt == "binary":
        with open(path, "rb") as f:
            rows.append({"data": f.read()})
    else:
        raise ValueError(f"unknown format {fmt!r}")
    if with_metadata:
        st = os.stat(path)
        meta = {
            "path": os.path.abspath(path),
            "size": st.st_size,
            "modified_at": int(st.st_mtime),
            "seen_at": int(time.time()),
        }
        for r in rows:
            r["_metadata"] = Json(meta)
    return rows


def _coerce(v: str):
    if v is None:
        return None
    try:
        return int(v)
    except (ValueError, TypeError):
        pass
    try:
        return float(v)
    except (ValueError, TypeError):
        pass
    if v == "True":
        return True
    if v == "False":
        return False
    return v


class _FsSubject(ConnectorSubject):
    # multi-process runs: every rank scans, each owns the paths that hash
    # to it (reference: per-worker partitioned reads, data_storage.rs:692)
    _distributed_partitioned = True

    def __init__(self, path, fmt, schema, with_metadata, mode, refresh_interval=0.2):
        super().__init__()
        self.path = path
        self.fmt = fmt
        self.schema = schema
        self.with_metadata = with_metadata
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._seen: dict[str, float] = {}
        self._emitted: dict[str, list] = {}
        self._stop = False

    def _owns(self, path: str) -> bool:
        """THE ownership predicate: does this rank scan ``path`` under
        the current world? Shards by the path RELATIVE to the source
        root (absolute paths differ across ranks with different
        mounts/cwds, which would let two ranks own the same file — or
        none own it). Shared by the live scan (``_owned_paths``) and
        the rescale re-shard of committed scan state
        (``reshard_scan_state``), so the two can never drift."""
        from pathway_tpu.internals.config import get_pathway_config
        from pathway_tpu.parallel.procgroup import stable_shard

        c = get_pathway_config()
        if c.processes <= 1:
            return True
        root = self.path if os.path.isdir(self.path) else (
            os.path.dirname(self.path) or "."
        )
        rel = os.path.relpath(path, root)
        return stable_shard(rel, c.processes) == c.process_id

    def _owned_paths(self):
        for p in _iter_paths(self.path):
            if self._owns(p):
                yield p

    def _scan_once(self):
        # modified-file diffing + deletion detection (reference:
        # src/connectors/scanner/filesystem.rs object cache)
        current = set()
        for p in self._owned_paths():
            try:
                mtime = os.path.getmtime(p)
            except OSError:
                continue
            current.add(p)
            if self._seen.get(p) == mtime:
                continue
            for old_key, old_row in self._emitted.pop(p, []):
                self._remove(old_key, old_row)
            rows = _parse_file(
                p, self.fmt, None, self.schema.column_names(), self.with_metadata
            )
            # stable per-(path, line) keys so deleting a file retracts ITS
            # rows even when identical content exists in other files
            keyed = [
                (ref_scalar("fs", os.path.abspath(p), i), row)
                for i, row in enumerate(rows)
            ]
            for key, row in keyed:
                self._upsert(key, row)
            # scan state recorded only AFTER the rows are emitted, so a
            # flush snapshot can never claim a file whose rows it lacks
            self._emitted[p] = keyed
            self._seen[p] = mtime
        for p in list(self._emitted):
            if p not in current:
                for old_key, old_row in self._emitted.pop(p, []):
                    self._remove(old_key, old_row)
                self._seen.pop(p, None)
        self.commit()

    def run(self):
        self._scan_once()
        if self.mode == "static":
            return
        while not self._stop:
            time.sleep(self.refresh_interval)
            self._scan_once()

    def on_stop(self):
        self._stop = True

    # -- persistence hooks (reference: Reader::seek, data_storage.rs:394;
    # scanner object cache, scanner/filesystem.rs) -------------------------
    def snapshot_state(self):
        return {"seen": dict(self._seen), "emitted": dict(self._emitted)}

    def seek(self, state) -> None:
        self._seen = dict(state.get("seen", {}))
        self._emitted = dict(state.get("emitted", {}))

    def reshard_scan_state(self, states: list) -> dict:
        """Elastic-mesh rescale (persistence/reshard.py): merge every
        old rank's scan state and keep the paths THIS rank owns under
        the new world — the SAME ``_owns`` predicate the live scan
        shards with, so a re-sharded restore never re-reads a committed
        file and never retracts another rank's rows as 'deleted'. Runs
        even for a single old state (a 1→N grow must still re-filter
        the full old coverage per new rank)."""
        seen: dict = {}
        emitted: dict = {}
        for st in states:
            for p, mtime in st.get("seen", {}).items():
                if self._owns(p) and p not in seen:
                    seen[p] = mtime
            for p, keyed in st.get("emitted", {}).items():
                if self._owns(p) and p not in emitted:
                    emitted[p] = keyed
        return {"seen": seen, "emitted": emitted}


def _infer_schema(path: str, fmt: str, with_metadata: bool) -> type[Schema]:
    if fmt in ("plaintext", "plaintext_by_file"):
        cols: dict[str, Any] = {"data": dt.STR}
    elif fmt == "binary":
        cols = {"data": dt.BYTES}
    else:
        sample_rows: list[dict] = []
        for p in _iter_paths(path)[:3]:
            sample_rows.extend(
                _parse_file(p, fmt, None, [], False)[:20]
            )
        names: list[str] = []
        for r in sample_rows:
            for k in r:
                if k not in names:
                    names.append(k)
        cols = {}
        for name in names:
            vals = [r.get(name) for r in sample_rows if name in r]
            cols[name] = dt.lub(*(dt.dtype_of_value(v) for v in vals)) if vals else dt.ANY
    if with_metadata:
        cols["_metadata"] = dt.JSON
    return schema_from_types(**cols)


def read(
    path: str,
    *,
    format: str = "csv",
    schema: type[Schema] | None = None,
    mode: str = "streaming",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 0.2,
    name: str | None = None,
    **kwargs,
) -> Table:
    if format == "plaintext_by_object":
        format = "plaintext_by_file"
    if schema is None:
        schema = _infer_schema(path, format, with_metadata)
    elif with_metadata and "_metadata" not in schema.column_names():
        from pathway_tpu.internals.schema import ColumnDefinition, schema_builder

        cols = dict(schema.columns())
        cols["_metadata"] = ColumnDefinition(dtype=dt.JSON, name="_metadata")
        schema = schema_builder(cols)
    if mode == "static":
        # materialise immediately as a static table
        rows = []
        seq = 0
        pkeys = schema.primary_key_columns()
        cols = schema.column_names()
        defaults = schema.default_values()
        for p in _iter_paths(path):
            for row in _parse_file(p, format, None, cols, with_metadata):
                values = tuple(row.get(c, defaults.get(c)) for c in cols)
                if pkeys:
                    key = ref_scalar(*(row[c] for c in pkeys))
                else:
                    key = ref_scalar("fs", p, seq)
                seq += 1
                rows.append((key, *values))
        from pathway_tpu.debug import table_from_rows

        return table_from_rows(schema, rows)
    subject = _FsSubject(path, format, schema, with_metadata, mode, refresh_interval)
    return python_read(
        subject,
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"fs:{path}",
    )


def write(table: Table, filename: str, *, format: str = "csv", name: str | None = None, **kwargs) -> None:
    """Write the table's change stream to ``filename`` through the
    transactional egress plane (io/txn.py; ISSUE 12): rows are STAGED
    per commit timestamp and become visible only by atomic rename — a
    crash mid-write can never leave a partial file visible. Under
    ``OPERATOR_PERSISTING`` the sink is epoch-aligned: staged output
    finalizes only when the engine's ``snapshot_commit`` marker lands,
    so the committed file is bit-identical across any rollback or
    rescale; without it, segments finalize at every commit timestamp
    (the documented at-least-once boundary)."""
    from pathway_tpu.io.txn import TxnFileSink

    cols = table.column_names()
    sink = TxnFileSink(filename, format=format, cols=cols)

    def lower(ctx):
        # columnar egress (ISSUE 14): NativeBatch deliveries arrive as
        # Arrow record batches (on_batch_arrow) and serialize straight
        # off the columns; tuple deltas (retractions, object columns,
        # PATHWAY_NO_NB_CAPTURE) keep the row path — both encode to
        # bit-identical bytes
        ctx.scope.output(
            ctx.engine_table(table),
            on_batch=sink.on_batch,
            on_batch_arrow=sink.on_batch_arrow,
            arrow_cols=cols,
            on_time_end=sink.on_time_end,
            on_end=sink.on_end,
            txn_sink=sink,
        )

    G.add_operator([table], [], lower, f"fs_write_{format}", is_output=True)
