"""pw.io.elasticsearch — connector surface (reference: python/pathway/io/elasticsearch (native ElasticSearchWriter data_storage.rs:1328)).

Client transport gated on its library; the configuration surface matches
the reference so templates parse and fail only at run time with a clear
dependency error."""

from __future__ import annotations

from pathway_tpu.io._gated import require


def write(table, *args, name=None, **kwargs):
    require('elasticsearch')
    raise NotImplementedError(
        "pw.io.elasticsearch.write: client library found, but no elasticsearch service "
        "transport is wired in this build"
    )
