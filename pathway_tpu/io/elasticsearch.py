"""pw.io.elasticsearch — Elasticsearch sink (reference:
python/pathway/io/elasticsearch over the native ElasticSearchWriter,
src/connectors/data_storage.rs:1328).

Redesigned transport: no elasticsearch client package — the writer
speaks the bulk REST API directly (``POST {host}/{index}/_bulk`` with
ndjson ``{"index": {}}`` action lines, exactly the body the reference
builds at data_storage.rs:1345), authenticated via basic/apikey/bearer
headers. One bulk request per non-empty commit, plus max_batch_size
early flushes like the reference.
"""

from __future__ import annotations

import base64
import json as _json
import urllib.request

from pathway_tpu.internals.parse_graph import G

__all__ = ["ElasticSearchAuth", "write"]


class ElasticSearchAuth:
    """Credential holder (reference: io/elasticsearch/__init__.py:12 —
    same three constructors)."""

    def __init__(self, kind: str, **params):
        self.kind = kind
        self.params = params

    @classmethod
    def apikey(cls, apikey_id, apikey):
        return cls("apikey", apikey_id=apikey_id, apikey=apikey)

    @classmethod
    def basic(cls, username, password):
        return cls("basic", username=username, password=password)

    @classmethod
    def bearer(cls, bearer):
        return cls("bearer", bearer=bearer)

    def header(self) -> str:
        if self.kind == "basic":
            raw = f"{self.params['username']}:{self.params['password']}"
            return "Basic " + base64.b64encode(raw.encode()).decode()
        if self.kind == "apikey":
            raw = f"{self.params['apikey_id']}:{self.params['apikey']}"
            return "ApiKey " + base64.b64encode(raw.encode()).decode()
        return "Bearer " + self.params["bearer"]


def write(
    table,
    host: str,
    auth: ElasticSearchAuth,
    index_name: str,
    *,
    max_batch_size: int | None = None,
    name: str | None = None,
    _opener=None,
) -> None:
    """Write a table to an Elasticsearch index (reference:
    io/elasticsearch/__init__.py:52). Each output row becomes one
    document carrying the columns plus ``time`` and ``diff``."""
    cols = table.column_names()
    opener = _opener or urllib.request.build_opener()
    state = {"buf": []}

    def _flush():
        if not state["buf"]:
            return
        body = ("\n".join(state["buf"]) + "\n").encode()
        state["buf"] = []
        url = f"{host.rstrip('/')}/{index_name}/_bulk"
        req = urllib.request.Request(
            url,
            data=body,
            method="POST",
            headers={
                "Content-Type": "application/x-ndjson",
                "Authorization": auth.header(),
            },
        )
        with opener.open(req, timeout=60) as resp:
            payload = _json.loads(resp.read() or b"{}")
        if payload.get("errors"):
            raise RuntimeError(
                f"elasticsearch bulk errors on index {index_name!r}: "
                f"{str(payload)[:500]}"
            )

    def on_change(key, row, time_, diff):
        doc = dict(zip(cols, row))
        doc["time"] = time_
        doc["diff"] = diff
        state["buf"].append('{"index": {}}')
        state["buf"].append(_json.dumps(doc, default=str))
        if max_batch_size is not None and len(state["buf"]) // 2 >= max_batch_size:
            _flush()

    def on_time_end(time_):
        _flush()

    def on_end():
        _flush()

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table), on_change=on_change,
            on_time_end=on_time_end, on_end=on_end,
        )

    G.add_operator([table], [], lower, "elasticsearch_write", is_output=True)
