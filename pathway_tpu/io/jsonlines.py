"""pw.io.jsonlines (reference: python/pathway/io/jsonlines) — wrapper over fs."""

from __future__ import annotations

from pathway_tpu.io import fs


def read(path, *, schema=None, mode="streaming", **kwargs):
    return fs.read(path, format="jsonlines", schema=schema, mode=mode, **kwargs)


def write(table, filename, **kwargs):
    return fs.write(table, filename, format="jsonlines", **kwargs)
