"""pw.io.subscribe (reference: python/pathway/io/_subscribe.py:13)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def subscribe(
    table: Table,
    on_change: Callable | None = None,
    on_end: Callable | None = None,
    on_time_end: Callable | None = None,
    *,
    on_batch: Callable | None = None,
    with_envelope: bool = False,
    name: str | None = None,
    sort_by=None,
) -> None:
    """on_change(key, row: dict, time: int, is_addition: bool).

    ``on_batch(time, changes)`` is the batched egress: one callback per
    delivered batch with ``changes = [(key, row_dict, diff), ...]`` —
    serving fan-outs and columnar sinks should prefer it over the
    per-row ``on_change`` (which expands every C-owned batch row-wise
    through a Python callback; the Plan Doctor's ``sink.row-expanding``
    diagnostic names exactly that de-optimization).

    ``with_envelope=True`` (ISSUE 12) changes the ``on_batch``
    signature to ``on_batch(envelope, changes)`` where ``envelope`` is
    a :class:`~pathway_tpu.io.txn.DeliveryEnvelope` ``(epoch,
    commit_ts, seq)`` — delivery metadata for the remaining
    at-least-once surface: ``commit_ts`` is the plain ``time`` of the
    unenveloped form (monotone across restarts), ``seq`` strictly
    monotone per subscription within one process incarnation, and an
    epoch bump or ``seq`` reset marks a redelivery window (see the
    ``DeliveryEnvelope`` docstring for the exact guarantees and what
    still needs consumer-side keys).
    """
    cols = tuple(table.column_names())

    def lower(ctx):
        batch_cb = None
        if on_batch is not None:
            if with_envelope:

                def batch_cb(env, deltas):
                    on_batch(
                        env,
                        [
                            (k, dict(zip(cols, row)), d)
                            for k, row, d in deltas
                        ],
                    )

            else:

                def batch_cb(time, deltas):
                    on_batch(
                        time,
                        [
                            (k, dict(zip(cols, row)), d)
                            for k, row, d in deltas
                        ],
                    )

        # dict_cols pushes the row-dict building into the OutputNode's C
        # delivery loop instead of a per-change Python wrapper
        ctx.scope.output(
            ctx.engine_table(table),
            on_change=on_change,
            on_batch=batch_cb,
            on_time_end=on_time_end,
            on_end=on_end,
            dict_cols=cols if on_change is not None else None,
            envelope=with_envelope and on_batch is not None,
        )

    G.add_operator([table], [], lower, "subscribe", is_output=True)
