"""pw.io.subscribe (reference: python/pathway/io/_subscribe.py:13)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def subscribe(
    table: Table,
    on_change: Callable | None = None,
    on_end: Callable | None = None,
    on_time_end: Callable | None = None,
    *,
    name: str | None = None,
    sort_by=None,
) -> None:
    """on_change(key, row: dict, time: int, is_addition: bool)."""
    cols = table.column_names()

    def wrapped_on_change(key, row, time, diff):
        if on_change is not None:
            on_change(key, dict(zip(cols, row)), time, diff > 0)

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table),
            on_change=wrapped_on_change if on_change is not None else None,
            on_time_end=on_time_end,
            on_end=on_end,
        )

    G.add_operator([table], [], lower, "subscribe", is_output=True)
