"""pw.io.subscribe (reference: python/pathway/io/_subscribe.py:13)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def subscribe(
    table: Table,
    on_change: Callable | None = None,
    on_end: Callable | None = None,
    on_time_end: Callable | None = None,
    *,
    on_batch: Callable | None = None,
    with_envelope: bool = False,
    batch_format: str = "rows",
    include_key: bool = True,
    name: str | None = None,
    sort_by=None,
) -> None:
    """on_change(key, row: dict, time: int, is_addition: bool).

    ``on_batch(time, changes)`` is the batched egress: one callback per
    delivered batch with ``changes = [(key, row_dict, diff), ...]`` —
    serving fan-outs and columnar sinks should prefer it over the
    per-row ``on_change`` (which expands every C-owned batch row-wise
    through a Python callback; the Plan Doctor's ``sink.row-expanding``
    diagnostic names exactly that de-optimization).

    ``batch_format="arrow"`` (ISSUE 14) is the fully columnar egress:
    ``on_batch(time, batch)`` receives a ``pyarrow.RecordBatch`` whose
    schema is the table's columns (nullable), a ``diff`` int64 column
    (±1) and — unless ``include_key=False`` — a 16-byte ``_key`` column
    carrying the engine's row keys little-endian
    (``pathway_tpu.io._arrow.key_from_bytes`` converts back; counting/
    aggregating consumers that never touch keys should turn it off, it
    is the priciest column of the tuple-delta fallback leg). Columnar NativeBatch deliveries export ZERO-COPY through the
    Arrow C data interface — no Python row objects exist at the sink;
    tuple-delta deliveries (retractions, object columns, forced row
    path) are built column-wise on the Python side, with cells outside
    the Arrow scalar set pickled into binary columns tagged with
    ``pw_pickled`` field metadata (``unpickle_columns`` restores them)
    — so an Arrow-mode subscriber receives *every* delivery as a
    record batch. Requires pyarrow.

    ``batch_format="tuples"`` is the zero-transformation rows egress:
    ``on_batch(time, deltas)`` receives the engine's raw
    ``[(key, row_tuple, diff), ...]`` batch — row tuples in the table's
    column order, NO per-row dict building (the dict wrapper of the
    default ``"rows"`` format costs one dict per change; a counting or
    forwarding consumer pays it for nothing). The batch is a shared
    read-only view — consumers must not mutate it.

    ``with_envelope=True`` (ISSUE 12) changes the ``on_batch``
    signature to ``on_batch(envelope, changes)`` where ``envelope`` is
    a :class:`~pathway_tpu.io.txn.DeliveryEnvelope` ``(epoch,
    commit_ts, seq)`` — delivery metadata for the remaining
    at-least-once surface: ``commit_ts`` is the plain ``time`` of the
    unenveloped form (monotone across restarts), ``seq`` strictly
    monotone per subscription within one process incarnation, and an
    epoch bump or ``seq`` reset marks a redelivery window (see the
    ``DeliveryEnvelope`` docstring for the exact guarantees and what
    still needs consumer-side keys). Composes with either batch format.
    """
    if batch_format not in ("rows", "tuples", "arrow"):
        raise ValueError(
            f"batch_format must be 'rows', 'tuples' or 'arrow', "
            f"got {batch_format!r}"
        )
    if batch_format == "arrow":
        if on_batch is None:
            raise ValueError("batch_format='arrow' requires on_batch=")
        from pathway_tpu.io._arrow import get_pyarrow

        if get_pyarrow() is None:
            raise ValueError(
                "batch_format='arrow' requires pyarrow to be installed"
            )
    cols = tuple(table.column_names())

    def lower(ctx):
        batch_cb = None
        arrow_cb = None
        if on_batch is not None and batch_format == "arrow":
            # direct columnar delivery; the rows callback below is the
            # fallback leg for tuple-delta batches, converted column-
            # wise so the consumer STILL sees a record batch
            arrow_cb = on_batch

            def batch_cb(stamp, deltas):
                from pathway_tpu.io._arrow import deltas_to_arrow

                on_batch(
                    stamp,
                    deltas_to_arrow(deltas, cols, include_key=include_key),
                )

        elif on_batch is not None and batch_format == "tuples":
            # raw engine batch, zero per-row transformation — the
            # OutputNode's delivery is one callback + nothing else
            batch_cb = on_batch
        elif on_batch is not None:
            if with_envelope:

                def batch_cb(env, deltas):
                    on_batch(
                        env,
                        [
                            (k, dict(zip(cols, row)), d)
                            for k, row, d in deltas
                        ],
                    )

            else:

                def batch_cb(time, deltas):
                    on_batch(
                        time,
                        [
                            (k, dict(zip(cols, row)), d)
                            for k, row, d in deltas
                        ],
                    )

        # dict_cols pushes the row-dict building into the OutputNode's C
        # delivery loop instead of a per-change Python wrapper
        ctx.scope.output(
            ctx.engine_table(table),
            on_change=on_change,
            on_batch=batch_cb,
            on_batch_arrow=arrow_cb,
            arrow_cols=cols,
            arrow_key=include_key,
            on_time_end=on_time_end,
            on_end=on_end,
            dict_cols=cols if on_change is not None else None,
            envelope=with_envelope and on_batch is not None,
        )

    G.add_operator([table], [], lower, "subscribe", is_output=True)
