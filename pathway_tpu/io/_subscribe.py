"""pw.io.subscribe (reference: python/pathway/io/_subscribe.py:13)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def subscribe(
    table: Table,
    on_change: Callable | None = None,
    on_end: Callable | None = None,
    on_time_end: Callable | None = None,
    *,
    name: str | None = None,
    sort_by=None,
) -> None:
    """on_change(key, row: dict, time: int, is_addition: bool)."""
    cols = tuple(table.column_names())

    def lower(ctx):
        # dict_cols pushes the row-dict building into the OutputNode's C
        # delivery loop instead of a per-change Python wrapper
        ctx.scope.output(
            ctx.engine_table(table),
            on_change=on_change,
            on_time_end=on_time_end,
            on_end=on_end,
            dict_cols=cols if on_change is not None else None,
        )

    G.add_operator([table], [], lower, "subscribe", is_output=True)
