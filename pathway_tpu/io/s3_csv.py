"""pw.io.s3_csv — CSV-from-S3 convenience wrapper (reference:
python/pathway/io/s3_csv — delegates to the S3 scanner with csv format)."""

from __future__ import annotations

from pathway_tpu.io.s3 import AwsS3Settings, read as _s3_read

__all__ = ["AwsS3Settings", "read"]


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    schema=None,
    mode: str = "streaming",
    csv_settings=None,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs,
):
    return _s3_read(
        path,
        "csv",
        aws_s3_settings=aws_s3_settings,
        schema=schema,
        mode=mode,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name,
        **kwargs,
    )
