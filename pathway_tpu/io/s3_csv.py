"""pw.io.s3_csv — connector surface (reference: python/pathway/io/s3_csv).

Client transport gated on its library; the configuration surface matches
the reference so templates parse and fail only at run time with a clear
dependency error."""

from __future__ import annotations

from pathway_tpu.io._gated import require


def read(*args, schema=None, mode="streaming", autocommit_duration_ms=1500,
         name=None, **kwargs):
    require('boto3')
    raise NotImplementedError(
        "pw.io.s3_csv.read: client library found, but no s3_csv service "
        "transport is wired in this build"
    )


