"""pw.io.gcs — Google Cloud Storage connector (reference: the S3/MinIO
object-store scanners, src/connectors/scanner/s3.rs:268 + posix_like.rs:301
— object polling with metadata diffing and deletion detection; GCS is this
environment's installed object store, google-cloud-storage).

Streaming mode polls the bucket prefix; changed objects (by generation) are
re-emitted with retraction of their previous rows, deleted objects retract.
``client`` injects a fake/emulator in tests.
"""

from __future__ import annotations

import csv as _csv
import io as _io
import json as _json
import time
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import Json, ref_scalar
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema, schema_from_types
from pathway_tpu.io.python import ConnectorSubject, read as python_read


def _parse_bytes(data: bytes, fmt: str) -> list[dict]:
    rows: list[dict] = []
    if fmt in ("csv", "dsv"):
        for rec in _csv.DictReader(_io.StringIO(data.decode("utf-8", "replace"))):
            rows.append(dict(rec))
    elif fmt in ("json", "jsonlines"):
        for line in data.decode("utf-8", "replace").splitlines():
            line = line.strip()
            if line:
                rows.append(_json.loads(line))
    elif fmt == "plaintext":
        for line in data.decode("utf-8", "replace").splitlines():
            rows.append({"data": line})
    elif fmt in ("plaintext_by_object", "plaintext_by_file"):
        rows.append({"data": data.decode("utf-8", "replace")})
    elif fmt == "binary":
        rows.append({"data": data})
    else:
        raise ValueError(f"unknown format {fmt!r}")
    return rows


class _GcsSubject(ConnectorSubject):
    def __init__(self, bucket, prefix, fmt, with_metadata, mode,
                 refresh_interval=5.0, client=None):
        super().__init__()
        self.bucket_name = bucket
        self.prefix = prefix
        self.fmt = fmt
        self.with_metadata = with_metadata
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._client = client
        self._seen: dict[str, Any] = {}      # object -> generation
        self._emitted: dict[str, list] = {}  # object -> [(key, row)]
        self._stop = False

    def _gcs(self):
        if self._client is None:
            from google.cloud import storage

            self._client = storage.Client()
        return self._client

    def _scan_once(self):
        client = self._gcs()
        current = set()
        for blob in client.list_blobs(self.bucket_name, prefix=self.prefix):
            name = blob.name
            gen = getattr(blob, "generation", None) or getattr(
                blob, "updated", None
            )
            current.add(name)
            if self._seen.get(name) == gen:
                continue
            try:
                data = blob.download_as_bytes()
            except Exception:
                # object vanished between list and download: the next poll's
                # deletion path retracts it; don't kill the pipeline
                continue
            for old_key, old_row in self._emitted.pop(name, []):
                self._remove(old_key, old_row)
            rows = _parse_bytes(data, self.fmt)
            if self.with_metadata:
                meta = {
                    "path": f"gs://{self.bucket_name}/{name}",
                    "size": len(data),
                    "seen_at": int(time.time()),
                }
                for r in rows:
                    r["_metadata"] = Json(meta)
            keyed = [
                (ref_scalar("gcs", self.bucket_name, name, i), row)
                for i, row in enumerate(rows)
            ]
            for key, row in keyed:
                self._upsert(key, row)
            # bookkeeping after emission: flush snapshots stay consistent
            # (io/_connector.py commit-boundary protocol)
            self._emitted[name] = keyed
            self._seen[name] = gen
        for name in list(self._emitted):
            if name not in current:
                for old_key, old_row in self._emitted.pop(name, []):
                    self._remove(old_key, old_row)
                self._seen.pop(name, None)
        self.commit()

    def run(self):
        self._scan_once()
        if self.mode == "static":
            return
        while not self._stop:
            time.sleep(self.refresh_interval)
            self._scan_once()

    def on_stop(self):
        self._stop = True

    def snapshot_state(self):
        return {"seen": dict(self._seen), "emitted": dict(self._emitted)}

    def seek(self, state) -> None:
        self._seen = dict(state.get("seen", {}))
        self._emitted = dict(state.get("emitted", {}))


def read(
    bucket: str,
    prefix: str = "",
    *,
    format: str = "jsonlines",
    schema: type[Schema] | None = None,
    mode: str = "streaming",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 5.0,
    client=None,
    name: str | None = None,
    **kwargs,
):
    if schema is None:
        if format in ("plaintext", "plaintext_by_object", "plaintext_by_file"):
            cols: dict[str, Any] = {"data": dt.STR}
        elif format == "binary":
            cols = {"data": dt.BYTES}
        else:
            raise ValueError(
                "pw.io.gcs.read requires schema= for structured formats"
            )
        if with_metadata:
            cols["_metadata"] = dt.JSON
        schema = schema_from_types(**cols)
    subject = _GcsSubject(
        bucket, prefix, format, with_metadata, mode,
        refresh_interval=refresh_interval, client=client,
    )
    return python_read(
        subject,
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"gcs://{bucket}/{prefix}",
    )


def write(table, bucket: str, prefix: str, *, format: str = "jsonlines",
          client=None, name: str | None = None, **kwargs) -> None:
    """Streams output batches as sequential objects under `prefix`
    (reference: object-store writers emit one object per commit)."""
    cols = table.column_names()
    state = {"client": client, "seq": 0, "buf": []}

    def _client():
        if state["client"] is None:
            from google.cloud import storage

            state["client"] = storage.Client()
        return state["client"]

    def on_change(key, row, time_, diff):
        payload = dict(zip(cols, row))
        payload["time"] = time_
        payload["diff"] = diff
        state["buf"].append(_json.dumps(payload, default=str))

    def on_time_end(time_):
        if not state["buf"]:
            return
        data = ("\n".join(state["buf"]) + "\n").encode()
        state["buf"] = []
        blob = _client().bucket(bucket).blob(
            f"{prefix.rstrip('/')}/{state['seq']:08d}.jsonl"
        )
        state["seq"] += 1
        blob.upload_from_string(data)

    def on_end():
        on_time_end(None)

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table), on_change=on_change,
            on_time_end=on_time_end, on_end=on_end,
        )

    G.add_operator([table], [], lower, "gcs_write", is_output=True)
