"""pw.io.gcs — Google Cloud Storage connector (reference: the S3/MinIO
object-store scanners, src/connectors/scanner/s3.rs:268 + posix_like.rs:301
— object polling with metadata diffing and deletion detection; GCS is this
environment's installed object store, google-cloud-storage).

Streaming mode polls the bucket prefix; changed objects (by generation) are
re-emitted with retraction of their previous rows, deleted objects retract.
``client`` injects a fake/emulator in tests.
"""

from __future__ import annotations

import json as _json
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema, schema_from_types
from pathway_tpu.io._objstore import ObjectStoreSubject, parse_object_bytes
from pathway_tpu.io.python import read as python_read

# back-compat alias: s3.py historically imported the parser from here
_parse_bytes = parse_object_bytes


class _GcsSubject(ObjectStoreSubject):
    _scheme = "gcs"

    def __init__(self, bucket, prefix, fmt, with_metadata, mode,
                 refresh_interval=5.0, client=None):
        super().__init__(fmt, with_metadata, mode, refresh_interval)
        self.bucket_name = bucket
        self.prefix = prefix
        self._client = client

    def _gcs(self):
        if self._client is None:
            from google.cloud import storage

            self._client = storage.Client()
        return self._client

    def _list(self):
        for blob in self._gcs().list_blobs(
            self.bucket_name, prefix=self.prefix
        ):
            gen = getattr(blob, "generation", None) or getattr(
                blob, "updated", None
            )
            yield blob.name, gen, {}

    def _get(self, name: str) -> bytes:
        return self._gcs().bucket(self.bucket_name).blob(name).download_as_bytes()

    def _uri(self, name: str) -> str:
        return f"gs://{self.bucket_name}/{name}"


def read(
    bucket: str,
    prefix: str = "",
    *,
    format: str = "jsonlines",
    schema: type[Schema] | None = None,
    mode: str = "streaming",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 5.0,
    client=None,
    name: str | None = None,
    **kwargs,
):
    if schema is None:
        if format in ("plaintext", "plaintext_by_object", "plaintext_by_file"):
            cols: dict[str, Any] = {"data": dt.STR}
        elif format == "binary":
            cols = {"data": dt.BYTES}
        else:
            raise ValueError(
                "pw.io.gcs.read requires schema= for structured formats"
            )
        if with_metadata:
            cols["_metadata"] = dt.JSON
        schema = schema_from_types(**cols)
    subject = _GcsSubject(
        bucket, prefix, format, with_metadata, mode,
        refresh_interval=refresh_interval, client=client,
    )
    return python_read(
        subject,
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"gcs://{bucket}/{prefix}",
    )


def write(table, bucket: str, prefix: str, *, format: str = "jsonlines",
          client=None, name: str | None = None, **kwargs) -> None:
    """Streams output batches as sequential objects under `prefix`
    (reference: object-store writers emit one object per commit)."""
    cols = table.column_names()
    state = {"client": client, "seq": 0, "buf": []}

    def _client():
        if state["client"] is None:
            from google.cloud import storage

            state["client"] = storage.Client()
        return state["client"]

    def on_change(key, row, time_, diff):
        payload = dict(zip(cols, row))
        payload["time"] = time_
        payload["diff"] = diff
        state["buf"].append(_json.dumps(payload, default=str))

    def on_time_end(time_):
        if not state["buf"]:
            return
        data = ("\n".join(state["buf"]) + "\n").encode()
        state["buf"] = []
        blob = _client().bucket(bucket).blob(
            f"{prefix.rstrip('/')}/{state['seq']:08d}.jsonl"
        )
        state["seq"] += 1
        blob.upload_from_string(data)

    def on_end():
        on_time_end(None)

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table), on_change=on_change,
            on_time_end=on_time_end, on_end=on_end,
        )

    G.add_operator([table], [], lower, "gcs_write", is_output=True)
