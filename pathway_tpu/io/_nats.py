"""Minimal NATS client — dependency-free (raw TCP text protocol).

The reference's NATS reader/writer are native Rust over async-nats
(reference: src/connectors/data_storage.rs:2226 NatsReader / :2300
NatsWriter). This build speaks the NATS wire protocol directly — it is
a deliberately small, line-oriented protocol:

    server → INFO {...}            client → CONNECT {...}
    client → SUB <subject> <sid>   client → [H]PUB <subject> ...
    server → MSG/HMSG ...          both   → PING / PONG

HPUB carries the ``pathway_time`` / ``pathway_diff`` headers the
reference writer attaches to every message.
"""

from __future__ import annotations

import json
import socket
import urllib.parse


class NatsConnection:
    def __init__(self, uri: str, timeout: float = 10.0):
        parsed = urllib.parse.urlsplit(
            uri if "://" in uri else "nats://" + uri
        )
        host = parsed.hostname or "127.0.0.1"
        port = parsed.port or 4222
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        self._buf = b""
        line = self._read_line()  # INFO {...}
        if not line.startswith(b"INFO"):
            raise ConnectionError(f"not a NATS server: {line[:80]!r}")
        self.server_info = json.loads(line[4:].strip() or b"{}")
        connect = {
            "verbose": False,
            "pedantic": False,
            "lang": "python-pathway-tpu",
            "version": "1",
            "headers": True,
        }
        if parsed.username:
            connect["user"] = parsed.username
            connect["pass"] = parsed.password or ""
        self._send(b"CONNECT " + json.dumps(connect).encode() + b"\r\n")

    # -- io ---------------------------------------------------------------
    def _send(self, data: bytes) -> None:
        self.sock.sendall(data)

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise EOFError("NATS connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise EOFError("NATS connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    # -- protocol ----------------------------------------------------------
    def publish(
        self, subject: str, payload: bytes,
        headers: dict[str, str] | None = None,
    ) -> None:
        if headers:
            hdr = b"NATS/1.0\r\n" + b"".join(
                f"{k}: {v}\r\n".encode() for k, v in headers.items()
            ) + b"\r\n"
            total = len(hdr) + len(payload)
            self._send(
                f"HPUB {subject} {len(hdr)} {total}\r\n".encode()
                + hdr + payload + b"\r\n"
            )
        else:
            self._send(
                f"PUB {subject} {len(payload)}\r\n".encode()
                + payload + b"\r\n"
            )

    def subscribe(self, subject: str, sid: int = 1) -> None:
        self._send(f"SUB {subject} {sid}\r\n".encode())

    def next_msg(self, timeout: float | None = None):
        """Returns (subject, payload, headers) or None on timeout.
        Handles PING keepalives transparently.

        The poll timeout applies only to the FIRST line of a frame —
        returning None there is safe because no bytes were consumed.
        Once a MSG/HMSG header arrived, payload reads switch to a long
        deadline and a timeout mid-frame is a hard protocol error (the
        stream would be desynced if we returned)."""
        base_timeout = self.sock.gettimeout()
        try:
            while True:
                if timeout is not None:
                    self.sock.settimeout(timeout)
                try:
                    line = self._read_line()
                except (socket.timeout, TimeoutError):
                    return None
                self.sock.settimeout(30.0)  # committed to a frame now
                if line == b"PING":
                    self._send(b"PONG\r\n")
                    continue
                if line in (b"PONG", b"+OK") or not line:
                    continue
                if line.startswith(b"-ERR"):
                    raise ConnectionError(line.decode(errors="replace"))
                parts = line.split(b" ")

                def size_of(raw: bytes) -> int:
                    # malformed/corrupt size fields must fail cleanly —
                    # a negative or absurd size would silently desync
                    # the stream (max NATS payload is 64MB)
                    try:
                        n = int(raw)
                    except ValueError:
                        raise ConnectionError(
                            f"malformed NATS size field {raw[:40]!r}"
                        ) from None
                    if n < 0 or n > 64 * 1024 * 1024:
                        raise ConnectionError(
                            f"malformed NATS frame size {n}"
                        )
                    return n

                try:
                    if parts[0] == b"MSG":
                        # MSG <subject> <sid> [reply-to] <#bytes>
                        nbytes = size_of(parts[-1])
                        payload = self._read_exact(nbytes)
                        self._read_exact(2)  # trailing \r\n
                        return parts[1].decode(), payload, {}
                    if parts[0] == b"HMSG":
                        # HMSG <subject> <sid> [reply-to] <hdr_len> <total>
                        hdr_len = size_of(parts[-2])
                        total = size_of(parts[-1])
                        if hdr_len > total:
                            raise ConnectionError(
                                "malformed NATS HMSG: hdr_len > total"
                            )
                        blob = self._read_exact(total)
                        self._read_exact(2)
                        headers = {}
                        for h in blob[:hdr_len].split(b"\r\n")[1:]:
                            if b":" in h:
                                k, _, v = h.partition(b":")
                                headers[k.decode().strip()] = v.decode().strip()
                        return parts[1].decode(), blob[hdr_len:], headers
                except (socket.timeout, TimeoutError) as e:
                    raise ConnectionError(
                        "NATS stream desync: timed out mid-frame"
                    ) from e
                raise ConnectionError(
                    f"unexpected NATS frame: {line[:80]!r}"
                )
        finally:
            # Restore the pre-call timeout unconditionally: both the poll
            # timeout and the mid-frame settimeout(30.0) would otherwise
            # leak into later publish/flush calls (and settimeout(None)
            # would leave a hung broker stalling the pipeline forever).
            self.sock.settimeout(base_timeout)

    def flush(self) -> None:
        self._send(b"PING\r\n")

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
