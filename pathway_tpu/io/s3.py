"""pw.io.s3 — Amazon S3 / S3-compatible object-store connector
(reference: python/pathway/io/s3 over the native scanner
src/connectors/scanner/s3.rs:268).

Redesigned transport: no boto3 — a dependency-free SigV4 REST client
(`pathway_tpu/io/_s3.py`) drives the same object-polling scanner the GCS
connector uses (metadata diffing by ETag, deletion detection,
retraction-correct re-reads). DigitalOcean Spaces and Wasabi are the
same protocol with preset endpoints (reference: io/s3/__init__.py:304,
:435).
"""

from __future__ import annotations

import json as _json
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema, schema_from_types
from pathway_tpu.io._objstore import ObjectStoreSubject
from pathway_tpu.io._s3 import AwsS3Settings, S3Client
from pathway_tpu.io.python import read as python_read

__all__ = [
    "AwsS3Settings",
    "read",
    "write",
    "read_from_digital_ocean",
    "read_from_wasabi",
]


def _split_path(path: str) -> tuple[str | None, str]:
    """s3://bucket/prefix -> (bucket, prefix); bare prefix -> (None, path)."""
    if path.startswith("s3://"):
        rest = path.removeprefix("s3://")
        bucket, _, prefix = rest.partition("/")
        return bucket, prefix
    return None, path


class _S3Subject(ObjectStoreSubject):
    _scheme = "s3"

    def __init__(self, client: S3Client, bucket, prefix, fmt, with_metadata,
                 mode, refresh_interval=5.0):
        super().__init__(fmt, with_metadata, mode, refresh_interval)
        self.client = client
        self.bucket_name = bucket
        self.prefix = prefix

    def _list(self):
        # modification-time order, matching the reference scanner's
        # "smaller modification time first" contract (io/s3:112)
        objs = sorted(
            self.client.list_objects(self.prefix),
            key=lambda o: o.last_modified,
        )
        for obj in objs:
            extras = {"modified_at": obj.last_modified}
            if obj.owner:
                extras["owner"] = obj.owner
            yield obj.key, (obj.etag, obj.last_modified), extras

    def _get(self, name: str) -> bytes:
        return self.client.get_object(name)

    def _uri(self, name: str) -> str:
        return f"s3://{self.bucket_name}/{name}"


def _default_schema(format: str, with_metadata: bool):
    if format in ("plaintext", "plaintext_by_object", "plaintext_by_file"):
        cols: dict[str, Any] = {"data": dt.STR}
    elif format == "binary":
        cols = {"data": dt.BYTES}
    else:
        raise ValueError("pw.io.s3.read requires schema= for structured formats")
    if with_metadata:
        cols["_metadata"] = dt.JSON
    return schema_from_types(**cols)


def read(
    path: str,
    format: str = "jsonlines",
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    schema: type[Schema] | None = None,
    mode: str = "streaming",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 5.0,
    name: str | None = None,
    _opener=None,
    **kwargs,
):
    """Read a table from object(s) under an S3 path prefix (reference:
    io/s3/__init__.py:94 — csv/json/jsonlines/plaintext/
    plaintext_by_object/binary formats, streaming object polling)."""
    bucket, prefix = _split_path(path)
    # path-derived bucket wins; the caller's settings are copied, never
    # mutated, so one settings object is reusable across buckets
    settings = (aws_s3_settings or AwsS3Settings()).with_bucket(bucket)
    client = S3Client(settings, opener=_opener)
    if schema is None:
        schema = _default_schema(format, with_metadata)
    subject = _S3Subject(
        client, settings.bucket_name, prefix, format, with_metadata, mode,
        refresh_interval=refresh_interval,
    )
    return python_read(
        subject,
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"s3://{settings.bucket_name}/{prefix}",
    )


def _preset_endpoint(settings: AwsS3Settings, template: str, provider: str):
    if settings.endpoint is not None:
        return settings
    if not settings.region_explicit:
        raise ValueError(
            f"{provider} settings need an explicit region= (e.g. "
            f"{'nyc3' if 'digitalocean' in template else 'us-west-1'}) "
            "to derive the endpoint"
        )
    out = settings.with_bucket(None)
    out.endpoint = template.format(region=settings.region)
    return out


def read_from_digital_ocean(
    path: str,
    do_s3_settings: AwsS3Settings,
    format: str = "jsonlines",
    **kwargs,
):
    """DigitalOcean Spaces: same REST protocol, Spaces endpoint
    (reference: io/s3/__init__.py:304)."""
    settings = _preset_endpoint(
        do_s3_settings,
        "https://{region}.digitaloceanspaces.com",
        "DigitalOcean Spaces",
    )
    return read(path, format, aws_s3_settings=settings, **kwargs)


def read_from_wasabi(
    path: str,
    wasabi_s3_settings: AwsS3Settings,
    format: str = "jsonlines",
    **kwargs,
):
    """Wasabi: same REST protocol, Wasabi endpoint (reference:
    io/s3/__init__.py:435)."""
    settings = _preset_endpoint(
        wasabi_s3_settings,
        "https://s3.{region}.wasabisys.com",
        "Wasabi",
    )
    return read(path, format, aws_s3_settings=settings, **kwargs)


def write(
    table,
    path: str,
    *,
    format: str = "jsonlines",
    aws_s3_settings: AwsS3Settings | None = None,
    name: str | None = None,
    _opener=None,
    **kwargs,
) -> None:
    """Stream output batches as sequential objects under the prefix (one
    object per non-empty commit, like the object-store writers)."""
    bucket, prefix = _split_path(path)
    settings = (aws_s3_settings or AwsS3Settings()).with_bucket(bucket)
    client = S3Client(settings, opener=_opener)
    cols = table.column_names()
    state = {"seq": 0, "buf": []}

    def on_change(key, row, time_, diff):
        payload = dict(zip(cols, row))
        payload["time"] = time_
        payload["diff"] = diff
        state["buf"].append(_json.dumps(payload, default=str))

    def on_time_end(time_):
        if not state["buf"]:
            return
        data = ("\n".join(state["buf"]) + "\n").encode()
        state["buf"] = []
        client.put_object(
            f"{prefix.rstrip('/')}/{state['seq']:08d}.jsonl", data
        )
        state["seq"] += 1

    def on_end():
        on_time_end(None)

    def lower(ctx):
        ctx.scope.output(
            ctx.engine_table(table), on_change=on_change,
            on_time_end=on_time_end, on_end=on_end,
        )

    G.add_operator([table], [], lower, "s3_write", is_output=True)
