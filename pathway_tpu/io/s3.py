"""pw.io.s3 — connector surface (reference: python/pathway/io/s3 (native S3 scanner scanner/s3.rs:268)).

Client transport gated on its library; the configuration surface matches
the reference so templates parse and fail only at run time with a clear
dependency error."""

from __future__ import annotations

from pathway_tpu.io._gated import require


def read(*args, schema=None, mode="streaming", autocommit_duration_ms=1500,
         name=None, **kwargs):
    require('boto3')
    raise NotImplementedError(
        "pw.io.s3.read: client library found, but no s3 service "
        "transport is wired in this build"
    )


