"""Minimal MongoDB wire-protocol client — dependency-free (OP_MSG).

The reference's MongoWriter drives the mongodb crate (reference:
src/connectors/data_storage.rs MongoWriter; BSON payloads from
data_format.rs:1982). This build speaks OP_MSG (opcode 2013, the only
opcode modern MongoDB requires) directly: one section-0 command document
per request, BSON-encoded by the same hand-rolled encoder the Bson
formatter uses (io/_formats.py bson_document), plus a small BSON decoder
for command replies.
"""

from __future__ import annotations

import socket
import struct

from pathway_tpu.io._formats import bson_document

OP_MSG = 2013


def bson_decode(data: bytes, offset: int = 0) -> dict:
    """Decode one BSON document (subset: the types server replies use)."""
    (length,) = struct.unpack_from("<i", data, offset)
    end = offset + length - 1
    pos = offset + 4
    out: dict = {}
    while pos < end:
        etype = data[pos]
        pos += 1
        nend = data.index(b"\x00", pos)
        name = data[pos:nend].decode()
        pos = nend + 1
        if etype == 0x01:  # double
            (out[name],) = struct.unpack_from("<d", data, pos)
            pos += 8
        elif etype == 0x02:  # string
            (slen,) = struct.unpack_from("<i", data, pos)
            out[name] = data[pos + 4 : pos + 3 + slen].decode()
            pos += 4 + slen
        elif etype in (0x03, 0x04):  # document / array
            (dlen,) = struct.unpack_from("<i", data, pos)
            sub = bson_decode(data, pos)
            out[name] = (
                [sub[k] for k in sorted(sub, key=int)] if etype == 0x04 else sub
            )
            pos += dlen
        elif etype == 0x05:  # binary
            (blen,) = struct.unpack_from("<i", data, pos)
            out[name] = data[pos + 5 : pos + 5 + blen]
            pos += 5 + blen
        elif etype == 0x08:  # bool
            out[name] = data[pos] == 1
            pos += 1
        elif etype == 0x09:  # datetime (ms)
            (out[name],) = struct.unpack_from("<q", data, pos)
            pos += 8
        elif etype == 0x0A:  # null
            out[name] = None
        elif etype == 0x10:  # int32
            (out[name],) = struct.unpack_from("<i", data, pos)
            pos += 4
        elif etype == 0x12:  # int64
            (out[name],) = struct.unpack_from("<q", data, pos)
            pos += 8
        else:
            raise ValueError(f"unsupported BSON type 0x{etype:02x} in reply")
    return out


class MongoConnection:
    def __init__(self, connection_string: str, timeout: float = 30.0):
        import urllib.parse

        parsed = urllib.parse.urlsplit(
            connection_string
            if "://" in connection_string
            else "mongodb://" + connection_string
        )
        host = parsed.hostname or "127.0.0.1"
        port = parsed.port or 27017
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = b""
        self._req_id = 0
        if parsed.username:
            query = urllib.parse.parse_qs(parsed.query)
            auth_db = query.get("authSource", ["admin"])[0]
            self._scram_auth(
                urllib.parse.unquote(parsed.username),
                urllib.parse.unquote(parsed.password or ""),
                auth_db,
            )

    def _scram_auth(self, user: str, password: str, auth_db: str) -> None:
        """SCRAM-SHA-256 (RFC 7677) over saslStart/saslContinue — the
        default MongoDB mechanism the reference's driver negotiates."""
        import base64
        import hashlib
        import hmac
        import os

        nonce = base64.b64encode(os.urandom(18)).decode()
        user_esc = user.replace("=", "=3D").replace(",", "=2C")
        first_bare = f"n={user_esc},r={nonce}"
        reply = self.command(
            {
                "saslStart": 1,
                "mechanism": "SCRAM-SHA-256",
                "payload": b"n,," + first_bare.encode(),
                "$db": auth_db,
            }
        )
        server_first = reply["payload"].decode()
        fields = dict(kv.split("=", 1) for kv in server_first.split(","))
        if not fields["r"].startswith(nonce):
            raise ConnectionError("mongodb SCRAM: server nonce mismatch")
        salt = base64.b64decode(fields["s"])
        iterations = int(fields["i"])
        salted = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), salt, iterations
        )
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        without_proof = f"c=biws,r={fields['r']}"
        auth_message = (
            f"{first_bare},{server_first},{without_proof}".encode()
        )
        client_sig = hmac.new(stored_key, auth_message, hashlib.sha256).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
        client_final = (
            f"{without_proof},p={base64.b64encode(proof).decode()}"
        )
        reply = self.command(
            {
                "saslContinue": 1,
                "conversationId": reply.get("conversationId", 1),
                "payload": client_final.encode(),
                "$db": auth_db,
            }
        )
        server_final = dict(
            kv.split("=", 1) for kv in reply["payload"].decode().split(",")
        )
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        expect = hmac.new(server_key, auth_message, hashlib.sha256).digest()
        import base64 as _b64

        if _b64.b64decode(server_final.get("v", "")) != expect:
            raise ConnectionError(
                "mongodb SCRAM: server signature verification failed"
            )
        while not reply.get("done", True):
            reply = self.command(
                {
                    "saslContinue": 1,
                    "conversationId": reply.get("conversationId", 1),
                    "payload": b"",
                    "$db": auth_db,
                }
            )

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise EOFError("mongodb connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def command(self, doc: dict) -> dict:
        """Send one OP_MSG command document, return the reply document."""
        self._req_id += 1
        body = struct.pack("<i", 0) + b"\x00" + bson_document(doc)
        header = struct.pack(
            "<iiii", 16 + len(body), self._req_id, 0, OP_MSG
        )
        self.sock.sendall(header + body)
        (length, _rid, _rto, opcode) = struct.unpack(
            "<iiii", self._read_exact(16)
        )
        # 48MB is MongoDB's own max message size; 21 = header + flagBits +
        # section byte + minimal document. Anything outside is a corrupt
        # or non-mongo stream — fail cleanly instead of desyncing.
        if length < 21 or length > 48 * 1024 * 1024:
            raise ConnectionError(
                f"malformed mongodb frame: length={length} "
                "(stream corrupt or not a mongodb server)"
            )
        payload = self._read_exact(length - 16)
        if opcode != OP_MSG:
            raise ConnectionError(f"unexpected mongodb opcode {opcode}")
        # flagBits (4) + section kind byte, then the reply document
        reply = bson_decode(payload, 5)
        if not reply.get("ok"):
            raise RuntimeError(f"mongodb command failed: {reply}")
        # MongoDB reports per-document rejections (schema validation,
        # duplicate key, oversize doc) alongside ok:1 — treating those as
        # success silently drops rows from the sink.
        if reply.get("writeErrors"):
            raise RuntimeError(
                "mongodb bulk write failed for "
                f"{len(reply['writeErrors'])} document(s): "
                f"{reply['writeErrors']}"
            )
        if reply.get("writeConcernError"):
            raise RuntimeError(
                "mongodb write concern not satisfied: "
                f"{reply['writeConcernError']}"
            )
        return reply

    def insert_many(self, database: str, collection: str, docs: list[dict]):
        return self.command(
            {
                "insert": collection,
                "$db": database,
                "ordered": True,
                "documents": docs,
            }
        )

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
