"""pw.io.csv (reference: python/pathway/io/csv) — thin wrapper over fs."""

from __future__ import annotations

from pathway_tpu.io import fs


def read(path, *, schema=None, mode="streaming", **kwargs):
    return fs.read(path, format="csv", schema=schema, mode=mode, **kwargs)


def write(table, filename, **kwargs):
    return fs.write(table, filename, format="csv", **kwargs)
