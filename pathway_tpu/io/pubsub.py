"""pw.io.pubsub — connector surface (reference: python/pathway/io/pubsub).

Client transport gated on its library; the configuration surface matches
the reference so templates parse and fail only at run time with a clear
dependency error."""

from __future__ import annotations

from pathway_tpu.io._gated import require


def write(table, *args, name=None, **kwargs):
    require('google.cloud.pubsub_v1')
    raise NotImplementedError(
        "pw.io.pubsub.write: client library found, but no pubsub service "
        "transport is wired in this build"
    )
